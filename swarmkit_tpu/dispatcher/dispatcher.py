"""Dispatcher: the manager side of the worker protocol.

Behavioral re-derivation of manager/dispatcher/dispatcher.go: node
registration issuing session ids, heartbeat liveness (period 5s, grace ×3 —
dispatcher.go:28-53), assignment streaming (initial COMPLETE snapshot then
INCREMENTAL diffs batched every 100ms — :1013-1207), task status write-back
batching (:726-886), and down-node handling (mark DOWN → orchestrators
reschedule; ORPHANED after 24h).

Transport: sessions expose a watch `Channel` of assignment messages — the
in-process equivalent of the Dispatcher.Assignments gRPC stream; the wire
layer (swarmkit_tpu.rpc) carries the same messages across processes.
"""
from __future__ import annotations

import logging
import os
import random
import threading
import time
from collections import deque
from collections.abc import Set as _AbstractSet
from dataclasses import dataclass, field

from ..analysis.lockgraph import make_lock, make_rlock
from ..api.objects import (
    Config,
    EventCommit,
    EventCreate,
    EventDelete,
    EventUpdate,
    Node,
    Secret,
    Task,
)
from ..api.types import NodeStatusState, TaskState
from ..store import by
from ..store.memory import MemoryStore
from ..store.watch import Channel, WatchQueue
from ..utils import failpoints, lifecycle, telemetry, trace
from ..utils.identity import new_id
from ..utils.metrics import (
    CounterDict,
    histogram,
    snapshot_series_count,
    snapshot_within_budget,
)
from . import columnar_diff
from .heartbeat import Heartbeat, ShardedHeartbeatWheel, stable_shard

log = logging.getLogger("swarmkit_tpu.dispatcher")

_scheduling_delay = histogram(
    "swarm_dispatcher_scheduling_delay_seconds",
    "task creation → observed RUNNING")

DEFAULT_HEARTBEAT_PERIOD = 5.0       # reference: dispatcher.go:28-53
HEARTBEAT_EPSILON = 0.5
GRACE_MULTIPLIER = 3
RATE_LIMIT_PERIOD = 8.0              # dispatcher.go:34
RATE_LIMIT_COUNT = 3                 # nodes.go:14 — registrations per period
BATCH_INTERVAL = 0.1                 # assignment/status batching, 100ms
MAX_BATCH_ITEMS = 10000
# Slow-subscriber bound on the per-session assignments stream (the
# reference's LimitQueue idea): an agent that stops draining — or, since
# ISSUE 13, one whose stream moved to a follower read plane while its
# leader-forwarded heartbeats keep the leader session alive — must shed
# (Channel closes at the limit; the delivery gate leaves known-state
# untouched and a reconnect rebuilds from a COMPLETE) instead of growing
# the leader's queue without bound.
ASSIGNMENTS_CHANNEL_LIMIT = 4096
DEFAULT_NODE_DOWN_PERIOD = 24 * 3600.0  # dispatcher.go:48-52 → ORPHANED


def default_shard_count() -> int:
    """Flush-plane shard count when the operator didn't choose one:
    min(4, cores), floored at 1 (ISSUE 13). Overridable with
    SWARMKIT_TPU_DISPATCHER_SHARDS (the swarmd --dispatcher-shards
    plumbing rides the explicit constructor arg instead)."""
    env = os.environ.get("SWARMKIT_TPU_DISPATCHER_SHARDS", "")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            log.warning("ignoring bad SWARMKIT_TPU_DISPATCHER_SHARDS=%r",
                        env)
    return max(1, min(4, os.cpu_count() or 1))


@dataclass
class _Shard:
    """One slice of the fan-out plane: the shard owns its dirty set (its
    lock is a leaf — NEVER acquire `dispatcher.lock` while holding it;
    the global→shard order is the one the lockgraph tier pins) and its
    heartbeat-jitter RNG stream. The session→shard assignment is
    `stable_shard(node_id, P)` — identical to the heartbeat wheel's
    slice assignment, so a shard's sessions, dirt, and liveness ride
    together."""

    index: int
    lock: object
    dirty: set = field(default_factory=set)
    # ISSUE 16: the HARD subset of `dirty` — nodes whose dirt came from
    # a cause the columnar diff gate cannot see (volume events, external
    # test/operator marks, crash re-dirty). A hard-dirty session always
    # takes the dict-diff path; soft dirt (task/secret/config events) is
    # gate-eligible. Owned like `dirty`, under the same leaf lock.
    hard: set = field(default_factory=set)
    # ISSUE 16 per-shard event pump: dirty marks append here LOCK-FREE
    # (deque appends are atomic under the GIL) and apply to dirty/hard
    # in FIFO order at drain time — ONE shard-lock hold per drain
    # instead of one per event. Every reader of dirty/hard drains
    # first (`Dispatcher._drain_pumps`), so the observable sets are
    # identical to immediate marking (event-order parity).
    pending: deque = field(default_factory=deque)
    rng: random.Random = field(default_factory=random.Random)
    # ISSUE 15: latest telemetry report per node —
    # node id -> (snapshot dict, monotonic clock stamp). Owned by the
    # shard like its dirty set (same leaf lock); the manager aggregator
    # reads per-shard copies and merges the partials.
    reports: dict = field(default_factory=dict)


class _DirtyView(_AbstractSet):
    """Read/write facade presenting the per-shard dirty sets as ONE set
    (`Dispatcher._dirty_nodes` kept its pre-sharding surface: tests and
    operators read and occasionally clear it). Mutators route to the
    owning shard under its lock; set-algebra comes from the Set ABC over
    a per-call snapshot."""

    __slots__ = ("_disp",)

    def __init__(self, disp: "Dispatcher"):
        self._disp = disp

    @classmethod
    def _from_iterable(cls, it):
        return set(it)

    def _snapshot(self) -> set:
        self._disp._drain_pumps()
        out: set = set()
        for sh in self._disp._shards:
            with sh.lock:
                out |= sh.dirty
        return out

    def __contains__(self, key) -> bool:
        self._disp._drain_pumps()
        sh = self._disp._shard_for(key)
        with sh.lock:
            return key in sh.dirty

    def __iter__(self):
        return iter(self._snapshot())

    def __len__(self) -> int:
        self._disp._drain_pumps()
        return sum(len(self._snapshot_shard(sh))
                   for sh in self._disp._shards)

    @staticmethod
    def _snapshot_shard(sh: _Shard) -> set:
        with sh.lock:
            return set(sh.dirty)

    def __repr__(self):
        return f"_DirtyView({self._snapshot()!r})"

    def add(self, key) -> None:
        self._disp._mark_dirty(key)

    def update(self, keys) -> None:
        self._disp._mark_dirty_many(keys)

    def discard(self, key) -> None:
        # drain first: a pending pump op for `key` applied later would
        # resurrect what this discard removed (single-pump parity)
        self._disp._drain_pumps()
        sh = self._disp._shard_for(key)
        with sh.lock:
            sh.dirty.discard(key)
            sh.hard.discard(key)

    def clear(self) -> None:
        self._disp._drain_pumps()
        for sh in self._disp._shards:
            with sh.lock:
                sh.dirty.clear()
                sh.hard.clear()


class DispatcherError(Exception):
    pass


class SessionInvalid(DispatcherError):
    pass


@dataclass
class Assignment:
    """One element of an assignment message: a task/secret/config the node
    must run or may drop (reference api/dispatcher.proto Assignment)."""

    action: str   # "update" | "remove"
    kind: str     # "task" | "secret" | "config" | "volume"
    item: object


@dataclass
class AssignmentsMessage:
    type: str     # "complete" | "incremental"
    app_sequence: int
    changes: list[Assignment] = field(default_factory=list)


@dataclass
class SessionMessage:
    """The Session stream payload (api/dispatcher.proto SessionMessage:
    manager list for reconnect failover, the cluster root CA so agents
    track rotations, network bootstrap keys, and the node object's current
    role/availability so role changes reach the node without polling)."""

    managers: list = field(default_factory=list)     # [(node_id, addr)]
    root_ca_pem: bytes = b""
    network_keys: list = field(default_factory=list)
    node_role: int | None = None                     # observed cert role
    desired_role: int | None = None                  # spec.desired_role


@dataclass
class Session:
    node_id: str
    session_id: str
    channel: Channel
    sequence: int = 0
    known_tasks: dict[str, int] = field(default_factory=dict)  # id -> version
    # id -> version: an UPDATED secret/config (e.g. rotated credential or a
    # re-materialized driver payload) must re-ship incrementally, so the
    # diff compares versions, not mere id presence
    known_secrets: dict[str, int] = field(default_factory=dict)
    known_configs: dict[str, int] = field(default_factory=dict)
    known_volumes: set[str] = field(default_factory=set)
    # secret key -> base id AS RECORDED WHEN SHIPPED: removal-side
    # reverse-map cleanup must not depend on the global _clone_bases
    # entry still existing (another session retiring the same clone —
    # task moved nodes — pops it eagerly)
    known_bases: dict[str, str] = field(default_factory=dict)
    session_channel: Channel | None = None
    last_session_msg: SessionMessage | None = None
    # legacy Dispatcher.Tasks stream (pre-Assignments wire surface)
    tasks_channel: Channel | None = None


class RateLimitExceeded(DispatcherError):
    pass


class Dispatcher:
    # lifecycle SHIPPED is recorded where delivery is authoritative —
    # the leader's commit closures; the follower read plane (which
    # borrows _diff) overrides this to False so a follower-served diff
    # never double-stamps the SLO leg (docs/dispatcher.md)
    _record_shipped = True
    # columnar diff gate (ISSUE 16): per-shard plan stores, or None
    # when the plane is off. Class default None so the borrowed helpers
    # (_commit_known/_drop_session_refs) no-op on the follower plane,
    # which never defines it.
    _diffcols = None

    def __init__(self, store: MemoryStore,
                 heartbeat_period: float = DEFAULT_HEARTBEAT_PERIOD,
                 node_down_period: float = DEFAULT_NODE_DOWN_PERIOD,
                 rate_limit_period: float = RATE_LIMIT_PERIOD,
                 secret_drivers=None, clock=None,
                 shards: int | None = None, jitter_seed=None):
        from ..utils.clock import REAL_CLOCK

        self.store = store
        self.secret_drivers = secret_drivers  # DriverRegistry | None
        self.clock = clock or REAL_CLOCK
        self.heartbeat_period = heartbeat_period
        self.node_down_period = node_down_period
        self.rate_limit_period = rate_limit_period
        self._sessions: dict[str, Session] = {}
        # --- sharded fan-out plane (ISSUE 13): sessions partition into
        # P shards by stable_shard(node_id); each shard owns its dirty
        # set (leaf lock), its heartbeat-wheel slice, and its jitter RNG
        # stream. shards=None -> min(4, cores) (or the env override).
        if shards is None:
            shards = default_shard_count()
        self.shards = max(1, int(shards))
        seed_rng = random.Random(jitter_seed)
        self._shards: list[_Shard] = [
            _Shard(index=i,
                   lock=make_lock(f"dispatcher.shard{i}.lock"),
                   rng=random.Random(seed_rng.getrandbits(64)))
            for i in range(self.shards)]
        self._dirty_view = _DirtyView(self)
        # lazy ThreadPoolExecutor serving multi-shard flushes; None
        # while single-shard (or before the first parallel flush)
        self._pool = None
        # session liveness rides coarse-bucketed wheels, one slice per
        # shard (beat() is a dict write); the rare timers (leadership
        # grace, orphaning) keep per-event Heartbeat objects
        self._hb_wheel = ShardedHeartbeatWheel(
            granularity=self._wheel_granularity(heartbeat_period),
            clock=self.clock, shards=self.shards)
        self._lock = make_rlock('dispatcher.lock')
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # (task_id, status, reporting node_id)
        self._status_queue: list[tuple[str, object, str]] = []
        self._status_cond = threading.Condition(
            make_rlock("dispatcher.status_cond"))
        self._unknown_timers: dict[str, Heartbeat] = {}
        # node id -> (attempts, window start) for registration rate limiting
        self._reg_attempts: dict[str, tuple[int, float]] = {}
        # down-node timers driving the 24h → ORPHANED transition
        self._orphan_timers: dict[str, Heartbeat] = {}
        self._session_plane_dirty = False
        # (secret id, secret version, task id) -> materialized clone
        self._driver_cache: dict[tuple, object] = {}
        # driver-clone id -> base secret id (clone ids are opaque to the
        # known-secret diffing; the reverse reference maps key by base)
        self._clone_bases: dict[str, str] = {}
        # --- fan-out plane reverse indexes (assignments.go's reference
        # sets): built incrementally from the event stream, consulted by
        # _note_event and the flush instead of per-node table scans.
        # node id -> volume ids with a PENDING_NODE_UNPUBLISH status for
        # that node (forward map alongside for O(changed) maintenance).
        # Values are FROZENSETS replaced wholesale: _pending_unpublish
        # reads them INSIDE store-view callbacks, where taking the
        # dispatcher lock would invert the RPC paths' dispatcher→store
        # lock order (assignments() holds self._lock across store.view)
        self._vol_pending_unpub: dict[str, frozenset] = {}
        self._unpub_nodes_by_vol: dict[str, frozenset] = {}
        self._vol_index_primed = False
        # secret/config id -> node ids whose session was SHIPPED it
        self._secret_refs: dict[str, set[str]] = {}
        self._config_refs: dict[str, set[str]] = {}
        # counters the op-count regression guard and bench storm
        # sub-rows read. flushes/flush_tx/dirty_walks/last_flush_s are
        # flush-thread-only (plain item writes); ships/wire_copies may
        # be bumped from shard workers and RPC threads and go through
        # _bump → CounterDict.inc (the metric primitives' internal-lock
        # contract, ISSUE 15 — `+=` on a dict value is not atomic
        # across threads)
        self.metrics = CounterDict(
            {"flushes": 0, "flush_tx": 0, "wire_copies": 0,
             "ships": 0, "dirty_walks": 0, "last_flush_s": 0.0,
             # ISSUE 16 columnar diff gate: known-state entries the
             # vectorized pass compared, sessions it proved zero-delta
             # (skipped before any dict walk), and sessions that DID
             # take the dict `_diff` (the zero-dict-walk guard's key)
             "diff_rows_scanned": 0, "zero_delta_skips": 0,
             "dict_diffs": 0,
             # ISSUE 16 per-shard event pumps: total ops drained, plus
             # one backlog-at-drain gauge per shard (set below)
             "pump_events": 0})
        for i in range(self.shards):
            self.metrics[f"pump_depth_shard{i}"] = 0
        # --- columnar diff gate (ISSUE 16): per-shard plan stores in
        # delivery-commit lockstep with the known_* dicts. None when
        # the store carries no columnar mirror or the operator reverted
        # with SWARMKIT_TPU_NO_COLUMNAR_DIFF=1 — every session then
        # takes the dict path, exactly the pre-16 plane.
        if columnar_diff.plane_enabled() \
                and getattr(store, "columnar", None) is not None:
            self._diffcols = [columnar_diff.ShardDiffColumns(i)
                              for i in range(self.shards)]

    # ------------------------------------------------------------- lifecycle
    @staticmethod
    def _wheel_granularity(period: float) -> float:
        """Wheel tick width: ≤ ε so wheel lateness stays inside the
        heartbeat epsilon's design slack, and ≤ period/2 so tiny test
        periods still get several ticks inside their grace window."""
        return min(HEARTBEAT_EPSILON, max(period / 2.0, 0.01))

    # --------------------------------------------------------- shard plane
    def _shard_for(self, node_id: str) -> _Shard:
        return self._shards[stable_shard(node_id, self.shards)]

    def _mark_dirty(self, node_id: str, hard: bool = True) -> None:
        """Route a dirty node to its shard's event pump: ONE lock-free
        deque append per mark, applied to the dirty/hard sets FIFO at
        the next drain (ISSUE 16 — one shard-lock hold per drain
        replaces one per event; every dirty-set reader drains first, so
        visibility is unchanged). `hard` defaults True (dict-diff always
        serves it); ONLY the event plane's task/secret/config marks pass
        False — those are the causes the columnar gate provably sees
        (ISSUE 16), so any un-audited caller stays on the safe path."""
        self._shard_for(node_id).pending.append((node_id, hard))

    def _drain_pumps(self) -> None:
        """Apply every shard's pending pump ops under ONE shard-lock
        hold each. Must run before ANY read of a shard's dirty/hard
        sets (the flush top, every _DirtyView read/mutate) — drained,
        the sets are exactly what immediate marking would have built
        (ops apply in append order; set adds commute and are
        idempotent, so per-shard FIFO is event-order parity)."""
        drained = 0
        for sh in self._shards:
            if not sh.pending:
                continue
            with sh.lock:
                depth = 0
                while True:
                    try:
                        node_id, hard = sh.pending.popleft()
                    except IndexError:
                        break
                    sh.dirty.add(node_id)
                    if hard:
                        sh.hard.add(node_id)
                    depth += 1
                # gauge: backlog this drain retired (sampled pre-apply
                # depth; concurrent appends land in the next drain)
                self.metrics[f"pump_depth_shard{sh.index}"] = depth
                drained += depth
        if drained:
            self._bump("pump_events", drained)

    def _mark_dirty_many(self, node_ids, hard: bool = True) -> None:
        if self.shards == 1:
            # deque.extend of a LIST is one C-level op (no Python
            # callbacks interleave); materialize first so a generator
            # argument can't re-enter mid-extend
            self._shards[0].pending.extend(
                [(nid, hard) for nid in node_ids])
            return
        by_shard: dict[int, list] = {}
        for nid in node_ids:
            by_shard.setdefault(stable_shard(nid, self.shards),
                                []).append((nid, hard))
        for idx, ops in by_shard.items():
            self._shards[idx].pending.extend(ops)

    @property
    def _dirty_nodes(self) -> _DirtyView:
        """The union of the per-shard dirty sets, as a set-like view
        (pre-sharding surface: tests/operators read and clear it)."""
        return self._dirty_view

    def _bump(self, key: str, n: int = 1) -> None:
        self.metrics.inc(key, n)

    def start(self):
        # restartable across leadership cycles (manager.go recreates the
        # dispatcher per leadership; in-process, agents hold this object)
        self._stop = threading.Event()
        with self._lock:
            # retire the previous wheel FIRST: replacing it without
            # stopping orphans its ticker, which re-arms forever. Swap +
            # survivor re-arm form ONE critical section so a racing
            # register() lands wholly before (its session is then in
            # _sessions and re-armed here) or wholly after (it adds to
            # the fresh wheel itself).
            self._hb_wheel.stop()
            self._hb_wheel = ShardedHeartbeatWheel(
                granularity=self._wheel_granularity(self.heartbeat_period),
                clock=self.clock, shards=self.shards)
            grace = self.heartbeat_period * GRACE_MULTIPLIER
            for s in self._sessions.values():
                # sessions that registered before/through the restart
                # window (the RPC plane serves register as soon as raft
                # elects) — the old per-session timers survived a
                # restart implicitly; the wheel must re-arm explicitly
                self._hb_wheel.add(
                    s.node_id, grace,
                    lambda nid=s.node_id, sid=s.session_id:
                    self._node_down(nid, sid))
        self._mark_nodes_unknown()
        self._arm_orphan_timers()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="dispatcher")
        self._thread.start()

    def stop(self):
        self._stop.set()
        with self._status_cond:
            self._status_cond.notify_all()
        if self._thread:
            self._thread.join(timeout=5)
        self._hb_wheel.stop()
        pool, self._pool = self._pool, None
        if pool is not None:
            # the flush thread joined above: no serve is in flight
            pool.shutdown(wait=True)
        with self._lock:
            for s in self._sessions.values():
                s.channel.close()
                if s.session_channel is not None:
                    s.session_channel.close()
                if s.tasks_channel is not None:
                    s.tasks_channel.close()
            self._sessions.clear()
            for sh in self._shards:
                with sh.lock:
                    sh.pending.clear()
                    sh.dirty.clear()
                    sh.hard.clear()
                    sh.reports.clear()
            if self._diffcols is not None:
                for dcs in self._diffcols:
                    dcs.clear()
            self._secret_refs.clear()
            self._config_refs.clear()
            self._clone_bases.clear()
            self._vol_pending_unpub.clear()
            self._unpub_nodes_by_vol.clear()
            self._vol_index_primed = False
            timers, self._unknown_timers = self._unknown_timers, {}
            orphans, self._orphan_timers = self._orphan_timers, {}
        for t in timers.values():
            t.stop()
        for t in orphans.values():
            t.stop()

    def _mark_nodes_unknown(self):
        """dispatcher.go markNodesUnknown:421-483 — a freshly-elected leader
        inherits node statuses written by the previous dispatcher but none
        of its sessions. Every READY node is demoted to UNKNOWN (removing it
        from scheduling candidacy) with a registration grace timer: nodes
        that re-register flip back READY; those that don't go DOWN, and the
        orchestrators reschedule their tasks."""
        try:
            nodes = self.store.view(lambda tx: tx.find_nodes())
        except Exception:
            return
        candidates = [n.id for n in nodes
                      if n.status.state == NodeStatusState.READY]
        # nodes a PREVIOUS leader demoted to UNKNOWN whose grace timer died
        # with it: they need a timer here too, or they hang UNKNOWN forever
        inherited = [n.id for n in nodes
                     if n.status.state == NodeStatusState.UNKNOWN]
        if not candidates and not inherited:
            return
        demoted: list[str] = []

        def cb(tx):
            demoted.clear()
            demoted.extend(inherited)
            # the live-session check runs INSIDE the txn: a register() that
            # lands between the snapshot above and this write must keep its
            # READY (the RPC plane serves register as soon as raft elects,
            # possibly before the dispatcher start reaches here)
            with self._lock:
                live = set(self._sessions)
            for node_id in candidates:
                if node_id in live:
                    continue
                node = tx.get_node(node_id)
                if node is None or \
                        node.status.state != NodeStatusState.READY:
                    continue
                node = node.copy()
                node.status.state = NodeStatusState.UNKNOWN
                node.status.message = \
                    "manager leadership changed; awaiting re-registration"
                tx.update(node)
                demoted.append(node_id)

        try:
            self.store.update(cb)
        except Exception:
            return
        grace = self.heartbeat_period * GRACE_MULTIPLIER
        with self._lock:
            for node_id in demoted:
                if node_id in self._sessions:
                    continue  # registered while the proposal committed
                timer = Heartbeat(
                    grace, lambda nid=node_id: self._unknown_expired(nid),
                    clock=self.clock)
                self._unknown_timers[node_id] = timer
                timer.start()

    def _unknown_expired(self, node_id: str):
        """Grace ran out without a register(): the node is gone
        (dispatcher.go moveTasksToOrphaned precursor — DOWN first)."""
        with self._lock:
            self._unknown_timers.pop(node_id, None)
            alive = node_id in self._sessions

        def cb(tx):
            node = tx.get_node(node_id)
            if node is None or \
                    node.status.state != NodeStatusState.UNKNOWN:
                return
            node = node.copy()
            if alive:
                # registered while the grace ran but after the UNKNOWN write
                # landed: restore candidacy
                node.status.state = NodeStatusState.READY
                node.status.message = ""
            else:
                node.status.state = NodeStatusState.DOWN
                node.status.message = \
                    "did not re-register after leadership change"
            tx.update(node)

        try:
            self.store.update(cb)
        except Exception:
            pass
        else:
            if not alive:
                # a node lost across a leadership change starts its orphan
                # countdown like any heartbeat-failed node
                self._arm_orphan_timer(node_id)

    # ------------------------------------------------------------------- rpc
    def register(self, node_id: str, description=None) -> str:
        """reference: dispatcher.go:553 register — issues a session id and
        marks the node READY. Re-registration is rate limited
        (nodes.go CheckRateLimit: >3 per 8s window is rejected) so a
        crash-looping agent cannot grind the control plane."""
        now = time.monotonic()
        with self._lock:
            attempts, window_start = self._reg_attempts.get(node_id, (0, now))
            if now - window_start > self.rate_limit_period:
                attempts, window_start = 0, now
            attempts += 1
            self._reg_attempts[node_id] = (attempts, window_start)
            if attempts > RATE_LIMIT_COUNT:
                raise RateLimitExceeded(
                    f"node {node_id} exceeded rate limit count of "
                    "registrations")

        def cb(tx):
            # mandatory-FIPS cluster: refuse non-FIPS registrations on the
            # server side too (the join token already gates the client,
            # reference node.go ErrMandatoryFIPS; this is the belt for a
            # node whose FIPS mode flipped after it joined). A missing
            # description falls back to the stored node's; a node the
            # cluster knows nothing about must assert FIPS to register.
            if any(c.fips for c in tx.find_clusters()):
                desc = description
                if desc is None:
                    known = tx.get_node(node_id)
                    desc = known.description if known is not None else None
                if desc is None or not desc.fips:
                    raise SessionInvalid(
                        "node is not FIPS-enabled but cluster "
                        "requires FIPS")
            node = tx.get_node(node_id)
            if node is None:
                node = Node(id=node_id)
                node.status.state = NodeStatusState.READY
                if description is not None:
                    node.description = description
                tx.create(node)
            else:
                node = node.copy()
                node.status.state = NodeStatusState.READY
                node.status.message = ""
                if description is not None:
                    node.description = description
                tx.update(node)

        self.store.update(cb)

        session_id = new_id()
        session = Session(
            node_id=node_id,
            session_id=session_id,
            channel=Channel(matcher=None, limit=ASSIGNMENTS_CHANNEL_LIMIT),
        )
        with self._lock:
            old = self._sessions.pop(node_id, None)
            if old is not None:
                self._drop_session_refs(old)
                old.channel.close()
                if old.session_channel is not None:
                    old.session_channel.close()
                if old.tasks_channel is not None:
                    old.tasks_channel.close()
            self._sessions[node_id] = session
            self._mark_dirty(node_id)
            pending = self._unknown_timers.pop(node_id, None)
            orphan = self._orphan_timers.pop(node_id, None)
            # wheel entry keyed by node, armed INSIDE the session-swap
            # critical section: racing register() calls must leave the
            # winning session with the winning callback (outside the
            # lock, a delayed loser could overwrite it — and a stale
            # expiry would drop the entry while _node_down discards the
            # superseded session id, leaving the live session without
            # liveness). Lock order dispatcher→wheel is safe: wheel
            # callbacks fire with no wheel lock held.
            self._hb_wheel.add(node_id,
                               self.heartbeat_period * GRACE_MULTIPLIER,
                               lambda: self._node_down(node_id, session_id))
        if pending is not None:
            pending.stop()  # re-registered within the leadership grace
        if orphan is not None:
            orphan.stop()   # the node came back before the orphan window
        return session_id

    def register_many(self, node_ids, description=None,
                      availability=None,
                      channel_limit: int | None = None) -> dict:
        """Batched register for fleet joins and session storms
        (ISSUE 16): N sessions in O(N / MAX_CHANGES) store transactions
        instead of N — one rate-limit pass, node writes chunked through
        `store.batch` (pipelined through propose_async when
        raft-backed), and session swaps in bounded critical sections so
        live heartbeats interleave with a large burst.

        `availability` (a NodeAvailability value or its lowercase name)
        applies to NEWLY CREATED node records only — bench simulacra
        join pre-DRAINed so the scheduler never places real work on
        them. `channel_limit` caps each session's assignments Channel
        below the default: a storm whose streams are never drained
        sheds at the cap (slow-subscriber rule; the delivery gate keeps
        known-state honest) instead of holding 4096 queued messages per
        session. The cluster FIPS gate is evaluated ONCE per batch (the
        per-register in-tx check is the oracle; a cluster spec flip
        racing the batch lands at the next register).

        Returns {node_id: session_id}; rate-limited or FIPS-rejected
        nodes are simply absent."""
        from ..api.types import NodeAvailability

        if isinstance(availability, str):
            availability = NodeAvailability[availability.upper()]
        now = time.monotonic()
        accepted: list[str] = []
        with self._lock:
            for node_id in node_ids:
                attempts, window_start = self._reg_attempts.get(
                    node_id, (0, now))
                if now - window_start > self.rate_limit_period:
                    attempts, window_start = 0, now
                attempts += 1
                self._reg_attempts[node_id] = (attempts, window_start)
                if attempts <= RATE_LIMIT_COUNT:
                    accepted.append(node_id)
        if not accepted:
            return {}

        def fips_gate(tx):
            if not any(c.fips for c in tx.find_clusters()):
                return set(accepted)
            if description is not None and description.fips:
                return set(accepted)
            ok = set()
            for nid in accepted:
                known = tx.get_node(nid)
                if known is not None and known.description is not None \
                        and known.description.fips:
                    ok.add(nid)
            return ok

        accepted = [nid for nid in accepted
                    if nid in self.store.view(fips_gate)]
        if not accepted:
            return {}

        def fill(b):
            for nid in accepted:
                def cb(tx, nid=nid):
                    node = tx.get_node(nid)
                    if node is None:
                        node = Node(id=nid)
                        node.status.state = NodeStatusState.READY
                        if description is not None:
                            node.description = description
                        if availability is not None:
                            node.spec.availability = availability
                        tx.create(node)
                    else:
                        node = node.copy()
                        node.status.state = NodeStatusState.READY
                        node.status.message = ""
                        if description is not None:
                            node.description = description
                        tx.update(node)
                b.update(cb)

        self.store.batch(fill, pipeline_depth=16)

        out: dict[str, str] = {}
        grace = self.heartbeat_period * GRACE_MULTIPLIER
        limit = channel_limit or ASSIGNMENTS_CHANNEL_LIMIT
        chunk_size = 1024
        for off in range(0, len(accepted), chunk_size):
            chunk = accepted[off:off + chunk_size]
            stopped: list = []
            with self._lock:
                for nid in chunk:
                    session_id = new_id()
                    session = Session(
                        node_id=nid, session_id=session_id,
                        channel=Channel(matcher=None, limit=limit))
                    old = self._sessions.pop(nid, None)
                    if old is not None:
                        self._drop_session_refs(old)
                        old.channel.close()
                        if old.session_channel is not None:
                            old.session_channel.close()
                        if old.tasks_channel is not None:
                            old.tasks_channel.close()
                    self._sessions[nid] = session
                    self._mark_dirty(nid)
                    pending = self._unknown_timers.pop(nid, None)
                    orphan = self._orphan_timers.pop(nid, None)
                    self._hb_wheel.add(
                        nid, grace,
                        lambda nid=nid, sid=session_id:
                            self._node_down(nid, sid))
                    if pending is not None:
                        stopped.append(pending)
                    if orphan is not None:
                        stopped.append(orphan)
                    out[nid] = session_id
            for timer in stopped:
                timer.stop()
        return out

    def _jittered_period(self, node_id: str | None = None) -> float:
        """period − uniform(0, ε) per beat (VERDICT item 6; reference
        DefaultHeartBeatEpsilon, dispatcher.go:29-33): 10k nodes
        registered in a burst would otherwise beat in phase forever.
        Jitter only ever SHORTENS the interval, so the grace window
        (full period × multiplier) keeps its margin; reading
        self.heartbeat_period per call keeps live reconfig applying.
        ε is floored to half the period so tiny test periods stay
        positive.

        ISSUE 13: the draw comes from the node's SHARD rng stream, not
        the process-global module RNG — each wheel slice disperses its
        own beats independently, so a shard rebuild (restart or
        re-register burst) cannot phase-align one shard's sessions into
        a single wheel bucket, and a seeded dispatcher (jitter_seed)
        replays deterministic per-shard schedules in tests."""
        period = self.heartbeat_period
        rng = (self._shard_for(node_id).rng if node_id is not None
               else self._shards[0].rng)
        return period - rng.uniform(0.0, min(HEARTBEAT_EPSILON,
                                             period / 2))

    def heartbeat(self, node_id: str, session_id: str,
                  metrics=None) -> float:
        """reference: dispatcher.go:1317-1335. The grace window re-arms
        from the CURRENT period so live reconfig applies to existing
        sessions too (nodes.go updatePeriod).

        `metrics` (ISSUE 15): an optional piggybacked telemetry
        snapshot (utils/telemetry.node_snapshot) stored in the node's
        owning SHARD. Disarmed agents send None, so the plain beat path
        pays one `is not None` test and nothing else."""
        # failpoint `dispatcher.heartbeat`: error = beats lost before
        # the timer re-arms (a heartbeat-miss storm: sessions expire,
        # nodes flip DOWN, tasks orphan); delay = a stalled dispatcher
        failpoints.fp("dispatcher.heartbeat")
        self._session(node_id, session_id)
        if metrics is not None:
            self._record_report(node_id, metrics)
        grace = self.heartbeat_period * GRACE_MULTIPLIER
        if not self._hb_wheel.beat(node_id, grace):
            # valid session without a wheel entry: it registered through
            # a leadership stop/start window and missed both the
            # register-time add and the start() re-arm — self-heal, but
            # only while still the CURRENT session (a racing register()
            # owns the entry otherwise)
            with self._lock:
                s = self._sessions.get(node_id)
                if s is not None and s.session_id == session_id:
                    self._hb_wheel.add(
                        node_id, grace,
                        lambda: self._node_down(node_id, session_id))
        return self._jittered_period(node_id)

    # -------------------------------------------------- telemetry plane
    def _record_report(self, node_id: str, snap) -> None:
        """Store a piggybacked telemetry snapshot in the node's shard
        (ISSUE 15). Stored only while the manager-side plane is armed
        (a disarmed manager must not accrete reports a test/operator
        never asked for) and bounded structurally — the wire codec
        rebuilds payloads without field checks, so one hostile agent
        must not balloon a shard's report store: bounded on series
        count AND on a structural cell budget that bails early, so a
        single huge counts vector (or a giant blob under an unknown
        key) is rejected without walking it and without a JSON encode
        on the beat path. The shard lock is a LEAF (the pinned
        `dispatcher.lock` → shard order; here we hold nothing above
        it)."""
        st = telemetry.state()
        if st is None:
            return
        if not isinstance(snap, dict) \
                or snapshot_series_count(snap) > telemetry.MAX_REPORT_SERIES \
                or not snapshot_within_budget(snap):
            st.bump("reports_rejected")
            return
        stamp = self.clock.monotonic()
        sh = self._shard_for(node_id)
        with sh.lock:
            sh.reports[node_id] = (snap, stamp)
        st.bump("reports_stored")

    def telemetry_reports(self) -> list[dict]:
        """Per-shard copies of the stored node reports
        ([{node id: (snapshot, stamp)}, ...]) — the manager aggregator
        merges each shard's partial, then composes the partials
        (merge_snapshot is associative/commutative)."""
        out = []
        for sh in self._shards:
            with sh.lock:
                out.append(dict(sh.reports))
        return out

    def drop_telemetry_report(self, node_id: str) -> None:
        sh = self._shard_for(node_id)
        with sh.lock:
            sh.reports.pop(node_id, None)

    def assignments(self, node_id: str, session_id: str) -> Channel:
        """Subscribe to this node's assignment stream; the initial COMPLETE
        snapshot is pushed before return (dispatcher.go:1013-1207)."""
        session = self._session(node_id, session_id)
        with self._lock:
            msg = self._full_assignment(session)
            session.channel._offer(msg)
        return session.channel

    def tasks(self, node_id: str, session_id: str) -> Channel:
        """Dispatcher.Tasks — the LEGACY task stream that predates
        Assignments (api/dispatcher.proto:40-47; agent/session.go:282-368
        watches Assignments WITH a Tasks fallback for old managers): the
        full list of this node's runnable tasks, re-sent whenever the
        node's assignment set changes. Superseded by `assignments` (which
        also ships secrets/configs/volumes incrementally); served for
        wire-surface parity."""
        session = self._session(node_id, session_id)
        with self._lock:
            if session.tasks_channel is None:
                session.tasks_channel = Channel(matcher=None, limit=256)
            snapshot = self.store.view(
                lambda tx: [t.copy()
                            for t in self._relevant_tasks(tx, node_id)])
            session.tasks_channel._offer(snapshot)
        return session.tasks_channel

    def session(self, node_id: str, session_id: str) -> Channel:
        """The Session message stream (dispatcher.go:1359+): an immediate
        snapshot (manager list, root CA, network keys, this node's roles)
        then pushes whenever any of those change."""
        session = self._session(node_id, session_id)
        with self._lock:
            if session.session_channel is None:
                session.session_channel = Channel(matcher=None, limit=256)
            msg = self._build_session_message(session.node_id)
            session.last_session_msg = msg
            session.session_channel._offer(msg)
        return session.session_channel

    def _session_plane_snapshot(self):
        """ONE store pass for everything the session plane serves: the
        shared (managers, root CA, network keys) plus a per-node roles map
        — per-session messages derive from this without re-scanning."""

        def cb(tx):
            managers = []
            roles: dict[str, tuple] = {}
            for n in tx.find_nodes():
                ms = n.manager_status
                if ms is not None and ms.addr:
                    managers.append((n.id, ms.addr))
                roles[n.id] = (n.role, n.spec.desired_role)
            root_pem, keys = b"", []
            for c in tx.find_clusters():
                if c.root_ca is not None and c.root_ca.ca_cert_pem:
                    root_pem = c.root_ca.ca_cert_pem
                    # mid-rotation: nodes must trust BOTH anchors, and the
                    # cross-signed intermediate ships along so old-pinned
                    # joiners can verify the old root vouches for the new
                    # (ca/reconciler.go — old-pinned peers and new-signed
                    # certs coexist until every cert has moved over)
                    rot = c.root_ca.root_rotation
                    if rot:
                        root_pem = (root_pem + rot["new_ca_cert_pem"]
                                    + rot["cross_signed_pem"])
                keys = list(c.network_bootstrap_keys or [])
                break
            return sorted(managers), root_pem, keys, roles

        return self.store.view(cb)

    def _build_session_message(self, node_id: str) -> SessionMessage:
        managers, root_pem, keys, roles = self._session_plane_snapshot()
        role, desired = roles.get(node_id, (None, None))
        return SessionMessage(managers=managers, root_ca_pem=root_pem,
                              network_keys=keys, node_role=role,
                              desired_role=desired)

    def _push_session_updates(self):
        """Offer a fresh SessionMessage to sessions whose view changed."""
        with self._lock:
            listeners = [s for s in self._sessions.values()
                         if s.session_channel is not None]
        if not listeners:
            return
        managers, root_pem, keys, roles = self._session_plane_snapshot()
        for s in listeners:
            role, desired = roles.get(s.node_id, (None, None))
            msg = SessionMessage(managers=managers, root_ca_pem=root_pem,
                                 network_keys=keys, node_role=role,
                                 desired_role=desired)
            if msg != s.last_session_msg:
                s.last_session_msg = msg
                try:
                    s.session_channel._offer(msg)
                except Exception:
                    pass

    def update_task_status(self, node_id: str, session_id: str,
                           updates: list[tuple[str, object]]):
        """Enqueue observed-state updates; written in batches
        (dispatcher.go:607, processUpdates :726-886). A malformed status
        is rejected here — the wire codec rebuilds payloads without
        field checks, and one bad entry inside the batch write would
        abort the whole flush, dropping other nodes' good statuses.
        Ownership is enforced at flush time against the task's CURRENT
        node (dispatcher.go:654 'cannot update a task not assigned this
        node' — a worker must not write observed state for tasks that
        are not its own)."""
        self._session(node_id, session_id)
        ok = []
        for task_id, status in updates:
            if not isinstance(getattr(status, "state", None), TaskState):
                # drop per-entry, not per-batch: rejecting the whole list
                # would bounce through the agent's retry queue forever
                # (the bad entry re-queues with the good ones), wedging
                # ALL status reporting from this node
                log.warning("dropping malformed task status %r for task "
                            "%s from node %s", status, task_id, node_id)
                continue
            ok.append((task_id, status, node_id))
        with self._status_cond:
            self._status_queue.extend(ok)
            self._status_cond.notify_all()

    def update_volume_status(self, node_id: str, session_id: str,
                             unpublished: list[str]):
        """The agent confirms node-side unpublish of volumes
        (dispatcher.proto UpdateVolumeStatus): advance
        PENDING_NODE_UNPUBLISH → PENDING_UNPUBLISH so the CSI manager can
        controller-detach (the store event wakes its reconciler).

        Same wire-payload threat model as update_task_status: the codec
        rebuilds payloads without field checks, so malformed entries
        (non-string / empty ids) are dropped per-entry here — one bad id
        must neither crash the handler nor void the node's good
        confirmations (ADVICE r5)."""
        from ..csi.manager import advance_node_unpublish

        self._session(node_id, session_id)
        ok = []
        for vid in unpublished:
            if not isinstance(vid, str) or not vid:
                log.warning("dropping malformed volume unpublish entry %r "
                            "from node %s", vid, node_id)
                continue
            ok.append(vid)
        if ok:
            advance_node_unpublish(self.store, node_id, ok)

    def leave(self, node_id: str, session_id: str):
        """Graceful node departure."""
        session = self._session(node_id, session_id)
        with self._lock:
            # pop + wheel removal gated on still being the CURRENT
            # session, in one critical section: a register() racing this
            # leave must not have its fresh wheel entry torn down
            if self._sessions.get(node_id) is session:
                self._sessions.pop(node_id)
                self._drop_session_refs(session)
                self._hb_wheel.remove(node_id)
        session.channel.close()
        if session.session_channel is not None:
            session.session_channel.close()
        if session.tasks_channel is not None:
            session.tasks_channel.close()
        # a deliberate departure retires the node's telemetry report too
        # — only nodes that VANISH should surface as stale in the rollup
        self.drop_telemetry_report(node_id)
        self._node_down(node_id, session_id, graceful=True)

    # ------------------------------------------------------------- internals
    def _session(self, node_id: str, session_id: str) -> Session:
        with self._lock:
            s = self._sessions.get(node_id)
        if s is None or s.session_id != session_id:
            raise SessionInvalid(f"session {session_id} invalid for {node_id}")
        return s

    def _node_down(self, node_id: str, session_id: str, graceful=False):
        with self._lock:
            s = self._sessions.get(node_id)
            if s is not None and s.session_id == session_id:
                s.channel.close()
                if s.session_channel is not None:
                    s.session_channel.close()
                if s.tasks_channel is not None:
                    s.tasks_channel.close()
                self._sessions.pop(node_id, None)
                self._drop_session_refs(s)
            elif not graceful:
                return  # superseded session
        # no wheel removal here: an expiry already dropped its entry, a
        # graceful leave removed it before calling, and a superseded
        # session's entry now belongs to its replacement

        def cb(tx):
            node = tx.get_node(node_id)
            if node is None:
                return
            node = node.copy()
            node.status.state = NodeStatusState.DOWN
            node.status.message = ("node left" if graceful
                                   else "heartbeat failure")
            tx.update(node)

        try:
            self.store.update(cb)
        except Exception:
            pass
        else:
            if not graceful:
                self._arm_orphan_timer(node_id)

    # ------------------------------------------------- down-node orphaning
    def _arm_orphan_timers(self):
        """On (re)start, nodes already DOWN resume their orphan countdown
        (the previous leader's timers died with it). The full window
        restarts — the store doesn't record when the node went down, and a
        conservative restart beats orphaning early."""
        try:
            nodes = self.store.view(lambda tx: tx.find_nodes())
        except Exception:
            return
        for n in nodes:
            if n.status.state == NodeStatusState.DOWN:
                self._arm_orphan_timer(n.id)

    def _arm_orphan_timer(self, node_id: str):
        with self._lock:
            if node_id in self._orphan_timers or self._stop.is_set():
                return
            timer = Heartbeat(self.node_down_period,
                              lambda: self._orphan_expired(node_id),
                              clock=self.clock)
            self._orphan_timers[node_id] = timer
        timer.start()

    def _orphan_expired(self, node_id: str):
        """dispatcher.go moveTasksToOrphaned:1209 — a node down for the
        full window: we cannot know whether its tasks still run; mark every
        task that could have made progress (ASSIGNED..RUNNING) ORPHANED so
        the reaper can collect them."""
        with self._lock:
            self._orphan_timers.pop(node_id, None)
            if node_id in self._sessions:
                return  # came back concurrently

        def cb(batch):
            tasks = self.store.view(
                lambda tx: tx.find_tasks(by.ByNodeID(node_id)))
            for t in tasks:
                if not (TaskState.ASSIGNED <= t.status.state
                        <= TaskState.RUNNING):
                    continue

                def update_one(tx, task_id=t.id):
                    cur = tx.get_task(task_id)
                    if cur is None or not (
                            TaskState.ASSIGNED <= cur.status.state
                            <= TaskState.RUNNING):
                        return
                    cur = cur.copy()
                    cur.status.state = TaskState.ORPHANED
                    cur.status.message = "node unreachable past the " \
                        "orphaning window"
                    tx.update(cur)

                batch.update(update_one)

        try:
            self.store.batch(cb)
        except Exception:
            log.warning("orphaning batch failed for node %s", node_id,
                        exc_info=True)

    # ---------------------------------------------------------- event plane
    def _run(self):
        # server-side kind filtering (what watchapi selectors do for
        # remote clients, objects.proto watch_selectors): the event loop
        # consumes these kinds only — service/network churn never reaches
        # it. The matcher runs in the committing writer's publish path,
        # so it is a bare table-name set test, not selector machinery.
        kinds = frozenset(
            ("task", "secret", "config", "volume", "cluster", "node"))

        def matcher(ev, _kinds=kinds):
            obj = getattr(ev, "obj", None)
            return obj is not None and obj.TABLE in _kinds

        # the reverse-index prime rides the SAME atomic
        # snapshot-then-subscribe: every event after the snapshot flows
        # through _note_event's maintenance, so the indexes never miss a
        # transition between prime and watch
        _, ch = self.store.view_and_watch(
            self._prime_reverse_indexes, matcher=matcher, limit=None)
        last_flush = time.monotonic()
        try:
            while not self._stop.is_set():
                self._flush_statuses()
                try:
                    ev = ch.get(timeout=BATCH_INTERVAL / 2)
                except TimeoutError:
                    ev = None
                except Exception:
                    return
                if ev is not None:
                    self._note_event(ev)
                now = time.monotonic()
                if now - last_flush >= BATCH_INTERVAL:
                    try:
                        self._send_incrementals()
                    except Exception:
                        # a crashed flush re-dirtied its unserved nodes
                        # (see _send_incrementals); the next interval
                        # retries them — never kill the event loop
                        log.warning("assignment flush failed; dirty "
                                    "sessions retained for retry",
                                    exc_info=True)
                    if self._session_plane_dirty:
                        self._session_plane_dirty = False
                        self._push_session_updates()
                    last_flush = now
        finally:
            self.store.queue.stop_watch(ch)

    def _note_event(self, ev):
        obj = getattr(ev, "obj", None)
        if isinstance(obj, Task):
            if isinstance(ev, EventDelete):
                with self._lock:
                    for key in [k for k in self._driver_cache
                                if k[2] == obj.id]:
                        del self._driver_cache[key]
            # SOFT dirt (hard=False): task churn is exactly what the
            # columnar gate's task leg compares (ISSUE 16)
            if obj.node_id:
                self._mark_dirty(obj.node_id, hard=False)
            if isinstance(ev, EventUpdate) and ev.old is not None \
                    and ev.old.node_id and ev.old.node_id != obj.node_id:
                self._mark_dirty(ev.old.node_id, hard=False)
        elif isinstance(obj, Secret):
            # only sessions that were shipped this secret care about its
            # change; fresh references always arrive via a task event,
            # which dirties the node anyway. The reverse reference map
            # (maintained by _commit_known, mirroring assignments.go's
            # per-node reference sets) answers this as one dict lookup —
            # the old per-event walk over every session's known_secrets
            # collapsed at 10k nodes
            with self._lock:
                if isinstance(ev, EventDelete):
                    for key in [k for k in self._driver_cache
                                if k[0] == obj.id]:
                        del self._driver_cache[key]
                self._mark_dirty_many(
                    self._secret_refs.get(obj.id, set())
                    & self._sessions.keys(), hard=False)
        elif isinstance(obj, Config):
            with self._lock:
                self._mark_dirty_many(
                    self._config_refs.get(obj.id, set())
                    & self._sessions.keys(), hard=False)
        else:
            from ..api.objects import Cluster, Volume

            if isinstance(obj, Volume):
                # publish-status changes gate volume assignment shipping
                from ..csi.plugin import PENDING_NODE_UNPUBLISH

                pending = set()
                if not isinstance(ev, EventDelete):
                    pending = {s.node_id for s in obj.publish_status
                               if s.state == PENDING_NODE_UNPUBLISH}
                touched = {s.node_id for s in obj.publish_status}
                old = getattr(ev, "old", None)
                if old is not None:
                    # a node whose publish entry VANISHED (vs moving
                    # through pending_node_unpublish) must still learn
                    # about the removal
                    touched |= {s.node_id for s in old.publish_status}
                with self._lock:
                    self._mark_dirty_many(
                        touched & set(self._sessions.keys()))
                    # the index resyncs from EVERY volume event (new
                    # pending set replaces the old wholesale), so a
                    # crashed flush can never leave it diverged past the
                    # next event touching the volume
                    self._reindex_volume(obj.id, pending)
            elif isinstance(obj, Cluster):
                # live reconfig from the replicated Cluster object
                # (dispatcher.go:1072-1077): heartbeat period applies to
                # future beats and is returned by the next heartbeat RPC.
                # Only an actual SPEC change applies — unrelated cluster
                # writes must not clobber an operator-configured period
                # with the seeded value.
                period = obj.spec.dispatcher.heartbeat_period
                old = getattr(ev, "old", None)
                old_period = (old.spec.dispatcher.heartbeat_period
                              if old is not None else None)
                if period and period != old_period \
                        and period != self.heartbeat_period:
                    self.heartbeat_period = period
                    # keep the wheel's lateness inside the new period's
                    # epsilon slack; existing deadlines re-bucket
                    self._hb_wheel.set_granularity(
                        self._wheel_granularity(period))
                self._session_plane_dirty = True
        if isinstance(obj, Node):
            # manager list / role changes ride the Session stream
            self._session_plane_dirty = True

    # ---------------------------------------------------- assignment building
    def _relevant_tasks(self, tx, node_id: str) -> list[Task]:
        return [
            t for t in tx.find_tasks(by.ByNodeID(node_id))
            if t.status.state >= TaskState.ASSIGNED
            and t.desired_state <= TaskState.REMOVE
        ]

    @staticmethod
    def _volume_assignment(v, st):
        """Build the VolumeAssignment shipped to an agent for volume `v`
        with per-node publish status `st` (assignments.go VolumeAssignment)."""
        from ..agent.csi import VolumeAssignment

        return VolumeAssignment(
            id=v.id,
            volume_id=v.volume_info.volume_id if v.volume_info else "",
            driver=v.spec.driver,
            volume_context=dict(
                v.volume_info.volume_context
            ) if v.volume_info else {},
            publish_context=dict(st.publish_context),
            availability=v.spec.availability,
        )

    def _materialize_driver_secret(self, secret, task, node_id: str):
        """Driver-provided secret: per-task clone with the plugin's payload
        (assignments.go:51-81 task-specific cloning — id is suffixed with
        the task id so one task can never read another's credentials).

        Runs OUTSIDE any store transaction — drivers do external I/O and
        must never stall the store lock. Results cache per
        (secret version, task), so incrementals don't re-fire plugin RPCs.
        """
        key = (secret.id, secret.meta.version.index, task.id)
        with self._lock:
            cached = self._driver_cache.get(key)
            if cached is not None:
                # re-register the base mapping: retirement pops it
                # unconditionally, and a task re-shipping the cached
                # clone (e.g. after moving nodes) must restore it
                self._clone_bases[cached.id] = secret.id
        if cached is not None:
            return cached
        driver_cfg = secret.spec.driver or {}
        name = driver_cfg.get("name", "")
        driver = self.secret_drivers.get(name) if self.secret_drivers else None
        if driver is None:
            return None
        try:
            payload = driver.get(secret, task, node_id)
        except Exception:
            return None
        clone = secret.copy()
        clone.id = f"{secret.id}.{task.id}"
        clone.spec.data = payload
        with self._lock:
            # purge superseded versions for this (secret, task): long-lived
            # tasks with rotated credentials must not accrete stale payloads
            for k in [k for k in self._driver_cache
                      if k[0] == secret.id and k[2] == task.id and k != key]:
                del self._driver_cache[k]
            self._driver_cache[key] = clone
            # the reverse reference maps key by BASE id; clone ids map
            # back through this (ids are opaque — never parsed)
            self._clone_bases[clone.id] = secret.id
        return clone

    def _referenced_deps(self, tx, tasks, node_id: str,
                         driver_refs: list,
                         missing: list | None = None
                         ) -> tuple[dict, dict, dict]:
        """Secrets/configs the node's tasks reference, plus cluster-volume
        assignments already controller-published to this node
        (assignments.go:21-81; volumes ship once PUBLISHED so the agent
        can node-stage them). Returns LIVE store references — store
        objects are immutable by contract and commits swap table entries,
        so they are stable snapshots; wire copies happen only when the
        diff actually ships an object. Driver-backed secret references
        are only COLLECTED here (into `driver_refs` as (secret, task)
        pairs) — their materialization does external I/O and happens
        after the transaction. Referenced-but-ABSENT secrets/configs
        collect into `missing` as (kind, id) pairs when the caller asks
        (ISSUE 16): a dep created later never events this session, so
        the columnar gate must re-check resolution per flush."""
        from ..csi.plugin import PUBLISHED

        secrets, configs, volumes = {}, {}, {}
        for t in tasks:
            # desired COMPLETE is a live job task and still needs its deps
            if t.desired_state > TaskState.COMPLETE:
                continue
            for vid in t.volumes:
                v = tx.get_volume(vid)
                if v is None:
                    continue
                for st in v.publish_status:
                    if st.node_id == node_id and st.state == PUBLISHED:
                        volumes[vid] = self._volume_assignment(v, st)
            runtime = t.spec.runtime
            if runtime is None:
                continue
            for ref in runtime.secrets:
                s = tx.get_secret(ref.secret_id)
                if s is None:
                    if missing is not None:
                        missing.append(("secret", ref.secret_id))
                    continue
                if s.spec.driver:
                    driver_refs.append((s, t))
                    continue
                secrets[s.id] = s
            for ref in runtime.configs:
                c = tx.get_config(ref.config_id)
                if c is None:
                    if missing is not None:
                        missing.append(("config", ref.config_id))
                    continue
                configs[c.id] = c
        return secrets, configs, volumes

    def _pending_unpublish(self, tx, node_id: str) -> dict:
        """Volumes awaiting node-side unpublish on this node. The remove
        assignment is re-sent in every message while the state persists —
        the node may be restarting and have lost the original remove
        (reference: dispatcher/assignments.go:364-373). The full
        VolumeAssignment is shipped (not just the id) so a fresh agent
        process can still run the idempotent node-unpublish.

        Served from the reverse index (node → pending volume ids) once
        primed: the per-node full `find_volumes()` scan made rollout
        storms O(nodes × volumes). The index is a HINT — each hit is
        re-checked against live volume state, and a stale entry lasts
        only until the next event touching that volume replaces its set
        — so a diverged index can produce extra lookups, never a wrong
        assignment."""
        from ..csi.plugin import PENDING_NODE_UNPUBLISH

        # LOCK-FREE index read — this runs inside store-view callbacks
        # (see the constructor note on lock ordering): `primed` is a
        # plain attribute and the frozenset value is immutable, so a
        # concurrent _reindex_volume can only swap in a new value, never
        # mutate the one being iterated
        out = {}
        if not self._vol_index_primed:
            # driven/un-started dispatchers (no event loop maintaining
            # the index) keep the original scan semantics
            for v in tx.find_volumes():
                for st in v.publish_status:
                    if st.node_id == node_id \
                            and st.state == PENDING_NODE_UNPUBLISH:
                        out[v.id] = self._volume_assignment(v, st)
            return out
        for vid in sorted(self._vol_pending_unpub.get(node_id, ())):
            # the index is a HINT: each hit re-checks live volume state,
            # so a stale entry (possible only until the next event
            # touching that volume replaces its set wholesale) costs one
            # lookup, never a wrong assignment
            v = tx.get_volume(vid)
            st = next((s for s in (v.publish_status if v is not None else ())
                       if s.node_id == node_id
                       and s.state == PENDING_NODE_UNPUBLISH), None)
            if st is not None:
                out[vid] = self._volume_assignment(v, st)
        return out

    # ------------------------------------------------- reverse-index plane
    def _prime_reverse_indexes(self, tx):
        """One startup scan building node → pending-unpublish volume ids;
        runs inside _run's atomic snapshot-then-subscribe so no volume
        transition can fall between the prime and the event stream.

        Deliberately NOT under self._lock — the callback runs while the
        store lock is held, and the RPC paths hold the dispatcher lock
        across store views (AB-BA otherwise). Safe because until
        `_vol_index_primed` flips, every other thread takes the scan
        fallback and the only index writer is this (the event) thread."""
        from ..csi.plugin import PENDING_NODE_UNPUBLISH

        self._vol_pending_unpub.clear()
        self._unpub_nodes_by_vol.clear()
        for v in tx.find_volumes():
            pending = {st.node_id for st in v.publish_status
                       if st.state == PENDING_NODE_UNPUBLISH}
            if pending:
                self._reindex_volume(v.id, pending)
        self._vol_index_primed = True

    def _reindex_volume(self, vid: str, pending_nodes: set):
        """Replace volume `vid`'s pending-unpublish node set (writers
        serialize under self._lock; the prime is the documented
        exception). Values swap wholesale as frozensets — readers
        (_pending_unpublish, inside store views) never take a lock.
        Diff-maintained both ways so one volume event costs O(changed
        nodes), not O(index)."""
        old = self._unpub_nodes_by_vol.get(vid, frozenset())
        for nid in old - pending_nodes:
            s = self._vol_pending_unpub.get(nid, frozenset()) - {vid}
            if s:
                self._vol_pending_unpub[nid] = s
            else:
                self._vol_pending_unpub.pop(nid, None)
        for nid in pending_nodes - old:
            self._vol_pending_unpub[nid] = \
                self._vol_pending_unpub.get(nid, frozenset()) | {vid}
        if pending_nodes:
            self._unpub_nodes_by_vol[vid] = frozenset(pending_nodes)
        else:
            self._unpub_nodes_by_vol.pop(vid, None)

    def _commit_known(self, session: Session, new_tasks: dict,
                      new_secrets: dict, new_configs: dict,
                      new_volumes: set, sequence: int,
                      ship_bases: dict | None = None,
                      column_plan=None):
        """Atomically replace the session's known-assignment maps and
        maintain the secret/config reverse reference maps from the diff.
        Runs ONLY after the carrying message was delivered (or there was
        nothing to deliver): known-state may never advance past what the
        agent actually saw. `column_plan` (ISSUE 16) is the columnar
        image of the SAME known state, installed here and only here —
        the plan columns advance in lockstep with the dicts; a commit
        without a captured plan invalidates the node's columns (the
        gate then serves it through the dict path until the next
        planned commit)."""
        with self._lock:
            node_id = session.node_id
            current = self._sessions.get(node_id) is session
            # base ids captured at materialize time win — the global map
            # can lose an entry to a concurrent retirement mid-flight;
            # non-clone keys (absent from ship_bases) are their own base
            ship_bases = ship_bases or {}
            new_bases = {k: ship_bases.get(k)
                         or self._clone_bases.get(k, k)
                         for k in new_secrets}
            if current:
                # (a superseded session must not touch the reference
                # maps — its node's entries belong to the replacement)
                for old_keys, new_keys, bases, refs in (
                        (session.known_secrets, new_secrets,
                         session.known_bases, self._secret_refs),
                        (session.known_configs, new_configs, {},
                         self._config_refs)):
                    for k in old_keys:
                        if k not in new_keys:
                            # base as recorded at ship time — immune to
                            # another session's eager _clone_bases pop
                            base = bases.get(k, k)
                            nodes = refs.get(base)
                            if nodes is not None:
                                nodes.discard(node_id)
                                if not nodes:
                                    refs.pop(base, None)
                            if base != k:
                                # clone retired here: collect the global
                                # mapping in O(1) — a cached re-ship
                                # re-registers it, and other sessions
                                # clean up from their OWN recorded base
                                self._clone_bases.pop(k, None)
                    for k in new_keys:
                        if k not in old_keys:
                            # config keys are never cloned: absent from
                            # new_bases, so the default k applies
                            refs.setdefault(new_bases.get(k, k),
                                            set()).add(node_id)
            session.known_tasks = new_tasks
            session.known_secrets = new_secrets
            session.known_configs = new_configs
            session.known_volumes = new_volumes
            session.known_bases = new_bases
            session.sequence = sequence
            if current and self._diffcols is not None:
                # lock order: dispatcher.lock → diffcol leaf (the gate
                # reads plans under store.lock → diffcol instead; the
                # diffcol lock never acquires anything, so no cycle)
                dcs = self._diffcols[stable_shard(node_id, self.shards)]
                if column_plan is not None:
                    dcs.install(node_id, column_plan)
                else:
                    dcs.invalidate(node_id)

    def _drop_session_refs(self, session: Session):
        """Remove a retiring session's entries from the reverse reference
        maps (called under self._lock, and only for the session that
        CURRENTLY owns its node key — a superseded session's references
        belong to its replacement)."""
        node_id = session.node_id
        if self._diffcols is not None:
            # the retiring session's plan must die with it: the next
            # session rebuilds from a COMPLETE and installs its own
            self._diffcols[stable_shard(node_id, self.shards)] \
                .invalidate(node_id)
        for keys, bases, refs in (
                (session.known_secrets, session.known_bases,
                 self._secret_refs),
                (session.known_configs, {}, self._config_refs)):
            for k in keys:
                base = bases.get(k, k)
                nodes = refs.get(base)
                if nodes is not None:
                    nodes.discard(node_id)
                    if not nodes:
                        refs.pop(base, None)
                if base != k:
                    # the session dies holding this clone: collect the
                    # base mapping too (a cached re-ship restores it)
                    self._clone_bases.pop(k, None)

    # -------------------------------------------------- fan-out shipping
    def _node_view(self, tx, node_id: str, driver_refs: list,
                   plan_sink: list | None = None, token: str = ""):
        """One node's assignment inputs as live references — the no-copy
        read half of a flush. When the caller passes a `plan_sink`, a
        ColumnPlan is captured HERE, inside the view (row indices and
        versions read under the store lock are mutually consistent) and
        appended for the delivery-gated commit to install (ISSUE 16);
        `token` is the session id the plan is bound to."""
        missing: list | None = [] if plan_sink is not None else None
        tasks = self._relevant_tasks(tx, node_id)
        secrets, configs, volumes = self._referenced_deps(
            tx, tasks, node_id, driver_refs, missing)
        unpublish = self._pending_unpublish(tx, node_id)
        if plan_sink is not None:
            col = getattr(self.store, "columnar", None)
            if col is not None:
                plan_sink.append(columnar_diff.ColumnPlan.capture(
                    col, token, node_id, tasks, secrets, configs,
                    missing, bool(driver_refs)))
        return tasks, secrets, configs, volumes, unpublish

    def _materialize_clones(self, session: Session, secrets: dict,
                            driver_refs: list) -> tuple[dict, dict]:
        """Outside the store lock: driver-backed secrets materialize per
        task (cached per (secret version, task)); returns
        ((base secret id, task id) -> clone id for shipped-task ref
        rewrites, clone id -> base id captured HERE — the commit must
        not re-derive bases from the mutable global _clone_bases, which
        a concurrent retirement can pop mid-flight)."""
        clone_ids: dict[tuple, str] = {}
        bases: dict[str, str] = {}
        for secret, task in driver_refs:
            clone = self._materialize_driver_secret(secret, task,
                                                    session.node_id)
            if clone is not None:
                secrets[clone.id] = clone
                clone_ids[(secret.id, task.id)] = clone.id
                bases[clone.id] = secret.id
        return clone_ids, bases

    def _ship_task(self, t: Task, clone_ids: dict) -> Task:
        """Wire copy, made ONLY at ship time; driver-backed secret
        references rewrite to this task's clone ids (the clone belongs
        to exactly one task — assignments.go:51-81)."""
        self._bump("wire_copies")
        c = t.copy()
        runtime = c.spec.runtime
        if clone_ids and runtime is not None:
            for ref in runtime.secrets:
                new_id_ = clone_ids.get((ref.secret_id, t.id))
                if new_id_ is not None:
                    ref.secret_id = new_id_
        return c

    def _ship(self, obj):
        self._bump("wire_copies")
        return obj.copy()

    def _full_assignment(self, session: Session) -> AssignmentsMessage:
        driver_refs: list = []
        plans: list = []
        tasks, secrets, configs, volumes, unpublish = self.store.view(
            lambda tx: self._node_view(tx, session.node_id, driver_refs,
                                       plan_sink=plans,
                                       token=session.session_id))
        clone_ids, ship_bases = self._materialize_clones(
            session, secrets, driver_refs)
        changes = (
            [Assignment("update", "task", self._ship_task(t, clone_ids))
             for t in tasks]
            + [Assignment("update", "secret", self._ship(s))
               for s in secrets.values()]
            + [Assignment("update", "config", self._ship(c))
               for c in configs.values()]
            + [Assignment("update", "volume", v) for v in volumes.values()]
            + [Assignment("remove", "volume", va)
               for vid, va in unpublish.items() if vid not in volumes]
        )
        self._bump("ships", len(changes))
        self._commit_known(
            session,
            {t.id: t.meta.version.index for t in tasks},
            {sid: s.meta.version.index for sid, s in secrets.items()},
            {cid: c.meta.version.index for cid, c in configs.items()},
            set(volumes), session.sequence + 1, ship_bases,
            column_plan=plans[0] if plans else None)
        if lifecycle.enabled():
            # lifecycle SHIPPED leg for the COMPLETE snapshot (fresh
            # session: ASSIGNED tasks reach their agent here, not via an
            # incremental)
            shipped = [t.id for t in tasks
                       if t.status.state == TaskState.ASSIGNED]
            if shipped:
                lifecycle.record_batch(lifecycle.SHIPPED, shipped)
        return AssignmentsMessage("complete", session.sequence, changes)

    def _incremental(self, session: Session) -> AssignmentsMessage:
        """Single-session diff outside a batched flush (driven tests,
        the fsm model): its own view, commit-on-build — the caller
        consumes the returned message synchronously."""
        driver_refs: list = []
        plans: list = []
        view = self.store.view(
            lambda tx: self._node_view(tx, session.node_id, driver_refs,
                                       plan_sink=plans,
                                       token=session.session_id))
        clone_ids, ship_bases = self._materialize_clones(
            session, view[1], driver_refs)
        msg, commit = self._diff(session, *view, clone_ids, ship_bases,
                                 column_plan=plans[0] if plans else None)
        commit()
        return msg

    def _send_incrementals(self):
        """THE fan-out hot path: ONE consistent store snapshot serves
        every dirty session's incremental diff (and its legacy
        tasks_channel snapshot) — group-commit applied to the control
        plane, replacing 2 transactions per dirty node per interval.

        ISSUE 13 sharding: the snapshot stays GLOBAL (1 view-tx per
        flush, shared read-only across shards — store objects are
        immutable), while the serve half runs per shard (≤1 dirty-walk
        per shard per flush) on a small worker pool when more than one
        shard has work. Each shard's known-state commits merge under ONE
        short `dispatcher.lock` hold (_serve_shard), keeping the
        reverse-index writes serialized without per-session lock churn.

        A crash at any point re-dirties the unserved sessions so the
        next interval retries; served sessions already committed their
        known-state and are NOT replayed.

        ISSUE 16 columnar gate: inside the same view, one vectorized
        pass per shard proves which soft-dirty sessions have a ZERO
        delta against the live columnar tables; proven-zero sessions
        skip the node view, the dict diff, and the serve entirely
        (their delivery-committed state is already current, so skipping
        IS serving them). HARD-dirty sessions — causes the columns
        can't see — always take the dict path."""
        shard_batches: list[list[Session]] = []
        shard_hard: list[set] = []
        self._drain_pumps()
        with self._lock:
            for sh in self._shards:
                with sh.lock:
                    dirty, sh.dirty = sh.dirty, set()
                    hard, sh.hard = sh.hard, set()
                shard_batches.append([self._sessions[n]
                                      for n in sorted(dirty)
                                      if n in self._sessions])
                shard_hard.append(hard)
        sessions = [s for batch in shard_batches for s in batch]
        if not sessions:
            return
        start = time.monotonic()
        self.metrics["flushes"] += 1
        # trace plane: one span per fan-out flush with snapshot/serve
        # sub-stages; None when disarmed (one truthiness test — the
        # op-count guard in tests/test_dispatcher_fanout.py stays exact)
        sp = trace.start("dispatcher.flush", sessions=len(sessions))
        views: list[list[tuple[Session, tuple, list, list]]] = []
        skipped: set = set()

        def cb(tx):
            self.metrics["flush_tx"] += 1
            views.clear()
            skipped.clear()
            gate = self._gate_context()
            for batch, hard in zip(shard_batches, shard_hard):
                if gate is not None and batch:
                    serve = self._gate_shard(gate, batch, hard, skipped)
                else:
                    serve = batch
                built: list = []
                for session in serve:
                    # failpoint `dispatcher.assignments.build`: one
                    # session's build crashes the flush snapshot
                    # mid-batch (nothing was offered yet — the whole
                    # dirty set retries). Per-session by design:
                    # mid-batch is the crash point under test.
                    # lint: allow(span-in-loop)
                    failpoints.fp("dispatcher.assignments.build")
                    driver_refs: list = []
                    plans: list = []
                    built.append((session,
                                  self._node_view(
                                      tx, session.node_id, driver_refs,
                                      plan_sink=plans,
                                      token=session.session_id),
                                  driver_refs, plans))
                views.append(built)

        out_sets: list[set] = []
        try:
            # failpoint `dispatcher.flush`: the flush dies before the
            # snapshot — the dirty set must survive for the retry
            failpoints.fp("dispatcher.flush")
            t0 = time.perf_counter() if sp is not None else 0.0
            self.store.view(cb)
            if sp is not None:
                trace.rec("dispatcher.flush.snapshot",
                          time.perf_counter() - t0, parent=sp)
                t0 = time.perf_counter()
            work = [batch for batch in views if batch]
            self.metrics["dirty_walks"] += len(work)
            out_sets = [set() for _ in work]
            if len(work) <= 1:
                for batch, served in zip(work, out_sets):
                    self._serve_shard(batch, served)
            else:
                futs = [self._serve_pool().submit(self._serve_shard,
                                                  batch, served)
                        for batch, served in zip(work, out_sets)]
                errs = []
                for f in futs:
                    try:
                        f.result()
                    except Exception as e:       # noqa: PERF203
                        errs.append(e)
                if errs:
                    raise errs[0]
            if sp is not None:
                trace.rec("dispatcher.flush.serve",
                          time.perf_counter() - t0, parent=sp,
                          served=sum(len(s) for s in out_sets))
        except Exception as exc:
            served = set().union(*out_sets) if out_sets else set()
            # gate-skipped sessions were proven current — they count as
            # served; everything else re-dirties HARD (conservative:
            # the retry must not trust a plan from the crashed flush)
            served |= skipped
            self._mark_dirty_many(
                s.node_id for s in sessions if s.node_id not in served)
            if sp is not None:
                # the forensics tail must show this flush FAILED, like
                # every other instrumented plane does on exception
                sp.attrs.setdefault("error", repr(exc))
            raise
        finally:
            self.metrics["last_flush_s"] = time.monotonic() - start
            if sp is not None:
                sp.end(served=sum(len(s) for s in out_sets))

    def _gate_context(self):
        """Per-flush shared gate state, or None when the columnar-diff
        plane is off (env-disabled, or the store has no columnar
        mirror). Built ONCE inside the flush's view callback — the store
        lock makes the relevance mask and per-node counts commit-
        consistent with every plan comparison in the same flush."""
        if self._diffcols is None:
            return None
        col = getattr(self.store, "columnar", None)
        if col is None:
            return None
        return columnar_diff.GateContext(col)

    def _gate_shard(self, gate, batch: list, hard: set,
                    skipped: set) -> list:
        """One shard's skip gate: collect the sessions whose zero delta
        the columns can prove, run the vectorized pass, and return the
        batch minus the proven-clean sessions (serve order preserved).
        Eligibility is conservative — anything the columns can't see
        keeps the dict path: hard-dirty causes (volume events, external
        marks, crash re-dirty), an unprimed volume index, a pending
        node-unpublish re-send, or an open legacy tasks stream (its
        snapshot re-sends per flush). Driver-secret clone state needs no
        check here: a serve with driver refs installs an INELIGIBLE plan
        (same atomic commit as the known dicts), and refs can only
        appear with a task-set change the gate already detects."""
        candidates: list = []
        plans: list = []
        for session in batch:
            nid = session.node_id
            if nid in hard or not self._vol_index_primed \
                    or nid in self._vol_pending_unpub:
                continue
            ch = session.tasks_channel
            if ch is not None and not ch.closed:
                continue
            plan = self._diffcols[stable_shard(nid, self.shards)] \
                .plan_for(nid, session.session_id, gate.col)
            if plan is None:
                continue
            candidates.append(session)
            plans.append(plan)
        if not plans:
            return batch
        clean, scanned = columnar_diff.gate_shard(gate, plans)
        self.metrics["diff_rows_scanned"] += scanned
        skip_ids = {s.node_id
                    for s, ok in zip(candidates, clean) if ok}
        if not skip_ids:
            return batch
        self.metrics["zero_delta_skips"] += len(skip_ids)
        skipped.update(skip_ids)
        return [s for s in batch if s.node_id not in skip_ids]

    def _serve_pool(self):
        """Lazy worker pool for multi-shard serves (only flushes where
        ≥2 shards have work ever reach it; single-shard dispatchers and
        single-shard flushes stay inline on the flush thread)."""
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._pool = ThreadPoolExecutor(
                max_workers=self.shards,
                thread_name_prefix="dispatcher-shard")
        return self._pool

    def _serve_shard(self, batch: list, served: set):
        """Serve one shard's slice of the flush: offer every session's
        diff, then merge the shard's known-state commits under ONE
        `dispatcher.lock` hold (the reverse reference maps stay global;
        the per-shard batch keeps the hold short and once-per-shard
        instead of once-per-session). `served` is an out-param so a
        mid-shard crash still reports the sessions whose offers landed —
        their commits run in the finally, because their agents DID see
        the message."""
        commits: list = []
        try:
            for session, view, driver_refs, plans in batch:
                commit = self._serve_session(session, view, driver_refs,
                                             plans)
                if commit is not None:
                    commits.append(commit)
                served.add(session.node_id)
        finally:
            if commits:
                with self._lock:
                    for commit in commits:
                        commit()

    def _serve_session(self, session: Session, view: tuple,
                       driver_refs: list, plans: list | None = None):
        """Build + offer one session's diff; returns the known-state
        commit closure when the message was delivered (the caller merges
        a whole shard's commits under one lock hold), None when the
        channel shed it."""
        tasks, secrets, configs, volumes, unpublish = view
        clone_ids, ship_bases = self._materialize_clones(
            session, secrets, driver_refs)
        msg, commit = self._diff(session, tasks, secrets, configs,
                                 volumes, unpublish, clone_ids, ship_bases,
                                 column_plan=plans[0] if plans else None)
        delivered = True
        if msg.changes:
            self._bump("ships", len(msg.changes))
            delivered = session.channel._offer(msg)
        # a closed channel (slow subscriber shed / racing disconnect)
        # must NOT advance known-state: the agent never saw this diff,
        # and a reconnect diffing from advanced state would miss
        # removals. The replacement session rebuilds from a COMPLETE.
        if session.tasks_channel is not None \
                and not session.tasks_channel.closed:
            # legacy stream: plain wire copies, no clone rewrite (the
            # pre-Assignments protocol never carried secrets)
            session.tasks_channel._offer(
                [self._ship_task(t, {}) for t in tasks])
        return commit if delivered else None

    def _diff(self, session: Session, tasks, secrets, configs, volumes,
              unpublish, clone_ids, ship_bases=None, column_plan=None):
        """Pure diff against the session's known maps: wire copies are
        made only for objects that actually ship (copy-on-ship). Returns
        the message plus a commit closure that publishes the new known
        state — run it ONLY once the message was delivered.

        `column_plan` is the columnar-diff plan captured alongside this
        view (ISSUE 16); the commit installs it under the same delivery
        gate that advances the known dicts. `dict_diffs` counts every
        walk through here — the zero-dict-walk acceptance guard reads
        it."""
        self._bump("dict_diffs")
        changes: list[Assignment] = []
        new_tasks = {t.id: t.meta.version.index for t in tasks}
        for t in tasks:
            old_version = session.known_tasks.get(t.id)
            if old_version is None or old_version != t.meta.version.index:
                changes.append(Assignment("update", "task",
                                          self._ship_task(t, clone_ids)))
        for tid in session.known_tasks:
            if tid not in new_tasks:
                changes.append(Assignment("remove", "task", tid))
        new_secrets = {sid: s.meta.version.index
                       for sid, s in secrets.items()}
        for sid, s in secrets.items():
            if session.known_secrets.get(sid) != s.meta.version.index:
                changes.append(Assignment("update", "secret",
                                          self._ship(s)))
        for sid in session.known_secrets:
            # single-pass removal detection (ISSUE 16): dict membership
            # against the fresh view, no throwaway set materialization —
            # this oracle path stays load-bearing under the parity fuzz
            if sid not in secrets:
                changes.append(Assignment("remove", "secret", sid))
        new_configs = {cid: c.meta.version.index
                       for cid, c in configs.items()}
        for cid, c in configs.items():
            if session.known_configs.get(cid) != c.meta.version.index:
                changes.append(Assignment("update", "config",
                                          self._ship(c)))
        for cid in session.known_configs:
            if cid not in configs:
                changes.append(Assignment("remove", "config", cid))
        for vid, v in volumes.items():
            if vid not in session.known_volumes:
                changes.append(Assignment("update", "volume", v))
        for vid in session.known_volumes:
            if vid in volumes:
                continue
            # prefer the assignment object when the volume is pending
            # node-unpublish so the agent can act without local state
            changes.append(Assignment("remove", "volume",
                                      unpublish.get(vid, vid)))
        for vid, va in unpublish.items():
            # re-send while pending, even if the agent was never told about
            # this volume in this session (agent restart)
            if vid not in session.known_volumes and vid not in volumes:
                changes.append(Assignment("remove", "volume", va))
        sequence = session.sequence + (1 if changes else 0)
        msg = AssignmentsMessage("incremental", sequence, changes)

        def commit():
            self._commit_known(session, new_tasks, new_secrets,
                               new_configs, set(volumes), sequence,
                               ship_bases, column_plan=column_plan)
            if self._record_shipped and lifecycle.enabled():
                # lifecycle plane: the SHIPPED leg, one batched record
                # per delivered diff (commit runs only once the agent
                # actually received the message). Only the FIRST ship
                # matters — a task re-ships on every version bump, so
                # filter to the ASSIGNED-state copy (later re-ships are
                # also rank-rejected by the recorder; this keeps the
                # batch small).
                shipped = [a.item.id for a in changes
                           if a.kind == "task" and a.action == "update"
                           and a.item.status.state == TaskState.ASSIGNED]
                if shipped:
                    lifecycle.record_batch(lifecycle.SHIPPED, shipped)

        return msg, commit

    # ------------------------------------------------------- status flushing
    def _flush_statuses(self):
        with self._status_cond:
            if not self._status_queue:
                return
            updates, self._status_queue = self._status_queue, []

        # de-dup: last status per (task, REPORTING node) wins — keying by
        # task alone would let a non-owner's entry clobber the owner's
        # legitimate status here, before the ownership check runs
        latest: dict[tuple[str, str], object] = {}
        for task_id, status, node_id in updates:
            latest[(task_id, node_id)] = status

        # lifecycle plane: statuses actually WRITTEN (ownership +
        # monotonicity passed) collect here and file as ONE batched
        # record after the store batch; disarmed, no list is ever built
        written: list[tuple] | None = [] if lifecycle.enabled() else None

        def cb(batch):
            for (task_id, node_id), status in latest.items():
                def update_one(tx, task_id=task_id, status=status,
                               node_id=node_id):
                    cur = tx.get_task(task_id)
                    if cur is None:
                        return
                    if cur.node_id != node_id:
                        # dispatcher.go:654: a node may only report tasks
                        # assigned to it — silently skip rather than let a
                        # rogue/buggy worker overwrite cluster-wide state
                        log.warning(
                            "dropping status for task %s from node %s "
                            "(assigned to %s)", task_id, node_id,
                            cur.node_id)
                        return
                    # monotonic: never lower observed state
                    if status.state < cur.status.state:
                        return
                    if cur.status.state < TaskState.RUNNING \
                            <= status.state and cur.meta.created_at:
                        # NEW→RUNNING scheduling delay (dispatcher.go:72-77)
                        _scheduling_delay.observe(
                            max(0.0, time.time() - cur.meta.created_at))
                    cur = cur.copy()
                    cur.status = status
                    tx.update(cur)
                    if written is not None:
                        written.append((task_id, status.state))
                batch.update(update_one)

        try:
            self.store.batch(cb)
        except Exception:
            # losing leadership mid-flush is routine (agents re-report
            # from their retry queues) — but LOG it: this bare swallow
            # once hid a NameError that dropped every status in the batch
            log.warning("status flush failed; statuses will be re-reported",
                        exc_info=True)
        else:
            if written:
                lifecycle.record_pairs(written)
