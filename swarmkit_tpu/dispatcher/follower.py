"""Lease-gated follower read plane (ISSUE 13).

Every agent session used to terminate on the raft leader; after the
scheduler/store ceilings fell (PRs 6/7/11), the one-leader fan-out was
the remaining serving ceiling. This plane lets a NON-leader manager
serve the read half of the worker protocol — Assignments/Tasks session
streams — from its own raft-replicated store, gated by the leader's
piggybacked read lease (raft/node.py `read_ok`; Raft dissertation §6.4
lease reads):

  * a follower serves a snapshot **no older than the leader's commit
    index at lease grant** and **only while the skew-discounted lease is
    live** — bounded-staleness reads, not linearizability;
  * the moment the lease dies (partition, leader loss, apply lag) the
    plane BOUNCES (`FollowerReadUnavailable` → the RPC layer's
    NotLeaderError redirect) and its incremental flushes hold; it never
    offers a message while stale past the bound;
  * status write-back (`update_task_status`) stays leader-only — the
    per-task node-ownership/de-dup contract in api/specs.py is untouched.

The snapshot/build machinery is LITERALLY the leader Dispatcher's: the
class aliases `_node_view` / `_diff` / `_commit_known` and their helpers
(see the class body), so the two serve paths cannot drift in what they
read or how they diff. The serve PROTOCOL around those shared calls
(snapshot → build → materialize → diff → offer → commit) lives in two
implementations — Dispatcher and this class — registered as the
`dispatcher-serve` mirror pair in analysis/mirror.py, which fails
tier-1 on a one-sided change.
"""
from __future__ import annotations

import logging
import threading
import time

from ..analysis.lockgraph import make_rlock
from ..api.objects import Config, Secret, Task, Volume
from ..store.watch import Channel
from ..utils.metrics import CounterDict
from .dispatcher import (
    ASSIGNMENTS_CHANNEL_LIMIT,
    BATCH_INTERVAL,
    Assignment,
    AssignmentsMessage,
    Dispatcher,
    DispatcherError,
    Session,
)

log = logging.getLogger("swarmkit_tpu.dispatcher.follower")


class FollowerReadUnavailable(DispatcherError):
    """This manager may not serve reads right now: it is not the leader
    and holds no live read lease (or has not applied the lease's commit
    index). The RPC layer translates this into its NotLeaderError so
    agents redirect to the leader."""


class FollowerReadPlane:
    """Read-only assignment serving on a non-leader manager.

    Per-node read sessions hold the same `Session` known-state the
    leader keeps, diffed by the same code; there is no registration, no
    liveness wheel, and no write-back — a read session's identity is the
    TLS-authenticated node id (the RPC layer enforces it), and a session
    id is deliberately absent (leader session ids name leader-side
    liveness state this plane does not have)."""

    # SLO legs are recorded where delivery is authoritative, on the
    # leader — the borrowed _diff's commit closure checks this flag, so
    # follower-served diffs never double-stamp SHIPPED (matching
    # _full_assignment below, which omits the leg for the same reason)
    _record_shipped = False

    # columnar diff gate (ISSUE 16): the follower plane never builds
    # plan stores — None makes the borrowed _commit_known /
    # _drop_session_refs install hooks no-op here (bounded-staleness
    # reads must not skip on leader-lockstep columns anyway)
    _diffcols = None

    def __init__(self, store, raft_node, secret_drivers=None, clock=None):
        from ..utils.clock import REAL_CLOCK

        self.store = store
        self.raft = raft_node
        self.secret_drivers = secret_drivers
        self.clock = clock or REAL_CLOCK
        self._lock = make_rlock("dispatcher.follower.lock")
        self._sessions: dict[str, Session] = {}
        self._dirty: set[str] = set()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # state the borrowed Dispatcher helpers read: the driver-clone
        # cache pair, the reverse reference maps _commit_known maintains,
        # and the (never-primed here) volume index — _pending_unpublish
        # takes its scan fallback on this plane
        self._driver_cache: dict[tuple, object] = {}
        self._clone_bases: dict[str, str] = {}
        self._secret_refs: dict[str, set[str]] = {}
        self._config_refs: dict[str, set[str]] = {}
        self._vol_index_primed = False
        self._vol_pending_unpub: dict[str, frozenset] = {}
        # CounterDict: internally-locked inc (ISSUE 15 — the metric
        # primitives own their atomicity; no ad-hoc guard locks here)
        self.metrics = CounterDict(
            {"reads_served": 0, "reads_bounced": 0,
             "flushes": 0, "flush_tx": 0, "held_flushes": 0,
             "ships": 0, "wire_copies": 0,
             # the borrowed _diff bumps this on every walk (ISSUE 16)
             "dict_diffs": 0})

    # ---- the shared snapshot/build vocabulary: the leader's own code.
    # These CANNOT drift from the Dispatcher — they are the same
    # function objects; the mirror pair pins the serve protocol AROUND
    # them (the methods defined below).
    _relevant_tasks = Dispatcher._relevant_tasks
    _volume_assignment = staticmethod(Dispatcher._volume_assignment)
    _referenced_deps = Dispatcher._referenced_deps
    _pending_unpublish = Dispatcher._pending_unpublish
    _node_view = Dispatcher._node_view
    _materialize_driver_secret = Dispatcher._materialize_driver_secret
    _materialize_clones = Dispatcher._materialize_clones
    _ship_task = Dispatcher._ship_task
    _ship = Dispatcher._ship
    _diff = Dispatcher._diff
    _commit_known = Dispatcher._commit_known
    _drop_session_refs = Dispatcher._drop_session_refs
    _bump = Dispatcher._bump

    # ------------------------------------------------------------ lease gate
    def read_ok(self) -> bool:
        """May this manager serve reads right now? Standalone (no raft)
        managers always may; raft-backed ones defer to the node's
        leader-or-live-lease verdict."""
        node = self.raft
        return node is None or node.read_ok()

    def _require_lease(self):
        if not self.read_ok():
            self._bump("reads_bounced")
            raise FollowerReadUnavailable(
                "not the leader and no live read lease; redirect to the "
                "leader")

    # ------------------------------------------------------------------- rpc
    def assignments(self, node_id: str) -> Channel:
        """Subscribe this node's lease-gated read stream: an immediate
        COMPLETE snapshot, then incremental diffs while the lease stays
        live (the same message shapes the leader serves)."""
        self._require_lease()
        session = Session(
            node_id=node_id, session_id="",
            channel=Channel(matcher=None,
                            limit=ASSIGNMENTS_CHANNEL_LIMIT))
        with self._lock:
            old = self._sessions.pop(node_id, None)
            if old is not None:
                self._drop_session_refs(old)
                old.channel.close()
                if old.tasks_channel is not None:
                    old.tasks_channel.close()
            self._sessions[node_id] = session
            msg = self._full_assignment(session)
            session.channel._offer(msg)
        self._bump("reads_served")
        return session.channel

    def tasks(self, node_id: str) -> Channel:
        """Lease-gated legacy Dispatcher.Tasks stream (wire parity with
        the leader's `tasks`)."""
        self._require_lease()
        with self._lock:
            session = self._sessions.get(node_id)
            if session is None:
                session = Session(
                    node_id=node_id, session_id="",
                    channel=Channel(matcher=None,
                                    limit=ASSIGNMENTS_CHANNEL_LIMIT))
                self._sessions[node_id] = session
            if session.tasks_channel is None:
                session.tasks_channel = Channel(matcher=None, limit=256)
            snapshot = self.store.view(
                lambda tx: [t.copy()
                            for t in self._relevant_tasks(tx, node_id)])
            session.tasks_channel._offer(snapshot)
        self._bump("reads_served")
        return session.tasks_channel

    # --------------------------------------------------------------- serving
    def _full_assignment(self, session: Session) -> AssignmentsMessage:
        """COMPLETE snapshot for a fresh read session — the follower
        mirror of Dispatcher._full_assignment (pair `dispatcher-serve`),
        minus the lifecycle SHIPPED leg: SLO legs are recorded where
        delivery is authoritative, on the leader."""
        driver_refs: list = []
        tasks, secrets, configs, volumes, unpublish = self.store.view(
            lambda tx: self._node_view(tx, session.node_id, driver_refs))
        clone_ids, ship_bases = self._materialize_clones(
            session, secrets, driver_refs)
        changes = (
            [Assignment("update", "task", self._ship_task(t, clone_ids))
             for t in tasks]
            + [Assignment("update", "secret", self._ship(s))
               for s in secrets.values()]
            + [Assignment("update", "config", self._ship(c))
               for c in configs.values()]
            + [Assignment("update", "volume", v) for v in volumes.values()]
            + [Assignment("remove", "volume", va)
               for vid, va in unpublish.items() if vid not in volumes]
        )
        self._bump("ships", len(changes))
        self._commit_known(
            session,
            {t.id: t.meta.version.index for t in tasks},
            {sid: s.meta.version.index for sid, s in secrets.items()},
            {cid: c.meta.version.index for cid, c in configs.items()},
            set(volumes), session.sequence + 1, ship_bases)
        return AssignmentsMessage("complete", session.sequence, changes)

    def _send_incrementals(self):
        """Flush the dirty read sessions — the follower mirror of the
        leader's flush: the lease gate runs FIRST (a dead lease holds
        the whole flush: nothing may be offered while the plane could be
        stale past the bound; dirt is kept for when the lease returns),
        then ONE store view builds every dirty session's node view, then
        each session is diffed/offered/committed in turn."""
        if not self.read_ok():
            with self._lock:
                if self._dirty:
                    self.metrics["held_flushes"] += 1
            return
        with self._lock:
            dirty, self._dirty = self._dirty, set()
            sessions = [self._sessions[n] for n in sorted(dirty)
                        if n in self._sessions]
        if not sessions:
            return
        self.metrics["flushes"] += 1
        views: list[tuple[Session, tuple, list]] = []

        def cb(tx):
            self.metrics["flush_tx"] += 1
            for session in sessions:
                driver_refs: list = []
                views.append((session,
                              self._node_view(tx, session.node_id,
                                              driver_refs),
                              driver_refs))

        served: set = set()
        try:
            self.store.view(cb)
            for session, view, driver_refs in views:
                self._serve_session(session, view, driver_refs)
                served.add(session.node_id)
        except Exception:
            with self._lock:
                self._dirty.update(s.node_id for s in sessions
                                   if s.node_id not in served)
            raise

    def _serve_session(self, session: Session, view: tuple,
                       driver_refs: list):
        """Diff + offer + commit one read session (the follower mirror
        of the leader's _serve_session; single-threaded plane, so the
        commit runs inline). A closed channel retires the session — the
        agent went away or moved to the leader."""
        tasks, secrets, configs, volumes, unpublish = view
        clone_ids, ship_bases = self._materialize_clones(
            session, secrets, driver_refs)
        msg, commit = self._diff(session, tasks, secrets, configs,
                                 volumes, unpublish, clone_ids, ship_bases)
        delivered = True
        if msg.changes:
            self._bump("ships", len(msg.changes))
            delivered = session.channel._offer(msg)
        if delivered:
            commit()
        elif session.channel.closed:
            with self._lock:
                if self._sessions.get(session.node_id) is session:
                    self._sessions.pop(session.node_id)
                    self._drop_session_refs(session)
            # close the session's OTHER stream too: a tasks()-only
            # subscriber whose (undrained) assignments channel shed must
            # see its legacy stream CLOSE — a silent stall would never
            # trigger the agent's resubscribe
            session.channel.close()
            if session.tasks_channel is not None:
                session.tasks_channel.close()
            return
        if session.tasks_channel is not None \
                and not session.tasks_channel.closed:
            session.tasks_channel._offer(
                [self._ship_task(t, {}) for t in tasks])

    # ------------------------------------------------------------ event plane
    def _note_event(self, ev):
        from ..api.objects import EventDelete

        obj = getattr(ev, "obj", None)
        with self._lock:
            live = self._sessions.keys()
            if isinstance(obj, Task):
                if isinstance(ev, EventDelete):
                    # the leader's purge, mirrored: a deleted task's
                    # driver-secret clones must not accrete (the
                    # per-version purge in _materialize_driver_secret
                    # never fires for deleted objects)
                    for key in [k for k in self._driver_cache
                                if k[2] == obj.id]:
                        del self._driver_cache[key]
                if obj.node_id and obj.node_id in live:
                    self._dirty.add(obj.node_id)
                old = getattr(ev, "old", None)
                if old is not None and old.node_id \
                        and old.node_id != obj.node_id \
                        and old.node_id in live:
                    self._dirty.add(old.node_id)
            elif isinstance(obj, Secret):
                if isinstance(ev, EventDelete):
                    for key in [k for k in self._driver_cache
                                if k[0] == obj.id]:
                        del self._driver_cache[key]
                self._dirty.update(
                    self._secret_refs.get(obj.id, set()) & live)
            elif isinstance(obj, Config):
                self._dirty.update(
                    self._config_refs.get(obj.id, set()) & live)
            elif isinstance(obj, Volume):
                touched = {st.node_id for st in obj.publish_status}
                old = getattr(ev, "old", None)
                if old is not None:
                    touched |= {st.node_id for st in old.publish_status}
                self._dirty.update(touched & live)

    def start(self):
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="dispatcher-follower")
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        with self._lock:
            for s in self._sessions.values():
                s.channel.close()
                if s.tasks_channel is not None:
                    s.tasks_channel.close()
            self._sessions.clear()
            self._secret_refs.clear()
            self._config_refs.clear()
            self._clone_bases.clear()
            self._dirty.clear()

    def _run(self):
        kinds = frozenset(("task", "secret", "config", "volume"))

        def matcher(ev, _kinds=kinds):
            obj = getattr(ev, "obj", None)
            return obj is not None and obj.TABLE in _kinds

        _, ch = self.store.view_and_watch(lambda tx: None,
                                          matcher=matcher, limit=None)
        last_flush = time.monotonic()
        try:
            while not self._stop.is_set():
                try:
                    ev = ch.get(timeout=BATCH_INTERVAL / 2)
                except TimeoutError:
                    ev = None
                except Exception:
                    return
                if ev is not None:
                    self._note_event(ev)
                now = time.monotonic()
                if now - last_flush >= BATCH_INTERVAL:
                    try:
                        self._send_incrementals()
                    except Exception:
                        log.warning("follower read flush failed; dirty "
                                    "sessions retained for retry",
                                    exc_info=True)
                    last_flush = now
        finally:
            self.store.queue.stop_watch(ch)
