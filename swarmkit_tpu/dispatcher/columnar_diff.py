"""Columnar assignment-diff gate (ISSUE 16 tentpole).

The per-session assignment diff (`Dispatcher._diff`) walks Python
`known_*` dicts — O(sessions x known-entries) dict gets per flush. This
module makes the steady case array-native without forking the wire
format: per shard, each session's delivery-committed known state is
kept as dense numpy columns (store row indices + the versions actually
shipped, captured INSIDE the flush's store view so they are mutually
consistent), and every flush runs ONE vectorized pass per shard that
proves which dirty sessions have a ZERO delta against the live columnar
task/secret/config tables. Proven-zero sessions skip the node view, the
dict diff, and the serve entirely; everything else falls through to the
existing dict `_diff`, which stays the SOLE shipping path — so wire
parity with the dict oracle holds by construction, and a false
POSITIVE (gate says maybe-changed, diff finds nothing) costs one dict
walk while a false NEGATIVE would be a correctness bug (the parity fuzz
in tests/test_dispatcher_fanout.py hunts those).

Soundness sketch (the exactness argument for the skip verdict):

* task leg — a known entry (row r, version v) is OK iff the row is
  still a live relevant task (`valid & state>=ASSIGNED &
  desired<=REMOVE`), its version still equals v, and it still sits on
  the session's node. Known ids are distinct, so OK entries are
  DISTINCT current-relevant rows of that node; if additionally the OK
  count equals the node's current relevant-task count, the known set
  EQUALS the current set with identical versions — no updates, no
  additions, no removals. Row recycling is safe because object versions
  are a store-global monotone counter: a recycled row carries a version
  strictly newer than any version captured before the delete, so it can
  only mismatch (dirty), never falsely match.
* dep leg — task specs are immutable per task, so an unchanged known
  task set implies unchanged referenced dep IDS; only dep version bumps
  and deletions matter, and both flip the (never-recycled) dep row's
  version/valid columns. Referenced-but-ABSENT deps are silently
  dropped by the build, so each plan records those ids and the gate
  re-checks `row_of(id)` — a dep created later produces no event for
  this session, and skipping would hide the resolved reference from the
  next soft-dirty serve.
* everything the columns cannot see arrives via the HARD dirty channel
  (volume events, external test/operator marks, crash re-dirty) or is
  excluded by the eligibility checks in `Dispatcher._gate_shard`
  (driver-secret clones, pending node-unpublish re-sends, an open
  legacy tasks stream, a session whose plan token is stale).

Lockstep rule (the dict contract, columnar): a plan is installed ONLY
by the delivery-gated `_commit_known` — columns advance exactly when
the known dicts do, never past what the agent saw. The per-shard plan
store takes a LEAF lock named `dispatcher.diffcol<i>.lock`:
deliberately OUTSIDE the lockgraph hazard key set (`dispatcher.lock`,
`dispatcher.follower.lock`, the `dispatcher.shard` prefix) because the
gate reads plans INSIDE store-view callbacks where taking any of those
would recreate the PR 4 inversion. Edges: dispatcher.lock -> diffcol
(commit installs), store.lock -> diffcol (gate reads in-view); the
diffcol lock never acquires anything, so no cycle is possible.

SWARMKIT_TPU_NO_COLUMNAR_DIFF=1 disables the plane (the dispatcher
serves every dirty session through the dict path, exactly as before);
a store without a columnar mirror (SWARMKIT_TPU_NO_COLUMNAR=1) disables
it implicitly.
"""
from __future__ import annotations

import os

import numpy as np

from ..analysis.lockgraph import make_lock
from ..api.types import TaskState
from ..store.columnar import IdVocab

_ASSIGNED = int(TaskState.ASSIGNED)
_REMOVE = int(TaskState.REMOVE)


def plane_enabled() -> bool:
    return os.environ.get("SWARMKIT_TPU_NO_COLUMNAR_DIFF", "") != "1"


class ColumnPlan:
    """One session's known-state image as store-row columns: what the
    delivered assignment message implies the agent now knows, expressed
    as (row, version) pairs against the store's columnar mirrors plus
    the referenced-but-absent dep ids. Captured inside a store view
    (`Dispatcher._node_view`), installed only by the delivery-gated
    commit, and immutable afterwards — the gate may read it without the
    plan store's lock held."""

    __slots__ = ("col", "token", "node_srow", "task_rows", "task_vers",
                 "secret_rows", "secret_vers", "config_rows",
                 "config_vers", "missing_secrets", "missing_configs",
                 "eligible")

    @classmethod
    def capture(cls, col, token: str, node_id: str, tasks, secrets,
                configs, missing, had_driver_refs: bool) -> "ColumnPlan":
        """Build the plan from one node view's results. `col` is the
        live ColumnarTasks the view read under the store lock; a plan
        is only ever compared against the SAME object (identity-gated),
        so a store restore() that swaps the mirror orphans every
        outstanding plan instead of comparing against re-assigned
        rows."""
        p = cls()
        p.col = col
        p.token = token
        p.eligible = not had_driver_refs
        p.node_srow = col.nodes.lookup(node_id)
        if p.node_srow <= 0:
            p.eligible = False
        p.task_rows, p.task_vers = _task_entries(col, tasks, p)
        p.secret_rows, p.secret_vers = _dep_entries(
            col.secret_cols, secrets, p)
        p.config_rows, p.config_vers = _dep_entries(
            col.config_cols, configs, p)
        p.missing_secrets = tuple(
            i for kind, i in missing if kind == "secret")
        p.missing_configs = tuple(
            i for kind, i in missing if kind == "config")
        return p


def _task_entries(col, tasks, plan: ColumnPlan):
    n = len(tasks)
    rows = np.empty(n, np.int64)
    vers = np.empty(n, np.int64)
    for j, t in enumerate(tasks):
        r = col.task_row(t.id)
        if r < 0:
            # task not mirrored (shouldn't happen in lockstep, but a
            # mid-lazy-wave read could race the heal): untrackable
            plan.eligible = False
            r = 0
        rows[j] = r
        vers[j] = t.meta.version.index
    return rows, vers


def _dep_entries(dep, objs: dict, plan: ColumnPlan):
    n = len(objs)
    rows = np.empty(n, np.int64)
    vers = np.empty(n, np.int64)
    for j, (oid, o) in enumerate(objs.items()):
        r = dep.row_of(oid)
        if r < 0:
            # a store object the mirror doesn't carry (e.g. a rebuild
            # that predates the dep mirrors): untrackable, serve dict
            plan.eligible = False
            r = 0
        rows[j] = r
        vers[j] = o.meta.version.index
    return rows, vers


class GateContext:
    """Per-flush shared gate state, computed ONCE under the flush's
    store view: the relevance mask (exactly `_relevant_tasks`'
    predicate, vectorized) and the per-node relevant-task counts every
    shard's pass compares against."""

    __slots__ = ("col", "rel", "node_counts")

    def __init__(self, col):
        self.col = col
        self.rel = (col.valid
                    & (col.state >= _ASSIGNED)
                    & (col.desired <= _REMOVE))
        self.node_counts = np.bincount(
            col.node_idx[self.rel], minlength=len(col.nodes))


def gate_shard(ctx: GateContext, plans: list) -> tuple[np.ndarray, int]:
    """THE vectorized pass: one shard's eligible plans against the live
    columns. Returns (clean, rows_scanned) where clean[j] is True iff
    session j provably has a zero delta. Every plan must be eligible
    and identity-bound to ctx.col (the caller's `plan_for` enforces
    both) — row indices are then in-bounds by construction (vocabs only
    grow, task rows < len(ids), dep rows never recycle)."""
    n = len(plans)
    clean = np.ones(n, bool)
    col = ctx.col
    node_srow = np.fromiter((p.node_srow for p in plans), np.int64, n)
    scanned = 0

    # --- task leg: every known entry must be an unchanged relevant
    # task still on the session's node, and the per-node relevant count
    # must match (count equality over distinct rows == set equality)
    lengths = np.fromiter((p.task_rows.size for p in plans), np.int64, n)
    total = int(lengths.sum())
    scanned += total
    c_ok = np.zeros(n, np.int64)
    if total:
        srow = np.concatenate([p.task_rows for p in plans])
        kver = np.concatenate([p.task_vers for p in plans])
        esess = np.repeat(np.arange(n), lengths)
        ok = (ctx.rel[srow]
              & (col.version[srow] == kver)
              & (col.node_idx[srow] == np.repeat(node_srow, lengths)))
        clean &= np.bincount(esess[~ok], minlength=n) == 0
        c_ok = np.bincount(esess[ok], minlength=n)
    clean &= c_ok == ctx.node_counts[node_srow]

    # --- dep legs: unchanged task set => unchanged referenced ids
    # (specs are immutable per task), so only version/liveness of the
    # captured rows can differ
    for rows_attr, vers_attr, dep in (
            ("secret_rows", "secret_vers", col.secret_cols),
            ("config_rows", "config_vers", col.config_cols)):
        lengths = np.fromiter(
            (getattr(p, rows_attr).size for p in plans), np.int64, n)
        total = int(lengths.sum())
        scanned += total
        if not total:
            continue
        srow = np.concatenate([getattr(p, rows_attr) for p in plans])
        kver = np.concatenate([getattr(p, vers_attr) for p in plans])
        esess = np.repeat(np.arange(n), lengths)
        ok = dep.valid[srow] & (dep.version[srow] == kver)
        clean &= np.bincount(esess[~ok], minlength=n) == 0

    # --- missing refs: a dep created AFTER it was referenced produces
    # no event for this session; re-check resolution per flush.
    # O(missing) scalar — the set is almost always empty.
    for j, p in enumerate(plans):
        if not clean[j]:
            continue
        if any(col.secret_cols.row_of(i) >= 0 for i in p.missing_secrets) \
                or any(col.config_cols.row_of(i) >= 0
                       for i in p.missing_configs):
            clean[j] = False
    return clean, scanned


class ShardDiffColumns:
    """One shard's plan store: session node ids intern into a vocab and
    map to their delivery-committed ColumnPlan. The lock is a strict
    LEAF (see the module docstring's lock-order argument); plans are
    immutable, so `plan_for` hands the object out and drops the lock."""

    def __init__(self, index: int):
        self.lock = make_lock(f"dispatcher.diffcol{index}.lock")
        self.vocab = IdVocab()
        self._plans: dict[str, ColumnPlan] = {}

    def install(self, node_id: str, plan: ColumnPlan) -> None:
        with self.lock:
            self.vocab.intern(node_id)
            self._plans[node_id] = plan

    def invalidate(self, node_id: str) -> None:
        with self.lock:
            self._plans.pop(node_id, None)

    def clear(self) -> None:
        with self.lock:
            self._plans.clear()

    def plan_for(self, node_id: str, token: str, col) -> ColumnPlan | None:
        """The session's live plan, or None when untracked: no plan,
        marked ineligible at capture, a stale session token (the plan
        belongs to a superseded session), or captured against a
        columnar mirror that has since been swapped (store restore)."""
        with self.lock:
            p = self._plans.get(node_id)
        if p is None or not p.eligible or p.token != token \
                or p.col is not col:
            return None
        return p

    def __len__(self) -> int:
        with self.lock:
            return len(self._plans)
