"""Per-node heartbeat expiry (reference: manager/dispatcher/heartbeat/heartbeat.go).

Two implementations share the contract "fire `on_expire` once if the
entry isn't beaten within its timeout":

* `Heartbeat` — one timer object per entry, cancel-and-re-arm per beat.
  The original shape; kept as the ORACLE for the wheel's property tests
  and for the dispatcher's rare timers (leadership-grace, orphaning),
  where one object per node-down event is the right cost.
* `HeartbeatWheel` — the dispatcher's session liveness plane: one
  coarse-bucketed wheel for every session, driven by a single repeating
  clock ticker. `beat()` is a few dict/set writes and allocates no timer
  objects, so 10k sessions beating every ~5s cost the shared TimerWheel
  nothing (the per-beat cancel/heap-push of `Heartbeat` was the
  `beat_per_s` ceiling in bench_host_micro).

Timers come from an injectable Clock (utils/clock.py) so the expiry logic
is deterministic under FakeClock in tests, mirroring the reference's
ClockSource seam."""
from __future__ import annotations

import threading
import time
import zlib
from typing import Callable, Hashable

from ..analysis.lockgraph import make_lock
from ..utils import trace
from ..utils.clock import REAL_CLOCK


def stable_shard(key: Hashable, n: int) -> int:
    """Stable key→shard assignment shared by the dispatcher's flush
    shards and the heartbeat wheel slices (ISSUE 13). crc32, NOT the
    salted builtin hash: the same node id must land on the same shard
    across process restarts and across the wheel/dirty-set planes."""
    if n <= 1:
        return 0
    if isinstance(key, str):
        return zlib.crc32(key.encode("utf-8", "surrogatepass")) % n
    if isinstance(key, bytes):
        return zlib.crc32(key) % n
    return hash(key) % n


class Heartbeat:
    """Fires `on_expire` once if `beat()` isn't called within `timeout`."""

    def __init__(self, timeout: float, on_expire: Callable[[], None],
                 clock=None):
        self.timeout = timeout
        self.on_expire = on_expire
        self.clock = clock or REAL_CLOCK
        self._timer = None
        self._lock = make_lock('dispatcher.heartbeat.timer')
        self._stopped = False

    def start(self):
        self.beat()

    def beat(self, timeout: float | None = None):
        if timeout is not None:
            self.timeout = timeout
        with self._lock:
            if self._stopped:
                return
            if self._timer is not None:
                self._timer.cancel()
            self._timer = self.clock.timer(self.timeout, self._expire)

    def _expire(self):
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
        self.on_expire()

    def stop(self):
        with self._lock:
            self._stopped = True
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None


class HeartbeatWheel:
    """Coarse-bucketed expiry wheel for many keyed heartbeats.

    Entries are bucketed by deadline quantized UP to the next
    `granularity` boundary, and one repeating ticker (re-armed every
    `granularity` while entries exist, stopped when empty) fires every
    bucket whose boundary has passed. So expirations are never EARLY and
    at most ~2×granularity late — callers size their grace windows with
    that slack (the dispatcher keeps granularity ≤ min(ε, period/2)
    against a period×3 grace, so the margin stays ≥ 2×period).

    `beat()` moves the entry between buckets: dict/set writes only, no
    timer objects, no heap traffic — the steady-state cost the 10k-node
    design point demands. Deterministic under FakeClock: the ticker is a
    plain clock timer, so `advance()` fires it and one tick drains every
    bucket that came due during the whole advance.
    """

    def __init__(self, granularity: float = 0.25, clock=None):
        if granularity <= 0:
            raise ValueError("granularity must be positive")
        self.clock = clock or REAL_CLOCK
        self._granularity = granularity
        self._lock = make_lock('dispatcher.heartbeat.wheel')
        self._timeout: dict[Hashable, float] = {}
        self._deadline: dict[Hashable, float] = {}
        self._cb: dict[Hashable, Callable[[], None]] = {}
        self._bucket_of: dict[Hashable, int] = {}
        self._buckets: dict[int, set] = {}
        self._ticker = None
        # generation guard: a _tick whose arming generation was
        # superseded (remove-to-empty then add re-armed while the fire
        # was in flight) must not null/re-arm over the live ticker
        self._ticker_gen = 0
        self._stopped = False
        self.ticks = 0              # observability: ticker fires
        self.fired = 0              # observability: expirations delivered

    def __len__(self):
        with self._lock:
            return len(self._timeout)

    @property
    def granularity(self) -> float:
        return self._granularity

    def set_granularity(self, granularity: float) -> None:
        """Re-bucket every entry under a new tick width (live heartbeat
        period reconfig). Bucket indexes are granularity-relative, so a
        change must rebuild placements — never mix indexes across
        widths."""
        if granularity <= 0:
            raise ValueError("granularity must be positive")
        with self._lock:
            if granularity == self._granularity:
                return
            self._granularity = granularity
            self._buckets.clear()
            self._bucket_of.clear()
            for key, due in self._deadline.items():
                self._place(key, due)

    # ------------------------------------------------------------ entries
    def add(self, key: Hashable, timeout: float,
            on_expire: Callable[[], None]) -> None:
        """Arm (or replace) `key`. Replacement swaps the callback too —
        a superseding session takes over its node's liveness entry."""
        with self._lock:
            if self._stopped:
                return             # dispatcher stopped: liveness is off
            self._timeout[key] = timeout
            self._cb[key] = on_expire
            due = self.clock.monotonic() + timeout
            self._deadline[key] = due
            self._place(key, due)
            if self._ticker is None:
                self._arm_ticker()

    def beat(self, key: Hashable, timeout: float | None = None) -> bool:
        """Push `key`'s deadline out; returns False if the entry already
        expired or was removed (the caller's session is gone). THE hot
        path: dict writes and at most one set move, nothing allocated."""
        with self._lock:
            if self._stopped or key not in self._timeout:
                return False
            if timeout is not None:
                self._timeout[key] = timeout
            due = self.clock.monotonic() + self._timeout[key]
            self._deadline[key] = due
            self._place(key, due)
            return True

    def remove(self, key: Hashable) -> None:
        with self._lock:
            if key not in self._timeout:
                return
            self._drop(key)
            if not self._timeout and self._ticker is not None:
                self._ticker.cancel()
                self._ticker = None

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            if self._ticker is not None:
                self._ticker.cancel()
                self._ticker = None
            self._timeout.clear()
            self._deadline.clear()
            self._cb.clear()
            self._bucket_of.clear()
            self._buckets.clear()

    # ------------------------------------------------------------ internals
    def _place(self, key: Hashable, due: float) -> None:
        # quantize UP: bucket b fires once now >= b*g, so an entry never
        # expires before its deadline
        b = int(due / self._granularity) + 1
        old = self._bucket_of.get(key)
        if old == b:
            return
        if old is not None:
            s = self._buckets.get(old)
            if s is not None:
                s.discard(key)
                if not s:
                    del self._buckets[old]
        self._buckets.setdefault(b, set()).add(key)
        self._bucket_of[key] = b

    def _drop(self, key: Hashable) -> None:
        self._timeout.pop(key, None)
        self._deadline.pop(key, None)
        self._cb.pop(key, None)
        b = self._bucket_of.pop(key, None)
        if b is not None:
            s = self._buckets.get(b)
            if s is not None:
                s.discard(key)
                if not s:
                    del self._buckets[b]

    def _arm_ticker(self) -> None:
        # under self._lock
        self._ticker_gen += 1
        gen = self._ticker_gen
        self._ticker = self.clock.timer(self._granularity,
                                        lambda: self._tick(gen))

    @property
    def bucket_count(self) -> int:
        """Live bucket count (the /metrics gauge next to len(self))."""
        with self._lock:
            return len(self._buckets)

    def _tick(self, gen: int) -> None:
        # trace plane: one span per ticker fire, never per beat (beat()
        # stays dict-writes-only); disarmed = one truthiness test
        traced = trace.enabled()
        t0 = time.perf_counter() if traced else 0.0
        fire: list[tuple[Hashable, Callable[[], None]]] = []
        with self._lock:
            if gen != self._ticker_gen or self._stopped:
                return             # superseded arming — a live ticker owns
            self._ticker = None
            self.ticks += 1
            now = self.clock.monotonic()
            g = self._granularity
            for b in [b for b in self._buckets if b * g <= now]:
                for key in list(self._buckets.get(b, ())):
                    due = self._deadline.get(key)
                    if due is None:
                        continue
                    if due <= now:
                        fire.append((key, self._cb[key]))
                        self._drop(key)
                    else:
                        # a beat raced the tick: the entry moved forward
                        # but its bucket record lagged — re-place it
                        self._place(key, due)
            if self._timeout:
                self._arm_ticker()
        for _key, cb in fire:
            self.fired += 1
            try:
                cb()
            except BaseException as exc:   # noqa: BLE001
                # one crashing expiry handler must not swallow the rest
                # of the batch (their entries are already dropped);
                # surface it exactly like a crashing timer thread so the
                # conftest guard still fails the suite on it
                threading.excepthook(threading.ExceptHookArgs(
                    (type(exc), exc, exc.__traceback__,
                     threading.current_thread())))
        if traced:
            trace.rec("hb.wheel.tick", time.perf_counter() - t0,
                      fired=len(fire), entries=len(self))


class ShardedHeartbeatWheel:
    """P independent `HeartbeatWheel`s, one per dispatcher shard
    (ISSUE 13): a key's liveness entry lives on the wheel picked by the
    SAME `stable_shard` hash the dispatcher uses for its dirty sets, so
    one shard's beat storm contends only on its own wheel lock and
    ticker. With shards=1 this is a transparent wrapper around a single
    wheel (the pre-sharding shape).

    The contract is the wheel's own: never-early, ≤ ~2×granularity-late
    expirations, beat() = dict/set writes, no timer objects on the
    steady path. Aggregate observability (`len`, `bucket_count`,
    `ticks`, `fired`) sums the slices."""

    def __init__(self, granularity: float = 0.25, clock=None,
                 shards: int = 1):
        self.wheels = [HeartbeatWheel(granularity=granularity, clock=clock)
                       for _ in range(max(1, int(shards)))]

    def _of(self, key: Hashable) -> HeartbeatWheel:
        return self.wheels[stable_shard(key, len(self.wheels))]

    def add(self, key: Hashable, timeout: float,
            on_expire: Callable[[], None]) -> None:
        self._of(key).add(key, timeout, on_expire)

    def beat(self, key: Hashable, timeout: float | None = None) -> bool:
        return self._of(key).beat(key, timeout)

    def remove(self, key: Hashable) -> None:
        self._of(key).remove(key)

    def stop(self) -> None:
        for w in self.wheels:
            w.stop()

    def set_granularity(self, granularity: float) -> None:
        for w in self.wheels:
            w.set_granularity(granularity)

    @property
    def granularity(self) -> float:
        return self.wheels[0].granularity

    def __len__(self) -> int:
        return sum(len(w) for w in self.wheels)

    @property
    def bucket_count(self) -> int:
        return sum(w.bucket_count for w in self.wheels)

    @property
    def ticks(self) -> int:
        return sum(w.ticks for w in self.wheels)

    @property
    def fired(self) -> int:
        return sum(w.fired for w in self.wheels)

    def __getattr__(self, name: str):
        # single-shard debug/back-compat surface (tests drive the ticker
        # via _tick/_ticker_gen): delegate unknown attributes to slice 0
        return getattr(self.wheels[0], name)
