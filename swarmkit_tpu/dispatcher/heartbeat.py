"""Per-node heartbeat timer (reference: manager/dispatcher/heartbeat/heartbeat.go).

Timers come from an injectable Clock (utils/clock.py) so the expiry logic
is deterministic under FakeClock in tests, mirroring the reference's
ClockSource seam."""
from __future__ import annotations

import threading
from typing import Callable

from ..utils.clock import REAL_CLOCK


class Heartbeat:
    """Fires `on_expire` once if `beat()` isn't called within `timeout`."""

    def __init__(self, timeout: float, on_expire: Callable[[], None],
                 clock=None):
        self.timeout = timeout
        self.on_expire = on_expire
        self.clock = clock or REAL_CLOCK
        self._timer = None
        self._lock = threading.Lock()
        self._stopped = False

    def start(self):
        self.beat()

    def beat(self, timeout: float | None = None):
        if timeout is not None:
            self.timeout = timeout
        with self._lock:
            if self._stopped:
                return
            if self._timer is not None:
                self._timer.cancel()
            self._timer = self.clock.timer(self.timeout, self._expire)

    def _expire(self):
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
        self.on_expire()

    def stop(self):
        with self._lock:
            self._stopped = True
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
