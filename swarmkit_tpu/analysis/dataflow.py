"""Flow-sensitive dataflow contract engine (ISSUE 12).

PR 8's lint rules are per-statement heuristics; the contracts that
actually guard parity are *flow* properties — "a store object tainted
here must be copied before a write reaches it THERE", "an unmarked
NodeInfo mutation is invisible to the tracked encoder unless a mark
lands on every path through it", "every drain trigger barriers before
it touches wave state". This module compiles those CLAUDE.md contracts
into dataflow rules over a per-function control-flow graph:

  * `CFG` — statement-level CFG built from the AST: if/else joins,
    for/while back edges (break/continue handled), try bodies with
    conservative edges into their handlers, finally on all paths,
    with-blocks linear. One synthetic entry and exit per function.
  * a forward taint engine (worklist fixpoint) whose lattice is
    per-name tags {OBJ: live store object, CONT: container holding
    live objects}; merge at joins is set-union (a MAY analysis: taint
    on any incoming path survives). Aliases propagate through plain
    assignment, attribute reads off a tainted base, tuple unpacking,
    container append/element reads, and loop iteration; `.copy()`,
    `copy.deepcopy` and `dataclasses.replace` sanitize. Call
    boundaries use the curated summary table below (`CALL_SUMMARIES`)
    — anything unknown returns clean (the engine under-approximates
    across calls on purpose; in-function flows are the bug class PR 8
    documented as blind spots).
  * path queries for the ordering rules: "does a mark-free path from
    entry reach this site AND a mark-free path from this site reach
    exit" (dirty-feed) and "does any barrier-free path from entry
    reach a wave-state read" (barrier-before-drain).

Three rules ride the engine (registered into the lint driver via
`lint.all_rules()`):

  `store-copy-dataflow`   flow- and alias-sensitive copy-before-mutate
                          (supersedes PR 8's linear-scan rule): catches
                          the append/loop-write shape (collect live
                          objects into a list, mutate them in a later
                          loop), tuple unpacking, attribute aliasing
                          (`st = t.status; st.state = X`), and clears
                          taint only on real sanitizers — a `.copy()`
                          on ONE alias does not clean the others.
  `dirty-feed`            the round-6 tracked-encoder contract: every
                          NodeInfo mutator call in the Scheduler's
                          event/tick paths must have a mark-feed call
                          (`mark_numeric`/`mark_replaced`/
                          `mark_node_set_changed`/restamp/poison) on
                          EVERY path through the mutation; the
                          `if info.add_task(t): mark_numeric(info)`
                          idiom is recognized (the mutation only
                          happened on the true branch). The wave-commit
                          path is whitelisted (restamp reconciles it).
  `barrier-before-drain`  the async-commit-plane contract, in BOTH
                          mirrored tick implementations: from each
                          curated drain-trigger entry point, every CFG
                          path must take a commit-plane barrier before
                          its first read of wave state (or, for the
                          terminal drains, must pass a barrier on every
                          path to exit). `barrier_coverage()` lets the
                          tier-1 gate pin that the curated entry points
                          still exist — a rename must not silently
                          disable the rule.

Suppression uses the ordinary pragma syntax (`# lint: allow(<rule>)`).
Adding a dataflow rule: build on `CFG`/`TaintAnalysis`/`path queries`
here, register in `RULES` at the bottom, add must-fire AND
must-not-fire fixtures in tests/test_analysis.py, and document it in
docs/static_analysis.md (the dataflow-engine section).
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Iterator

from .lint import Finding, Module, Rule, _attr_chain

# =====================================================================
# CFG
# =====================================================================


@dataclass
class CFGNode:
    """One statement (or synthetic entry/exit). `stmt` is the ast
    statement; branch heads (If/While/For/Try) appear as their own
    nodes whose successors are the branch arms, and their BODY
    statements are separate nodes — `stmt` for a branch head covers
    only the test/iter expression."""

    idx: int
    stmt: ast.stmt | None
    kind: str                      # "entry" | "exit" | "stmt" | "head"
    succs: list[int] = field(default_factory=list)
    preds: list[int] = field(default_factory=list)


class CFG:
    """Statement-level CFG of ONE function body (nested defs are NOT
    inlined — each gets its own CFG; a Lambda/def statement is an
    ordinary statement node here)."""

    def __init__(self, fn: ast.FunctionDef | ast.AsyncFunctionDef):
        self.fn = fn
        self.nodes: list[CFGNode] = []
        self.entry = self._new(None, "entry")
        self.exit = self._new(None, "exit")
        # loop stack: (head_idx, break_targets list) for continue/break
        self._loops: list[tuple[int, list[int]]] = []
        # enclosing finalbody statement lists (innermost last): an
        # abrupt exit (Return/Raise) threads INLINE CLONES of these
        # before reaching exit, so a mark/barrier in a finally is seen
        # on the abrupt path too (statement nodes are positional — the
        # clones share the same ast objects; rules dedupe by identity)
        self._finallies: list[list] = []
        tails = self._build(fn.body, [self.entry.idx])
        self._link(tails, self.exit.idx)

    # ------------------------------------------------------------ helpers
    def _new(self, stmt, kind: str) -> CFGNode:
        node = CFGNode(len(self.nodes), stmt, kind)
        self.nodes.append(node)
        return node

    def _link(self, preds: list[int], succ: int) -> None:
        for p in preds:
            if succ not in self.nodes[p].succs:
                self.nodes[p].succs.append(succ)
                self.nodes[succ].preds.append(p)

    # ------------------------------------------------------------- builder
    def _build(self, stmts, preds: list[int]) -> list[int]:
        """Thread `stmts` after `preds`; returns the fall-through
        tails (empty when every path returned/raised/broke)."""
        cur = preds
        for s in stmts:
            if not cur:
                # unreachable code after a return/raise: still give it
                # nodes (rules may want the sites) but leave it dangling
                cur = []
            if isinstance(s, ast.If):
                head = self._new(s, "head")
                self._link(cur, head.idx)
                body_tails = self._build(s.body, [head.idx])
                if s.orelse:
                    else_tails = self._build(s.orelse, [head.idx])
                else:
                    else_tails = [head.idx]
                cur = body_tails + else_tails
            elif isinstance(s, (ast.While, ast.For, ast.AsyncFor)):
                head = self._new(s, "head")
                self._link(cur, head.idx)
                breaks: list[int] = []
                self._loops.append((head.idx, breaks))
                body_tails = self._build(s.body, [head.idx])
                self._loops.pop()
                self._link(body_tails, head.idx)      # back edge
                else_tails = (self._build(s.orelse, [head.idx])
                              if s.orelse else [head.idx])
                cur = else_tails + breaks
            elif isinstance(s, (ast.Try, getattr(ast, "TryStar", ast.Try))):
                body_entry = self._new(s, "head")
                self._link(cur, body_entry.idx)
                # abrupt exits inside body/handlers/else must thread
                # this finalbody (popped again before the normal-flow
                # finalbody build below — a return IN a finally runs
                # only the OUTER finallies)
                self._finallies.append(list(s.finalbody))
                body_tails = self._build(s.body, [body_entry.idx])
                # conservative: an exception may fire after ANY body
                # statement — every body node can jump to each handler
                body_nodes = [n.idx for n in self.nodes
                              if n.idx > body_entry.idx
                              and n.kind != "exit"]
                handler_tails: list[int] = []
                for h in s.handlers:
                    h_entry = self._new(h, "head")
                    self._link([body_entry.idx], h_entry.idx)
                    for bn in body_nodes:
                        if bn < h_entry.idx:
                            self._link([bn], h_entry.idx)
                    handler_tails += self._build(h.body, [h_entry.idx])
                else_tails = (self._build(s.orelse, body_tails)
                              if s.orelse else body_tails)
                pre_finally = else_tails + handler_tails
                self._finallies.pop()
                if s.finalbody:
                    cur = self._build(s.finalbody, pre_finally or cur)
                else:
                    cur = pre_finally
            elif isinstance(s, (ast.With, ast.AsyncWith)):
                head = self._new(s, "head")
                self._link(cur, head.idx)
                cur = self._build(s.body, [head.idx])
            elif isinstance(s, (ast.Return, ast.Raise)):
                node = self._new(s, "stmt")
                self._link(cur, node.idx)
                # thread enclosing finally bodies (innermost first)
                # before exit: a mark/barrier in a finally IS executed
                # on this abrupt path. Inline clones — loop/finally
                # stacks are snapshot-restored so the clone build can't
                # leak break/continue targets into the outer walk.
                tails = [node.idx]
                pending = list(self._finallies)
                saved_fin, saved_loops = self._finallies, self._loops
                self._finallies, self._loops = [], []
                for fb in reversed(pending):
                    tails = self._build(fb, tails)
                    if not tails:
                        break       # the finally itself exits abruptly
                self._finallies, self._loops = saved_fin, saved_loops
                self._link(tails, self.exit.idx)
                cur = []
            elif isinstance(s, ast.Break):
                node = self._new(s, "stmt")
                self._link(cur, node.idx)
                if self._loops:
                    self._loops[-1][1].append(node.idx)
                cur = []
            elif isinstance(s, ast.Continue):
                node = self._new(s, "stmt")
                self._link(cur, node.idx)
                if self._loops:
                    self._link([node.idx], self._loops[-1][0])
                cur = []
            else:
                node = self._new(s, "stmt")
                self._link(cur, node.idx)
                cur = [node.idx]
        return cur

    # --------------------------------------------------------- path queries
    def reaches_without(self, start: int, targets: set[int],
                        blockers: set[int]) -> bool:
        """True when some path from `start` reaches any of `targets`
        without passing THROUGH a blocker node (a blocker that IS a
        target still counts as reached — callers exclude that case by
        construction)."""
        seen = set()
        stack = [start]
        while stack:
            i = stack.pop()
            if i in seen:
                continue
            seen.add(i)
            if i in targets:
                return True
            if i in blockers:
                continue
            stack.extend(self.nodes[i].succs)
        return False


def iter_functions(tree: ast.AST) -> Iterator[ast.FunctionDef]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _base_name(node: ast.AST) -> str:
    """Root Name of an attribute/subscript chain ('' if dynamic)."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else ""


def _contains_call(stmt: ast.AST, names: frozenset[str]) -> bool:
    """True when `stmt` (excluding nested defs) contains a call whose
    attribute/function name is in `names`."""
    for n in _walk_shallow(stmt):
        if isinstance(n, ast.Call):
            fn = n.func
            if isinstance(fn, ast.Attribute) and fn.attr in names:
                return True
            if isinstance(fn, ast.Name) and fn.id in names:
                return True
    return False


def _walk_shallow(node: ast.AST) -> Iterator[ast.AST]:
    """ast.walk that does not descend into nested function bodies."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for c in ast.iter_child_nodes(n):
            if not isinstance(c, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                stack.append(c)


# =====================================================================
# Taint engine
# =====================================================================

OBJ = "obj"        # a live store object (tx.get_* result or alias)
CONT = "cont"      # a container holding live store objects

# store getters/finders: the taint sources. Receiver must be the
# conventional transaction name — every store callback in this tree
# names it `tx` (the PR 8 rule pinned the same convention).
GETTERS = frozenset({
    "get_node", "get_task", "get_service", "get_cluster",
    "get_network", "get_secret", "get_config", "get_volume",
    "get_extension", "get_resource", "get_member",
})
FINDERS = frozenset({
    "find_nodes", "find_tasks", "find_services", "find_clusters",
    "find_networks", "find_secrets", "find_configs", "find_volumes",
    "find_extensions", "find_resources", "find_members",
})
TX_NAMES = frozenset({"tx"})

# curated call-boundary summaries: dotted chain (or bare attr) -> tag
# returned. Everything else returns CLEAN (under-approximate across
# calls; the in-function flows are the contract). `.copy()` /
# deepcopy / dataclasses.replace are the sanctioned sanitizers.
CALL_SUMMARIES: dict[str, str | None] = {
    "copy": None,                 # method: x.copy() -> fresh object
    "copy.deepcopy": None,
    "dataclasses.replace": None,
    "replace": None,              # dataclasses.replace imported bare
    "sorted": "arg0",             # order-only: sorted(tainted) stays
    "list": "arg0",               # container/identity pass-throughs
    "tuple": "arg0",
    "reversed": "arg0",
}

# container mutators that POUR a tainted element into the receiver
_POUR = frozenset({"append", "add", "insert", "appendleft"})
_POUR_MANY = frozenset({"extend", "update"})
# mutating methods that, invoked through an ATTRIBUTE of a live store
# object, mutate shared store state (`cur.volumes.append(v)`)
MUTATING_METHODS = frozenset({
    "append", "add", "extend", "insert", "update", "setdefault",
    "pop", "popitem", "remove", "discard", "clear", "sort", "reverse",
    "appendleft", "extendleft",
})


class TaintState:
    """Per-program-point name tags. Tiny immutable-ish wrapper over two
    frozensets so worklist convergence checks are cheap."""

    __slots__ = ("obj", "cont")

    def __init__(self, obj=frozenset(), cont=frozenset()):
        self.obj = obj
        self.cont = cont

    def merge(self, other: "TaintState") -> "TaintState":
        return TaintState(self.obj | other.obj, self.cont | other.cont)

    def __eq__(self, other):
        return (isinstance(other, TaintState)
                and self.obj == other.obj and self.cont == other.cont)

    __hash__ = None

    def tag_of(self, name: str) -> str | None:
        if name in self.obj:
            return OBJ
        if name in self.cont:
            return CONT
        return None

    def bind(self, name: str, tag: str | None) -> "TaintState":
        obj, cont = self.obj, self.cont
        obj = obj | {name} if tag == OBJ else obj - {name}
        cont = cont | {name} if tag == CONT else cont - {name}
        return TaintState(obj, cont)


def _expr_tag(expr: ast.AST, st: TaintState) -> str | None:
    """Abstract value of an expression under `st`."""
    if isinstance(expr, ast.Name):
        return st.tag_of(expr.id)
    if isinstance(expr, ast.Attribute):
        # attribute read off a live object is itself live shared state
        # (t.status, t.spec, ...) — the alias shape PR 8 missed
        base = _expr_tag(expr.value, st)
        return OBJ if base == OBJ else None
    if isinstance(expr, ast.Subscript):
        base = _expr_tag(expr.value, st)
        if base == CONT:
            return OBJ if not isinstance(expr.slice, ast.Slice) else CONT
        return OBJ if base == OBJ else None
    if isinstance(expr, ast.Call):
        return _call_tag(expr, st)
    if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
        tags = [_expr_tag(e, st) for e in expr.elts]
        return CONT if any(t in (OBJ, CONT) for t in tags) else None
    if isinstance(expr, ast.BoolOp):
        tags = [_expr_tag(v, st) for v in expr.values]
        if OBJ in tags:
            return OBJ
        return CONT if CONT in tags else None
    if isinstance(expr, ast.IfExp):
        t1, t2 = _expr_tag(expr.body, st), _expr_tag(expr.orelse, st)
        if OBJ in (t1, t2):
            return OBJ
        return CONT if CONT in (t1, t2) else None
    if isinstance(expr, ast.NamedExpr):
        return _expr_tag(expr.value, st)
    if isinstance(expr, (ast.ListComp, ast.SetComp)):
        # [f(t) for t in tainted_container]: if the element expression
        # is (an alias of) the iteration var over a tainted source, the
        # comprehension is a container of live objects
        gen = expr.generators[0] if expr.generators else None
        if gen is not None:
            src = _expr_tag(gen.iter, st)
            if src in (OBJ, CONT) and isinstance(gen.target, ast.Name) \
                    and isinstance(expr.elt, ast.Name) \
                    and expr.elt.id == gen.target.id:
                return CONT
        return None
    return None


def _call_tag(call: ast.Call, st: TaintState) -> str | None:
    fn = call.func
    if isinstance(fn, ast.Attribute):
        recv = fn.value
        # tx.get_* / tx.find_*
        if isinstance(recv, ast.Name) and recv.id in TX_NAMES:
            if fn.attr in GETTERS:
                return OBJ
            if fn.attr in FINDERS:
                return CONT
        # sanitizer: anything.copy() -> clean fresh object
        if fn.attr == "copy" and not call.args and not call.keywords:
            return None
        # container read-throughs on tainted receivers
        if fn.attr in ("values",):
            return CONT if _expr_tag(recv, st) == CONT else None
        if fn.attr in ("get", "popleft", "popitem"):
            return OBJ if _expr_tag(recv, st) == CONT else None
        if fn.attr == "pop":
            return OBJ if _expr_tag(recv, st) == CONT else None
        chain = _attr_chain(fn)
        summ = CALL_SUMMARIES.get(chain, "?")
        if summ != "?":
            return _summary_result(summ, call, st)
    elif isinstance(fn, ast.Name):
        summ = CALL_SUMMARIES.get(fn.id, "?")
        if summ != "?":
            return _summary_result(summ, call, st)
    return None


def _summary_result(summ, call: ast.Call, st: TaintState) -> str | None:
    if summ == "arg0":
        return _expr_tag(call.args[0], st) if call.args else None
    return summ


def _iter_tag(iter_expr: ast.AST, st: TaintState) -> str | None:
    """Tag of the loop variable for `for x in iter_expr`."""
    src = _expr_tag(iter_expr, st)
    if src == CONT:
        return OBJ
    if isinstance(iter_expr, ast.Call) \
            and isinstance(iter_expr.func, ast.Attribute):
        fn = iter_expr.func
        if fn.attr in ("values", "items") \
                and _expr_tag(fn.value, st) == CONT:
            return OBJ
        if isinstance(fn.value, ast.Name) and fn.value.id in TX_NAMES \
                and fn.attr in FINDERS:
            return OBJ
    return None


class TaintAnalysis:
    """Forward worklist fixpoint of TaintState over one CFG."""

    def __init__(self, cfg: CFG):
        self.cfg = cfg
        self.in_states: dict[int, TaintState] = {}
        self._run()

    # ----------------------------------------------------------- transfer
    def _transfer(self, node: CFGNode, st: TaintState) -> TaintState:
        s = node.stmt
        if s is None:
            return st
        if isinstance(s, ast.Assign):
            tag = _expr_tag(s.value, st)
            for tgt in s.targets:
                st = self._bind_target(tgt, s.value, tag, st)
            return st
        if isinstance(s, ast.AnnAssign) and s.value is not None:
            return self._bind_target(
                s.target, s.value, _expr_tag(s.value, st), st)
        if isinstance(s, ast.NamedExpr):
            return st.bind(s.target.id, _expr_tag(s.value, st))
        if isinstance(s, (ast.For, ast.AsyncFor)):
            tag = _iter_tag(s.iter, st)
            return self._bind_target(s.target, s.iter, tag, st,
                                     unpack_tag=tag)
        if isinstance(s, (ast.With, ast.AsyncWith)):
            for item in s.items:
                if isinstance(item.optional_vars, ast.Name):
                    st = st.bind(item.optional_vars.id,
                                 _expr_tag(item.context_expr, st))
            return st
        if isinstance(s, ast.Delete):
            for t in s.targets:
                if isinstance(t, ast.Name):
                    st = st.bind(t.id, None)
            return st
        if isinstance(s, ast.Expr) and isinstance(s.value, ast.Call):
            call = s.value
            fn = call.func
            if isinstance(fn, ast.Attribute) \
                    and isinstance(fn.value, ast.Name):
                recv = fn.value.id
                if fn.attr in _POUR and call.args \
                        and _expr_tag(call.args[0], st) in (OBJ, CONT):
                    return st.bind(recv, CONT)
                if fn.attr in _POUR_MANY and call.args \
                        and _expr_tag(call.args[0], st) == CONT:
                    return st.bind(recv, CONT)
            # walrus inside a call statement (rare) — pick up bindings
            for n in _walk_shallow(s):
                if isinstance(n, ast.NamedExpr) \
                        and isinstance(n.target, ast.Name):
                    st = st.bind(n.target.id, _expr_tag(n.value, st))
            return st
        if isinstance(s, ast.If) or isinstance(s, ast.While):
            # walrus in the test binds for both branches
            for n in _walk_shallow(s.test):
                if isinstance(n, ast.NamedExpr) \
                        and isinstance(n.target, ast.Name):
                    st = st.bind(n.target.id, _expr_tag(n.value, st))
            return st
        return st

    def _bind_target(self, tgt, value, tag, st: TaintState,
                     unpack_tag=None) -> TaintState:
        if isinstance(tgt, ast.Name):
            return st.bind(tgt.id, tag)
        if isinstance(tgt, (ast.Tuple, ast.List)):
            elts = tgt.elts
            if isinstance(value, (ast.Tuple, ast.List)) \
                    and len(value.elts) == len(elts):
                # a, b = t1, t2 — elementwise (the tuple-unpack shape)
                for e, v in zip(elts, value.elts):
                    st = self._bind_target(e, v, _expr_tag(v, st), st)
                return st
            # unpack of a tainted aggregate: every name may be live
            per = unpack_tag if unpack_tag is not None else (
                OBJ if tag in (OBJ, CONT) else None)
            for e in elts:
                if isinstance(e, ast.Name):
                    st = st.bind(e.id, per)
                elif isinstance(e, ast.Starred) \
                        and isinstance(e.value, ast.Name):
                    st = st.bind(e.value.id,
                                 CONT if per == OBJ else per)
            return st
        if isinstance(tgt, ast.Subscript):
            # lst[i] = tainted -> lst becomes container-of-tainted
            if isinstance(tgt.value, ast.Name) and tag in (OBJ, CONT):
                return st.bind(tgt.value.id, CONT)
        return st

    # ------------------------------------------------------------ fixpoint
    def _run(self) -> None:
        cfg = self.cfg
        init = TaintState()
        self.in_states = {cfg.entry.idx: init}
        work = [cfg.entry.idx]
        out: dict[int, TaintState] = {}
        while work:
            i = work.pop()
            node = cfg.nodes[i]
            st = self.in_states.get(i, init)
            new_out = self._transfer(node, st)
            if out.get(i) == new_out:
                continue
            out[i] = new_out
            for s in node.succs:
                merged = self.in_states.get(s)
                nxt = new_out if merged is None else merged.merge(new_out)
                if merged is None or nxt != merged:
                    self.in_states[s] = nxt
                    work.append(s)


# =====================================================================
# Rule 1: store-copy-dataflow
# =====================================================================


class StoreCopyDataflowRule(Rule):
    """Flow- and alias-sensitive copy-before-mutate (supersedes the
    PR 8 linear heuristic). A `tx.get_*` result is a live reference
    shared with every reader; `tx.find_*` returns a list of them. A
    write reaching any alias of one — through plain assignment, tuple
    unpack, attribute aliasing, or a container it was appended to —
    must be preceded by `.copy()` on THAT object along every path."""

    name = "store-copy-dataflow"
    invariant = ("store objects are live references: `.copy()` before "
                 "mutating a tx.get_*/find_* result in a transaction — "
                 "tracked flow-sensitively through aliases, tuple "
                 "unpacks, containers, and loop iteration")

    def applies(self, path: str) -> bool:
        return path.startswith("swarmkit_tpu/")

    def check(self, mod: Module) -> Iterator[Finding]:
        for fn in iter_functions(mod.tree):
            # pre-filter: no taint source in this function's own body
            # means no findings — skip the CFG+fixpoint entirely (the
            # whole-tree pass must stay inside the 10 s budget)
            if not self._has_source(fn):
                continue
            cfg = CFG(fn)
            ta = TaintAnalysis(cfg)
            for node in cfg.nodes:
                st = ta.in_states.get(node.idx)
                if st is None or (not st.obj and not st.cont):
                    continue
                yield from self._check_node(mod, node, st)

    @staticmethod
    def _has_source(fn) -> bool:
        for n in _walk_shallow(fn):
            if isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and isinstance(n.func.value, ast.Name) \
                    and n.func.value.id in TX_NAMES \
                    and n.func.attr in (GETTERS | FINDERS):
                return True
        return False

    def _check_node(self, mod: Module, node: CFGNode,
                    st: TaintState) -> Iterator[Finding]:
        s = node.stmt
        if s is None or node.kind == "head":
            return
        targets: list[ast.AST] = []
        if isinstance(s, ast.Assign):
            targets = s.targets
        elif isinstance(s, ast.AugAssign):
            targets = [s.target]
        elif isinstance(s, ast.AnnAssign):
            targets = [s.target]
        for tgt in targets:
            if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                yield from self._check_write(mod, s, tgt, st)
            elif isinstance(tgt, (ast.Tuple, ast.List)):
                for e in tgt.elts:
                    if isinstance(e, (ast.Attribute, ast.Subscript)):
                        yield from self._check_write(mod, s, e, st)
        # mutating method through an attribute of a live object
        # (`cur.volumes.append(v)` mutates replicated shared state)
        if isinstance(s, ast.Expr) and isinstance(s.value, ast.Call):
            call = s.value
            fn = call.func
            if isinstance(fn, ast.Attribute) \
                    and fn.attr in MUTATING_METHODS \
                    and isinstance(fn.value, (ast.Attribute,
                                              ast.Subscript)) \
                    and _expr_tag(fn.value, st) == OBJ:
                base = _base_name(fn.value) or "<expr>"
                yield self.finding(
                    mod, call,
                    f".{fn.attr}() on an attribute of {base!r}, a "
                    "live store object — .copy() the object before "
                    "mutating its containers")

    def _check_write(self, mod: Module, s, tgt,
                     st: TaintState) -> Iterator[Finding]:
        """Fire when the object being written into — the target minus
        its final attribute/index — is (an alias/element of) a live
        store object. `ts[0].status.state = X` over a find_* list
        fires; `lst[0] = x` on a plain local container does not."""
        if _expr_tag(tgt.value, st) != OBJ:
            return
        base = _base_name(tgt) or "<expr>"
        kind = ("augmented write" if isinstance(s, ast.AugAssign)
                else "write")
        yield self.finding(
            mod, tgt,
            f"{kind} through {base!r}, a live store object "
            "(tx.get_*/find_* result or alias) — .copy() before "
            "mutating; a copy of one alias does not clean the others")


# =====================================================================
# Rule 2: dirty-feed
# =====================================================================


class DirtyFeedRule(Rule):
    """Round-6 tracked-encoder contract: an unmarked NodeInfo mutation
    is INVISIBLE to the zero-scan encode. Every mutator call in the
    Scheduler's event/tick paths must have a mark-feed call on every
    path through the mutation (before OR after — a mark anywhere in
    the same invocation covers the row until the next encode)."""

    name = "dirty-feed"
    invariant = ("every NodeInfo mutation on a Scheduler path must "
                 "reach the tracked-encoder dirty feed (mark_numeric / "
                 "mark_replaced / mark_node_set_changed / restamp / "
                 "poison) on EVERY path through the mutation; the "
                 "wave-commit path is whitelisted (restamp reconciles)")

    AUDITED = ("swarmkit_tpu/scheduler/scheduler.py",)
    MUTATORS = frozenset({"add_task", "remove_task", "task_failed"})
    MARKS = frozenset({
        "mark_numeric", "mark_replaced", "mark_node_set_changed",
        "restamp_counts", "force_numeric_reencode", "poison_all_numeric",
        "apply_counts",
    })
    # the wave-commit path: apply_placements' bulk walk is reconciled
    # by restamp_counts / the unclean heal, per the async-commit plane
    WHITELIST_FUNCS = frozenset({"_apply_decisions", "_commit_heavy"})

    def applies(self, path: str) -> bool:
        return path in self.AUDITED

    def check(self, mod: Module) -> Iterator[Finding]:
        for fn in iter_functions(mod.tree):
            if fn.name in self.WHITELIST_FUNCS:
                continue
            if not _contains_call(fn, self.MUTATORS):
                continue
            cfg = CFG(fn)
            marks = {n.idx for n in cfg.nodes
                     if n.stmt is not None
                     and self._stmt_part_has_mark(n)}
            for node in cfg.nodes:
                if node.stmt is None:
                    continue
                call = self._mutator_call(node)
                if call is None:
                    continue
                if self._violates(cfg, node, marks):
                    yield self.finding(
                        mod, call,
                        f"NodeInfo .{call.func.attr}() with a "
                        "mark-free path through it — the tracked "
                        "encoder never sees an unmarked mutation "
                        "(mark_numeric/mark_replaced/"
                        "mark_node_set_changed, or poison the row)")

    def _stmt_part_has_mark(self, node: CFGNode) -> bool:
        """Mark calls in the node's OWN code: a head node owns only its
        test/iter expression (its body statements are separate nodes)."""
        s = node.stmt
        if node.kind == "head":
            if isinstance(s, (ast.If, ast.While)):
                return _contains_call(s.test, self.MARKS)
            if isinstance(s, (ast.For, ast.AsyncFor)):
                return _contains_call(s.iter, self.MARKS)
            return False
        return _contains_call(s, self.MARKS)

    def _mutator_call(self, node: CFGNode) -> ast.Call | None:
        s = node.stmt
        scope: ast.AST
        if node.kind == "head":
            if isinstance(s, (ast.If, ast.While)):
                scope = s.test
            elif isinstance(s, (ast.For, ast.AsyncFor)):
                scope = s.iter
            else:
                return None
        else:
            scope = s
        for n in _walk_shallow(scope):
            if isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr in self.MUTATORS:
                # exclude self-calls on encoder-ish receivers (none of
                # the mark receivers define these names, but be safe)
                return n
        return None

    def _violates(self, cfg: CFG, site: CFGNode, marks: set[int]) -> bool:
        """Violation iff a mark-free prefix reaches the site AND a
        mark-free suffix leaves it. The `if info.add_task(t): mark`
        idiom: when the mutator is an If test, the mutation only
        happened on the TRUE branch — the suffix query starts there."""
        if site.idx in marks:
            return False
        prefix_free = cfg.reaches_without(
            cfg.entry.idx, {site.idx}, marks)
        if not prefix_free:
            return False
        if site.kind == "head" and isinstance(site.stmt, ast.If):
            # successors: true-branch entry is the first successor
            # linked (builder order); fall-through/else is the rest.
            # Conservatively use the true-branch entry only.
            starts = site.succs[:1]
        else:
            starts = site.succs
        for s0 in starts:
            if s0 in marks:
                continue
            if cfg.reaches_without(s0, {cfg.exit.idx}, marks):
                return True
        return False


# =====================================================================
# Rule 3: barrier-before-drain
# =====================================================================


@dataclass(frozen=True)
class DrainEntry:
    func: str                       # function / nested-def name
    mode: str                       # "before-reads" | "postdominate"
    reads: frozenset = frozenset()  # call keys counting as wave reads


@dataclass(frozen=True)
class BarrierFileSpec:
    path: str
    barriers: frozenset             # call keys counting as a barrier
    entries: tuple


BARRIER_SPECS: tuple[BarrierFileSpec, ...] = (
    BarrierFileSpec(
        path="swarmkit_tpu/ops/pipeline.py",
        barriers=frozenset({"_barrier", "barrier"}),
        entries=(
            # the ONE drain sequence every trigger funnels through:
            # inline commits / pulls must sit behind the barrier
            DrainEntry("drain_serial", "before-reads",
                       frozenset({"commit_deferred", "finish_pulled",
                                  "_complete", "_commit", "_heavy"})),
            # full pipeline drain: every completion/commit post-barrier
            DrainEntry("flush", "before-reads",
                       frozenset({"_complete", "_commit", "_heavy"})),
            # the public external-mutation barrier must actually barrier
            DrainEntry("barrier", "postdominate"),
        ),
    ),
    BarrierFileSpec(
        path="swarmkit_tpu/scheduler/scheduler.py",
        barriers=frozenset({"_drain_commit_plane", "barrier"}),
        entries=(
            # event handler: mutates node_infos/pools/volume_set — the
            # external-mutation entry point of the contract
            DrainEntry("_handle", "before-reads",
                       frozenset({"add_task", "remove_task",
                                  "task_failed", "_add_or_update_node",
                                  "_remove_node", "add_or_update_volume",
                                  "remove_volume", "release_task",
                                  "reserve_task"})),
            # serial tick path: reads+mutates host state end to end
            # (_tick_pipelined is the mirror body, not a raw read — it
            # takes its own barrier per the tick protocol)
            DrainEntry("tick", "before-reads",
                       frozenset({"_process_preassigned",
                                  "_schedule_backlog"})),
            # not-primed backlog fallthrough inside the pipelined tick
            # is covered by the mirror table; the terminal drains must
            # END drained on every path:
            DrainEntry("flush_pipeline", "postdominate"),
        ),
    ),
)


class BarrierBeforeDrainRule(Rule):
    """Async-commit-plane contract, verified in BOTH mirrored tick
    implementations: from each curated drain-trigger entry point,
    every path takes a commit-plane barrier before its first read of
    wave state ("before-reads"), or passes a barrier on every path to
    exit ("postdominate")."""

    name = "barrier-before-drain"
    invariant = ("EVERY drain trigger must block on the commit worker "
                 "first — external mutations, inline commits, pending-"
                 "row/hypo-row/signature drains, flush paths — in both "
                 "TickPipeline and Scheduler (the mirrored pair)")

    def applies(self, path: str) -> bool:
        return any(path == s.path for s in BARRIER_SPECS)

    def check(self, mod: Module) -> Iterator[Finding]:
        spec = next(s for s in BARRIER_SPECS if s.path == mod.path)
        fns = {fn.name: fn for fn in iter_functions(mod.tree)}
        for entry in spec.entries:
            fn = fns.get(entry.func)
            if fn is None:
                continue        # coverage pinned by barrier_coverage()
            cfg = CFG(fn)
            barrier_nodes = {
                n.idx for n in cfg.nodes
                if n.stmt is not None
                and self._node_has_call(n, spec.barriers)}
            if entry.mode == "postdominate":
                if not barrier_nodes or cfg.reaches_without(
                        cfg.entry.idx, {cfg.exit.idx}, barrier_nodes):
                    yield self.finding(
                        mod, fn,
                        f"{entry.func}: a path reaches exit without "
                        "taking the commit-plane barrier "
                        f"({'/'.join(sorted(spec.barriers))}) — every "
                        "drain trigger must block on the worker")
                continue
            read_nodes = {
                n.idx for n in cfg.nodes
                if n.stmt is not None
                and n.idx not in barrier_nodes
                and self._node_has_call(n, entry.reads)}
            for r in sorted(read_nodes):
                if cfg.reaches_without(cfg.entry.idx, {r},
                                       barrier_nodes):
                    node = cfg.nodes[r]
                    yield self.finding(
                        mod, node.stmt,
                        f"{entry.func}: wave-state read reachable "
                        "without a commit-plane barrier "
                        f"({'/'.join(sorted(spec.barriers))} must "
                        "precede it on every path)")

    @staticmethod
    def _node_has_call(node: CFGNode, names: frozenset[str]) -> bool:
        s = node.stmt
        if node.kind == "head":
            if isinstance(s, (ast.If, ast.While)):
                s = s.test
            elif isinstance(s, (ast.For, ast.AsyncFor)):
                s = s.iter
            elif isinstance(s, (ast.With, ast.AsyncWith)):
                # with-item context exprs belong to the head
                for item in s.items:
                    if _contains_call(item.context_expr, names):
                        return True
                return False
            else:
                return False
        return _contains_call(s, names)


def barrier_coverage(root) -> dict[str, list[str]]:
    """{path: [missing names]} — the tier-1 gate pins this empty so a
    rename cannot silently disable barrier-before-drain. Covers the
    curated entry-point FUNCTIONS and the rule's whole call VOCABULARY:
    a renamed read/mutator (e.g. `_schedule_backlog` →
    `_schedule_backlog_chunked`) would otherwise leave that entry's
    check vacuously green."""
    out: dict[str, list[str]] = {}
    for spec in BARRIER_SPECS:
        p = root / spec.path
        try:
            tree = ast.parse(p.read_text(), filename=spec.path)
        except (OSError, SyntaxError):
            out[spec.path] = sorted(
                {e.func for e in spec.entries} | set(spec.barriers))
            continue
        found = {fn.name for fn in iter_functions(tree)}
        called = {n.func.attr for n in ast.walk(tree)
                  if isinstance(n, ast.Call)
                  and isinstance(n.func, ast.Attribute)}
        called |= {n.func.id for n in ast.walk(tree)
                   if isinstance(n, ast.Call)
                   and isinstance(n.func, ast.Name)}
        missing = sorted(
            {e.func for e in spec.entries if e.func not in found}
            | {b for b in spec.barriers if b not in called}
            | {r for e in spec.entries for r in e.reads
               if r not in called})
        if missing:
            out[spec.path] = missing
    return out


RULES: tuple[Rule, ...] = (
    StoreCopyDataflowRule(),
    DirtyFeedRule(),
    BarrierBeforeDrainRule(),
)
