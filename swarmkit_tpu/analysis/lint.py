"""AST invariant lint: CLAUDE.md contracts compiled into machine-checked
rules.

Each rule encodes one convention-only invariant that has already bitten
(or nearly bitten) a past round — the axon 2D-scatter-add bug, the
`rpc/services.py` ad-hoc retry loop, the ambient-mesh entry rule, the
donation-set pin, the failpoint/trace decision-boundary discipline, the
no-int64-in-kernels rule, and the lock/condition factory seam the
runtime detector (lockgraph.py) depends on. The FLOW-sensitive
contracts (copy-before-mutate through aliases/containers, the
tracked-encoder dirty feed, barrier-before-drain ordering) live in
analysis/dataflow.py on a real CFG; `all_rules()` is the combined set
and the default for every driver entry point.

Suppression is per-line and per-rule:

    x = y.at[rows].add(delta)   # lint: allow(scatter-2d) probed-safe: ...

A pragma on the flagged line or the line directly above it silences
exactly the named rule(s); `# lint: allow(rule-a, rule-b)` names several.
Every allow is expected to carry a justification in the same comment —
the pragma names WHAT is silenced, the prose says WHY it is safe.

Run as a tier-1 test (tests/test_lint_clean.py: the tree must be clean
modulo pragmas) and standalone:

    python -m swarmkit_tpu.analysis          # lint + mirror drift check

Adding a rule: subclass Rule, set `name` / `invariant` / `applies()`,
yield Findings from `check()`, append to RULES, add a must-fire and a
must-not-fire fixture to tests/test_analysis.py, and document it in
docs/static_analysis.md.
"""
from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from pathlib import Path
from typing import Iterable, Iterator

PRAGMA_RE = re.compile(r"#\s*lint:\s*allow\(([a-z0-9_\-,\s]+)\)")


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # repo-relative posix path
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class Module:
    """One parsed source file + its pragma map."""

    def __init__(self, path: str, source: str):
        self.path = path                      # relative posix
        self.source = source
        self.tree = ast.parse(source, filename=path)
        # line -> frozenset(rule names allowed on that line)
        self.allows: dict[int, frozenset[str]] = {}
        # lines that are comment-only: the ONLY form whose pragma also
        # covers the following line — a trailing pragma on a CODE line
        # must not spill onto its neighbor
        self._comment_only: set[int] = set()
        for i, text in enumerate(source.splitlines(), start=1):
            m = PRAGMA_RE.search(text)
            if m:
                rules = frozenset(
                    r.strip() for r in m.group(1).split(",") if r.strip())
                self.allows[i] = rules
                if text.lstrip().startswith("#"):
                    self._comment_only.add(i)

    def allowed(self, rule: str, line: int) -> bool:
        """Pragma on the flagged line, or on a comment-only line
        directly above it."""
        if rule in self.allows.get(line, ()):
            return True
        return (line - 1 in self._comment_only
                and rule in self.allows.get(line - 1, ()))


class Rule:
    name: str = ""
    invariant: str = ""      # the CLAUDE.md contract this rule enforces

    def applies(self, path: str) -> bool:
        return True

    def check(self, mod: Module) -> Iterator[Finding]:  # pragma: no cover
        raise NotImplementedError

    def finding(self, mod: Module, node: ast.AST, msg: str) -> Finding:
        return Finding(self.name, mod.path, getattr(node, "lineno", 0), msg)


def _attr_chain(node: ast.AST) -> str:
    """Dotted name for Name/Attribute chains ('jax.sharding.set_mesh');
    '' for anything dynamic."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _walk_with_parents(tree: ast.AST) -> Iterator[tuple[ast.AST, list]]:
    """Yield (node, ancestor_stack) — ancestors outermost-first."""
    stack: list[ast.AST] = []

    def rec(node):
        yield node, stack
        stack.append(node)
        for child in ast.iter_child_nodes(node):
            yield from rec(child)
        stack.pop()

    yield from rec(tree)


# --------------------------------------------------------------------- rules
class Scatter2DRule(Rule):
    """The axon backend's 2D scatter-add silently corrupts above ~512
    updates (CLAUDE.md): kernel code must use FLAT 1D index scatters."""

    name = "scatter-2d"
    invariant = ("x.at[r, c].add(d) is WRONG on the axon backend above "
                 "~512 updates — use flat.at[r * N + c].add(d) "
                 "(ops/reconcile.py task_count_flat)")

    def applies(self, path: str) -> bool:
        return path.startswith(("swarmkit_tpu/ops/", "swarmkit_tpu/models/",
                                "swarmkit_tpu/parallel/"))

    def check(self, mod: Module) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "add"):
                continue
            sub = node.func.value
            if not (isinstance(sub, ast.Subscript)
                    and isinstance(sub.value, ast.Attribute)
                    and sub.value.attr == "at"):
                continue
            if isinstance(sub.slice, ast.Tuple):
                yield self.finding(
                    mod, node,
                    "multi-axis .at[...].add(...) scatter-add — flat-1D "
                    "only (the axon 2D scatter-add bug)")


class AdHocSleepRule(Rule):
    """Caller-side waits are explicit Backoff policies / Clock timers —
    no new ad-hoc sleep loops (PR 3 contract)."""

    name = "ad-hoc-sleep"
    invariant = ("retries/waits go through utils/backoff.py Backoff or "
                 "utils/clock.py Clock (clock-injectable, test-"
                 "deterministic) — never bare time.sleep")

    ALLOWED = (
        "swarmkit_tpu/utils/backoff.py",     # the policy seam itself
        "swarmkit_tpu/utils/clock.py",       # the Clock seam
        "swarmkit_tpu/utils/failpoints.py",  # armed-only injected latency
        "swarmkit_tpu/cmd/",                 # CLI entrypoints (human pacing)
    )

    def applies(self, path: str) -> bool:
        return (path.startswith("swarmkit_tpu/")
                and not path.startswith(self.ALLOWED))

    def check(self, mod: Module) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            if chain.split(".")[0] in ("backoff", "_backoff"):
                continue      # the clock-driven seam itself
            if chain.endswith(".sleep") or chain == "sleep":
                yield self.finding(
                    mod, node,
                    f"bare {chain or 'sleep'}() — use a utils/backoff.py "
                    "Backoff policy or a utils/clock.py timer")


class AmbientMeshRule(Rule):
    """Ambient-mesh entry is parallel/mesh.py mesh_context() ONLY —
    jax.sharding.set_mesh/use_mesh vary across jax versions."""

    name = "ambient-mesh"
    invariant = ("every ambient-mesh entry goes through "
                 "parallel.mesh.mesh_context (set_mesh -> use_mesh -> "
                 "Mesh ctx fallback), never jax.sharding directly")

    def applies(self, path: str) -> bool:
        return (path.startswith("swarmkit_tpu/")
                and path != "swarmkit_tpu/parallel/mesh.py")

    def check(self, mod: Module) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            if (isinstance(node, ast.Attribute)
                    and node.attr in ("set_mesh", "use_mesh")):
                yield self.finding(
                    mod, node,
                    f".{node.attr} outside parallel/mesh.py — use "
                    "parallel.mesh.mesh_context()")


class DonatePinnedRule(Rule):
    """Donation sets in kernel jits are pinned to the 8 STATE arrays."""

    name = "donate-pinned"
    invariant = ("every donate_argnums in ops/ must be the "
                 "DONATE_STATE_ARGNUMS constant — donating a group-table "
                 "position would hand the kernel invalidated buffers on "
                 "a _gcache hit")

    def applies(self, path: str) -> bool:
        return path.startswith(("swarmkit_tpu/ops/", "swarmkit_tpu/models/",
                                "swarmkit_tpu/parallel/"))

    def check(self, mod: Module) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            for kw in node.keywords:
                if kw.arg != "donate_argnums":
                    continue
                if not (isinstance(kw.value, ast.Name)
                        and kw.value.id == "DONATE_STATE_ARGNUMS"):
                    yield self.finding(
                        mod, kw.value,
                        "donate_argnums must be DONATE_STATE_ARGNUMS "
                        "(the 8 STATE arrays; group tables are cached "
                        "and must never be donated)")


class SpanInLoopRule(Rule):
    """Trace/failpoint/lifecycle sites live at decision boundaries,
    never inside per-entry hot loops; per-entry emission must be guarded
    by the `.enabled()` pattern so the disarmed cost stays one
    truthiness test (CLAUDE.md trace-plane + lifecycle-plane
    contracts — the scheduler records ONE batch per wave, never per
    placed task in the walk)."""

    name = "span-in-loop"
    invariant = ("no trace.span/start/rec/event, failpoints.fp*, "
                 "lifecycle.record*, or telemetry snapshot-assembly "
                 "call inside a for/while body in the audited hot "
                 "modules unless under an `if trace.enabled()` / "
                 "`if lifecycle.enabled()` / `if telemetry.enabled()` "
                 "/ `if traced:` guard")

    AUDITED = (
        "swarmkit_tpu/ops/pipeline.py",
        "swarmkit_tpu/ops/commit.py",
        "swarmkit_tpu/ops/resident.py",
        "swarmkit_tpu/scheduler/scheduler.py",
        "swarmkit_tpu/scheduler/batch.py",
        "swarmkit_tpu/scheduler/encode.py",
        "swarmkit_tpu/raft/node.py",
        "swarmkit_tpu/raft/storage.py",
        "swarmkit_tpu/dispatcher/dispatcher.py",
        "swarmkit_tpu/dispatcher/heartbeat.py",
        "swarmkit_tpu/dispatcher/follower.py",
        "swarmkit_tpu/dispatcher/columnar_diff.py",
        "swarmkit_tpu/rpc/wire.py",
        "swarmkit_tpu/rpc/server.py",
        "swarmkit_tpu/rpc/client.py",
        "swarmkit_tpu/agent/agent.py",
        "swarmkit_tpu/logbroker/broker.py",
        "swarmkit_tpu/logbroker/sharded.py",
        "swarmkit_tpu/watchapi/watch.py",
    )
    TRACE_CALLS = frozenset({"span", "start", "rec", "event", "wrap"})
    FP_CALLS = frozenset({"fp", "fp_value", "fp_transform"})
    LIFECYCLE_CALLS = frozenset({"record", "record_batch", "record_pairs"})
    # telemetry snapshot assembly (ISSUE 15): the heartbeat loop builds
    # a snapshot every Kth beat — the build must sit under the
    # `if telemetry.enabled():` guard so a disarmed beat allocates
    # nothing
    TELEMETRY_CALLS = frozenset({"node_snapshot", "registry_snapshot"})

    def applies(self, path: str) -> bool:
        return path in self.AUDITED

    @staticmethod
    def _guarded(ancestors: list, loop_idx: int) -> bool:
        """True when an If between the innermost loop and the call tests
        the armed state (`if traced:` / `if trace.enabled():`)."""
        for anc in ancestors[loop_idx + 1:]:
            if not isinstance(anc, ast.If):
                continue
            for n in ast.walk(anc.test):
                if isinstance(n, ast.Name) and n.id == "traced":
                    return True
                if isinstance(n, ast.Attribute) and n.attr == "enabled":
                    return True
        return False

    def check(self, mod: Module) -> Iterator[Finding]:
        for node, ancestors in _walk_with_parents(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            base = node.func.value
            base_name = base.id if isinstance(base, ast.Name) else ""
            is_site = (
                (base_name == "trace"
                 and node.func.attr in self.TRACE_CALLS)
                or (base_name == "failpoints"
                    and node.func.attr in self.FP_CALLS)
                or (base_name == "lifecycle"
                    and node.func.attr in self.LIFECYCLE_CALLS)
                or (base_name == "telemetry"
                    and node.func.attr in self.TELEMETRY_CALLS))
            if not is_site:
                continue
            # innermost enclosing loop that is inside the same function
            # as the call (a call in a nested def is NOT "in" an outer
            # function's loop — the def body runs elsewhere)
            loop_idx = None
            for i in range(len(ancestors) - 1, -1, -1):
                anc = ancestors[i]
                if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.Lambda)):
                    break
                if isinstance(anc, (ast.For, ast.While)):
                    loop_idx = i
                    break
            if loop_idx is None:
                continue
            if self._guarded(ancestors, loop_idx):
                continue
            yield self.finding(
                mod, node,
                f"{base_name}.{node.func.attr} inside a loop body — "
                "hot-path sites live at decision boundaries; per-entry "
                f"emission needs the `if {base_name}.enabled():` guard")


class RawConditionRule(Rule):
    """The lockgraph detector's documented Condition blind spot
    (ISSUE 12): a bare `threading.Condition()` allocates an internal
    RLock the armed detector can never see, so an inversion involving
    only that lock produces no edges. Every Condition must be
    constructed over a lockgraph factory primitive."""

    name = "raw-condition"
    invariant = ("threading.Condition() must wrap a "
                 "lockgraph.make_lock/make_rlock primitive "
                 "(threading.Condition(make_rlock(name))) so the armed "
                 "lock-order detector sees its acquisitions; disarmed "
                 "the factory hands back the plain primitive — one "
                 "truthiness test, zero tracker allocations")

    FACTORIES = frozenset({"make_lock", "make_rlock"})

    def applies(self, path: str) -> bool:
        return (path.startswith("swarmkit_tpu/")
                and not path.startswith("swarmkit_tpu/analysis/"))

    def _lock_arg_ok(self, node: ast.Call) -> bool:
        """The lock argument (positional 0 or lock=) must be a direct
        factory call or a name/attribute (assumed factory-made — the
        raw-lock rule polices how names get bound)."""
        arg = None
        if node.args:
            arg = node.args[0]
        for kw in node.keywords:
            if kw.arg == "lock":
                arg = kw.value
        if arg is None:
            return False
        if isinstance(arg, ast.Call):
            fn = arg.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else "")
            return name in self.FACTORIES
        # a pre-built lock passed by name: raw-lock already guarantees
        # every lock binding routes through the factory
        return isinstance(arg, (ast.Name, ast.Attribute))

    def check(self, mod: Module) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            if chain == "threading.Condition" \
                    and not self._lock_arg_ok(node):
                yield self.finding(
                    mod, node,
                    "bare threading.Condition() — its internal RLock "
                    "is invisible to the lock-order detector; use "
                    "threading.Condition(lockgraph.make_rlock(name))")
            if isinstance(node.func, ast.Name) \
                    and node.func.id == "Condition" \
                    and not self._lock_arg_ok(node):
                yield self.finding(
                    mod, node,
                    "bare Condition() — wrap a lockgraph factory "
                    "primitive: threading.Condition(make_rlock(name))")


class Int64InKernelRule(Rule):
    """int64 is unavailable in kernels (no x64 on the TPU backend)."""

    name = "int64-in-kernel"
    invariant = ("kernel modules never touch int64 — jnp has no x64 "
                 "here; host-side staging arrays live outside these "
                 "modules")

    KERNEL_MODULES = (
        "swarmkit_tpu/ops/placement.py",
        "swarmkit_tpu/ops/reconcile.py",
        "swarmkit_tpu/ops/bitpack.py",
        "swarmkit_tpu/ops/raft_replay.py",
        "swarmkit_tpu/ops/alloc.py",
        "swarmkit_tpu/models/cluster_step.py",
    )

    def applies(self, path: str) -> bool:
        return path in self.KERNEL_MODULES

    def check(self, mod: Module) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Attribute) and node.attr == "int64":
                yield self.finding(
                    mod, node,
                    "int64 in a kernel module — kernels run without x64; "
                    "use int32 (jnp.argsort is stable for tie-breaks)")


class RawLockRule(Rule):
    """Lock creation routes through the lockgraph factory seam so the
    armed lock-order detector sees every acquisition."""

    name = "raw-lock"
    invariant = ("threading.Lock()/RLock() sites go through "
                 "analysis.lockgraph.make_lock/make_rlock — the factory "
                 "is what lets the armed detector shim acquisition "
                 "order; disarmed it returns the plain primitive")

    def applies(self, path: str) -> bool:
        return (path.startswith("swarmkit_tpu/")
                and not path.startswith("swarmkit_tpu/analysis/"))

    def check(self, mod: Module) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            # `from threading import Lock` would let a bare Lock() call
            # bypass the dotted-form check below — flag the import, the
            # only gateway to that spelling
            if isinstance(node, ast.ImportFrom) \
                    and node.module == "threading":
                for alias in node.names:
                    if alias.name in ("Lock", "RLock"):
                        yield self.finding(
                            mod, node,
                            f"`from threading import {alias.name}` — a "
                            "bare call would bypass the lockgraph "
                            "factory seam; import threading and route "
                            "through analysis.lockgraph")
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            if chain in ("threading.Lock", "threading.RLock"):
                kind = chain.rsplit(".", 1)[1]
                factory = "make_lock" if kind == "Lock" else "make_rlock"
                yield self.finding(
                    mod, node,
                    f"bare threading.{kind}() — route through "
                    f"analysis.lockgraph.{factory}(name) so the armed "
                    "lock-order detector can track it")


class ColumnarMutateRule(Rule):
    """The columnar task mirror is derived truth kept in lockstep by the
    commit path (docs/store.md): a direct array write anywhere else
    silently diverges the columns from the object table."""

    name = "columnar-mutate"
    invariant = ("columnar arrays (store.columnar.*) are written ONLY by "
                 "the columnar plane itself — store/columnar.py, the "
                 "store commit/wave path in store/memory.py, and the "
                 "batched allocator (allocator/batched.py, ops/alloc.py); "
                 "everyone else goes through assign_wave / the commit "
                 "lockstep or reads")

    ALLOWED = (
        "swarmkit_tpu/store/columnar.py",
        "swarmkit_tpu/store/memory.py",
        "swarmkit_tpu/allocator/batched.py",
        "swarmkit_tpu/ops/alloc.py",
        # ISSUE 16: the diff gate owns per-shard plan columns (reads of
        # store.columnar plus its own arrays; never writes the mirror)
        "swarmkit_tpu/dispatcher/columnar_diff.py",
    )

    def applies(self, path: str) -> bool:
        return (path.startswith("swarmkit_tpu/")
                and path not in self.ALLOWED)

    @staticmethod
    def _chain_of_target(node: ast.AST) -> str:
        """Dotted chain of an assignment target, unwrapping subscripts
        (`store.columnar.state[rows]` -> 'store.columnar.state')."""
        while isinstance(node, ast.Subscript):
            node = node.value
        return _attr_chain(node)

    def check(self, mod: Module) -> Iterator[Finding]:
        tainted: set[str] = set()
        # SOURCE order, not ast.walk's breadth-first order: an alias
        # bound inside a nested block (if/with/try) would otherwise be
        # visited AFTER a shallower write through it and the write
        # would escape the taint
        stmts = sorted(
            (n for n in ast.walk(mod.tree)
             if isinstance(n, (ast.Assign, ast.AugAssign))),
            key=lambda n: (n.lineno, n.col_offset))
        for node in stmts:
            targets: list[ast.AST] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
                # taint names bound to a .columnar read so writes
                # through the alias are caught too
                value_chain = _attr_chain(node.value)
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        if value_chain.split(".")[-1:] == ["columnar"]:
                            tainted.add(tgt.id)
                        else:
                            tainted.discard(tgt.id)
            elif isinstance(node, ast.AugAssign):
                targets = [node.target]
            for tgt in targets:
                if isinstance(tgt, ast.Name):
                    continue
                chain = self._chain_of_target(tgt)
                parts = chain.split(".") if chain else []
                hit = "columnar" in parts \
                    or (parts and parts[0] in tainted and len(parts) > 1)
                if hit:
                    yield self.finding(
                        mod, tgt,
                        f"direct write through {chain!r} — columnar "
                        "arrays are commit-path-owned derived truth; "
                        "use store.assign_wave / the commit lockstep "
                        "(docs/store.md)")


class RawMetricRule(Rule):
    """Metric families are constructed ONLY through the utils/metrics
    module factories (ISSUE 15): a directly-constructed
    Histogram/Counter/CounterFamily/HistogramFamily never enters the
    process registry, so the per-node /metrics exposition AND the
    cluster telemetry rollup silently miss it."""

    name = "raw-metric"
    invariant = ("Histogram/Counter/CounterFamily/HistogramFamily are "
                 "instantiated only inside utils/metrics.py — every "
                 "other module uses the factories (histogram(), "
                 "counter(), counter_family(), histogram_family()) so "
                 "the family is registry-visible to the exposition and "
                 "the telemetry rollup")

    CLASSES = frozenset({"Histogram", "Counter", "CounterFamily",
                         "HistogramFamily"})

    def applies(self, path: str) -> bool:
        # tests may build standalone families (codec fixtures, per-node
        # parity registries); product code may not
        return (path.startswith("swarmkit_tpu/")
                and path != "swarmkit_tpu/utils/metrics.py")

    def check(self, mod: Module) -> Iterator[Finding]:
        # names imported FROM a metrics module: `from ..utils.metrics
        # import Histogram` (collections.Counter and friends stay
        # invisible — only the metrics module's classes are policed)
        imported: dict[str, str] = {}   # bound name -> class name
        # names the metrics MODULE itself is bound to: `from ..utils
        # import metrics [as m]`, `import swarmkit_tpu.utils.metrics
        # as m` — an aliased module must not smuggle m.Histogram(...)
        mod_aliases: set[str] = {"metrics"}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ImportFrom) and node.module \
                    and node.module.split(".")[-1] == "metrics":
                for alias in node.names:
                    if alias.name in self.CLASSES:
                        imported[alias.asname or alias.name] = alias.name
            elif isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    if alias.name == "metrics":
                        mod_aliases.add(alias.asname or alias.name)
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name.split(".")[-1] == "metrics" \
                            and alias.asname:
                        mod_aliases.add(alias.asname)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = None
            if isinstance(fn, ast.Name) and fn.id in imported:
                name = imported[fn.id]
            elif isinstance(fn, ast.Attribute) \
                    and fn.attr in self.CLASSES:
                parts = _attr_chain(fn).split(".")
                if len(parts) >= 2 and parts[-2] in mod_aliases:
                    name = fn.attr
            if name is not None:
                factory = {
                    "Histogram": "histogram",
                    "Counter": "counter",
                    "CounterFamily": "counter_family",
                    "HistogramFamily": "histogram_family",
                }[name]
                yield self.finding(
                    mod, node,
                    f"direct {name}(...) construction — route through "
                    f"utils.metrics.{factory}(name) so the family is "
                    "registry-visible (exposition + telemetry rollup)")


RULES: tuple[Rule, ...] = (
    Scatter2DRule(),
    AdHocSleepRule(),
    AmbientMeshRule(),
    DonatePinnedRule(),
    SpanInLoopRule(),
    Int64InKernelRule(),
    RawLockRule(),
    RawConditionRule(),
    ColumnarMutateRule(),
    RawMetricRule(),
)


def all_rules() -> tuple[Rule, ...]:
    """The full rule set: the syntactic rules above plus the dataflow
    contract rules (analysis/dataflow.py). Lazy import — dataflow
    builds on this module, so a top-level import would be circular."""
    from . import dataflow

    return RULES + dataflow.RULES


# -------------------------------------------------------------------- driver
def lint_source(source: str, path: str,
                rules: Iterable[Rule] | None = None) -> list[Finding]:
    """Lint one in-memory source blob (the fixture-test entrypoint).
    Default rule set is `all_rules()` (syntactic + dataflow)."""
    if rules is None:
        rules = all_rules()
    mod = Module(path, source)
    out: list[Finding] = []
    for rule in rules:
        if not rule.applies(path):
            continue
        for f in rule.check(mod):
            if not mod.allowed(rule.name, f.line):
                out.append(f)
    # dedupe identical findings (a statement can own several CFG nodes
    # — e.g. a finally body cloned onto an abrupt-exit path)
    out = list(dict.fromkeys(out))
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


def iter_py_files(root: Path, subdirs: Iterable[str]) -> Iterator[Path]:
    for sub in subdirs:
        base = root / sub
        if not base.exists():
            continue
        for p in sorted(base.rglob("*.py")):
            if "__pycache__" in p.parts:
                continue
            yield p


def lint_tree(root: Path, subdirs=("swarmkit_tpu", "tests"),
              rules: Iterable[Rule] | None = None) -> list[Finding]:
    """Lint the repo tree. `root` is the repo root; paths in findings
    are repo-relative posix (what `applies()` matches on)."""
    if rules is None:
        rules = all_rules()
    findings: list[Finding] = []
    for p in iter_py_files(root, subdirs):
        rel = p.relative_to(root).as_posix()
        findings.extend(_lint_path(root, rel, rules))
    return findings


def _lint_path(root: Path, rel: str, rules: Iterable[Rule],
               ) -> list[Finding]:
    try:
        source = (root / rel).read_text()
    except (OSError, UnicodeDecodeError):       # unreadable: not lintable
        return []
    try:
        return lint_source(source, rel, rules)
    except SyntaxError:
        return [Finding("parse-error", rel, 0, "file does not parse")]


def lint_files(root: Path, rel_paths: Iterable[str],
               rules: Iterable[Rule] | None = None) -> list[Finding]:
    """Lint an explicit repo-relative file list (the `--changed-only`
    scope). Every rule is per-file, so findings for a file here are
    IDENTICAL to that file's slice of the full `lint_tree` pass — the
    scope-soundness guard in tests/test_lint_clean.py pins it."""
    if rules is None:
        rules = all_rules()
    findings: list[Finding] = []
    for rel in sorted(set(rel_paths)):
        if not rel.endswith(".py"):
            continue
        findings.extend(_lint_path(root, rel, rules))
    return findings
