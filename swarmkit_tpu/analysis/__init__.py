"""Static/dynamic analysis plane (ISSUE 8): machine-checked CLAUDE.md
invariants.

Three parts (docs/static_analysis.md):

  * `lint`      — AST invariant rules over the tree (pragma-suppressable)
  * `mirror`    — mirrored-tick protocol drift checker (TickPipeline vs
                  Scheduler._tick_pipelined against a checked-in table)
  * `lockgraph` — runtime lock-order detector (armable; the factory seam
                  every threading.Lock/RLock site routes through)

Run standalone over the tree:  python -m swarmkit_tpu.analysis
Tier-1 entry:                  tests/test_lint_clean.py

Kept import-light on purpose: `lockgraph` is imported at module scope by
nearly every package in the tree (the lock factory), so this __init__
must never pull jax-adjacent code.
"""
