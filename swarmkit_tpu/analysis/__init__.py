"""Static/dynamic analysis plane (ISSUE 8, dataflow engine ISSUE 12):
machine-checked CLAUDE.md invariants.

Four parts (docs/static_analysis.md):

  * `lint`      — syntactic AST invariant rules over the tree
                  (pragma-suppressable); `lint.all_rules()` is the full
                  set including the dataflow rules
  * `dataflow`  — per-function CFG + forward taint engine; the
                  flow-sensitive contract rules (store-copy-dataflow,
                  dirty-feed, barrier-before-drain) ride it
  * `mirror`    — mirrored-pair drift registry (tick protocol,
                  scalar-vs-batched allocator twins, eager-vs-lazy
                  assign_wave) against checked-in tables
  * `lockgraph` — runtime lock-order detector (armable; the factory
                  seam every threading.Lock/RLock/Condition site
                  routes through)

Run standalone over the tree:  python -m swarmkit_tpu.analysis
  (--json machine output, --changed-only git-scoped edit-loop mode,
   --print-protocol mirror re-record; exit 0 clean / 1 findings /
   2 internal error)
Tier-1 entry:                  tests/test_lint_clean.py

Kept import-light on purpose: `lockgraph` is imported at module scope by
nearly every package in the tree (the lock factory), so this __init__
must never pull jax-adjacent code.
"""
