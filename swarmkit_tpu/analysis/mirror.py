"""Mirrored-pair drift registry (ISSUE 8 tick pair, generalized in
ISSUE 12).

Several protocols in this tree live in TWO implementations that must
change in lockstep:

  * `tick` — the pipelined tick protocol: `TickPipeline`
    (ops/pipeline.py) vs `Scheduler._tick_pipelined`
    (scheduler/scheduler.py). A barrier moved, a poison dropped, or a
    drain trigger added in one mirror and not the other is exactly the
    class of bug convention alone has to catch.
  * `ipam-pool` — the scalar IPAM pool oracle (allocator/ipam.py
    `_Pool`) vs its array twin (allocator/batched.py `_ArrayPool`):
    grants, cursor motion, exhaustion and release must stay
    bit-identical (the ≥20-seed fuzz pins values; this registry pins
    the code SHAPE so a one-sided edit is caught before the fuzz run).
  * `port-alloc` — scalar `PortAllocator` vs `BatchedPorts`: the
    owner-conflict precheck, dynamic-run grants and the partial-grant
    failure shape.
  * `assign-wave` — the eager (`_assign_in_tx`) vs lazy
    (`_assign_wave_lazy` + `_heal_stale_locked`) wave write-back in
    store/memory.py: both must keep riding the SHARED `_wave_verdicts`
    and the same patch primitive, or their verdict sequences drift.

The checker extracts, from each member's AST, the lexically-ordered
sequence of PROTOCOL calls — normalized to a per-pair canonical event
language (plus `return` events where the return shape IS the protocol,
e.g. the port allocator's partial-failure returns) — and diffs it
against the checked-in expected table below. A change landing in one
member fails `tests/test_lint_clean.py` with a readable unified diff;
the author then either updates BOTH members or consciously re-records
the table (and the diff shows the reviewer exactly which step moved).

Lexical order is the contract here, not runtime order: the extraction
is deterministic, and every protocol-relevant statement in these
methods executes at most once per trigger, so source order is a
faithful proxy the test can pin.

Beyond the per-member sequences, each spec's `required` set pins the
event KINDS that member must contain — a one-sided removal of (say)
every poison call fails even if someone re-records that member's table
without noticing the asymmetry.

Registering a new pair: define a vocab (call name -> canonical event),
add one MirrorSpec per member (same `pair` key) to MIRRORS with the
pair's `required` event set, run
`python -m swarmkit_tpu.analysis --print-protocol` and paste the new
EXPECTED entries, then add a one-sided-edit drift fixture to
tests/test_analysis.py and a row to docs/static_analysis.md.
"""
from __future__ import annotations

import ast
import difflib
from dataclasses import dataclass
from pathlib import Path

# ---------------------------------------------------------------- vocabulary
# call-name -> canonical event. Keys match either the bare attribute /
# function name ("fold_counts") or a receiver-qualified form ("h.get")
# when the bare name is too generic to key on (dict.get, worker.submit).
_COMMON_VOCAB = {
    "fold_counts": "fold",
    "fold_problem": "fold_problem",
    "after_apply": "after_apply",
    "invalidate": "invalidate",
    "needs_full_upload": "needs_full_upload",
    "restamp_counts": "restamp",
    "force_numeric_reencode": "poison_rows",
    "poison_all_numeric": "poison_all",
    "nodes_clean": "nodes_clean",
    "encode": "encode",
    "schedule_async": "dispatch",
}

PIPELINE_VOCAB = dict(_COMMON_VOCAB, **{
    "_barrier": "barrier",
    "_pull_oldest": "pull",
    "_fold_pulled": "fold_pulled",
    "_complete": "complete",
    "_heavy": "commit_heavy",
    "_commit": "commit_inline",
    "commit_cb": "commit_cb",
    "_hazards": "hazard_check",
    "worker.submit": "submit_heavy",
    "worker.barrier": "barrier",
    "finish_pulled": "finish_pulled",
    "commit_deferred": "commit_deferred",
    "drain_serial": "drain_serial",
})

SCHEDULER_VOCAB = dict(_COMMON_VOCAB, **{
    "worker.barrier": "barrier",
    "_drain_commit_plane": "barrier",
    "h.get": "pull",
    "h2.get": "pull_discard",
    "_submit_heavy": "submit_heavy",
    "_commit_heavy": "commit_heavy",
    "_heal_unclean": "heal_unclean",
    "_process_preassigned": "preassigned",
    "_schedule_backlog": "backlog",
    "materialize_orders": "materialize",
    "_apply_decisions": "apply_decisions",
    "_tick_pipelined": "tick_pipelined",
})

# Event kinds BOTH tick mirrors must exhibit somewhere in their scope:
# a one-sided disappearance of any of these is protocol drift even if
# the per-mirror table is re-recorded to match.
REQUIRED_COMMON = frozenset({
    "barrier", "pull", "fold", "after_apply", "invalidate",
    "poison_rows", "restamp", "submit_heavy", "nodes_clean",
    "encode", "dispatch",
})

# --------------------------------------------------- allocator-twin pairs
# scalar IPAM pool vs the array twin: grants/exhaustion/release shape
_POOL_VOCAB = {
    "IPAMError": "error",            # exhaustion / out-of-subnet raise
    "ip_address": "parse",
    "grant_order": "grant_order",    # array twin's kernel call
    "allocated.add": "mark",
    "allocated.discard": "unmark",
}
REQUIRED_POOL = frozenset({"error", "parse", "return"})

# scalar PortAllocator vs BatchedPorts: owner precheck, dynamic runs,
# partial-failure returns
_PORTS_VOCAB = {
    "_allocated.get": "owner_check",
    "_find_dynamic": "dynamic",
    "_grant_dynamic_run": "dynamic",
    "_claim": "claim",
    "_unclaim": "unclaim",
    "grant_order": "grant_order",
    "_mask": "mask",
}
REQUIRED_PORTS = frozenset({"owner_check", "dynamic", "return"})

# leader vs follower assignment serving (ISSUE 13): the Dispatcher's
# serve path and the FollowerReadPlane's must ride the SHARED
# snapshot/build vocabulary — one store.view snapshot, _node_view build,
# clone materialization, _diff, delivery-gated _commit_known. The
# building blocks are literally shared (the follower aliases the
# Dispatcher methods); this pair pins the serve PROTOCOL around them,
# plus the follower's lease gate (its spec's `required` adds
# `lease_gate` on top of the common floor).
_SERVE_VOCAB = {
    "store.view": "snapshot",
    "_node_view": "build",
    "_materialize_clones": "materialize",
    "_diff": "diff",
    "_offer": "offer",
    "_commit_known": "commit_known",
    "commit": "commit_known",     # the diff's delivery-gated closure
    "_ship_task": "ship",
    "_ship": "ship",
    "_serve_session": "serve",
    "_serve_shard": "serve_shard",
    "read_ok": "lease_gate",
    "_require_lease": "lease_gate",
}
REQUIRED_SERVE = frozenset({"snapshot", "build", "materialize", "diff",
                            "offer", "commit_known"})

# scalar vs batched orchestration plane (ISSUE 14): the replicated
# reconciler (ReplicatedOrchestrator vs BatchedReconciler) and the
# rolling updater (threaded Updater vs UpdateWavePlanner) each live in
# two implementations that must keep riding the SHARED slot-diff /
# verdict vocabulary — decide_service / fill_slots / victim_order on
# the reconcile side, the updater.py slot-flip helpers + finalize_update
# on the update side. A store-write path grown privately in one member
# (bypassing create_replacement/promote_task/finalize_update) is exactly
# the drift this pair exists to catch.
_ORCH_VOCAB = {
    "decide_service": "decide",
    "fill_slots": "fill",
    "victim_order": "victims",
    "compute_slot_state": "census",
    "updater.update": "feed",
    "_dirty_slots": "dirty",
    "dirty_slots": "dirty",
    "_create_replacement": "create",
    "create_replacement": "create",
    "_shutdown_tasks": "shutdown",
    "shutdown_tasks": "shutdown",
    "_remove_task": "remove",
    "remove_task": "remove",
    "_promote": "promote",
    "promote_task": "promote",
    "finalize_update": "verdict",
    "_set_update_status": "status",
    "set_update_status": "status",
    "over_threshold": "threshold",
    "poll_failures": "monitor",
}
REQUIRED_ORCH_RECONCILE = frozenset({"decide"})
REQUIRED_ORCH_UPDATE = frozenset({
    "dirty", "create", "shutdown", "remove", "promote", "verdict",
    "status", "threshold", "monitor"})

# eager vs lazy assign_wave (store/memory.py): both ride the SHARED
# verdict helper and the same patch primitive
_ASSIGN_VOCAB = {
    "_wave_verdicts": "verdicts",
    "wave_codes": "codes",
    "_patch_assign": "patch",
    "assign_rows": "scatter",
    "has_watchers": "watcher_gate",
    "_heal_stale_locked": "heal",
    "publish_all": "publish",
    "row_of": "row_of",
    "intern": "intern",
}
REQUIRED_ASSIGN = frozenset({"verdicts", "codes", "patch"})


@dataclass(frozen=True)
class MirrorSpec:
    key: str
    path: str                    # repo-relative posix
    class_name: str
    methods: tuple               # extraction scope, in this order
    vocab: dict
    pair: str = "tick"           # registry group (drift is per-member;
                                 # `required` is the pair's common floor)
    required: frozenset = REQUIRED_COMMON
    capture_returns: bool = False  # emit a 'return' event per Return


MIRRORS: tuple[MirrorSpec, ...] = (
    MirrorSpec(
        key="tick_pipeline",
        path="swarmkit_tpu/ops/pipeline.py",
        class_name="TickPipeline",
        methods=("tick", "_tick_traced", "_pull_oldest", "_fold_pulled",
                 "_complete", "_heavy", "_commit", "_barrier", "flush",
                 "barrier"),
        vocab=PIPELINE_VOCAB,
    ),
    MirrorSpec(
        key="scheduler_tick",
        path="swarmkit_tpu/scheduler/scheduler.py",
        class_name="Scheduler",
        methods=("_tick_pipelined", "flush_pipeline", "_submit_heavy",
                 "_commit_heavy", "_drain_commit_plane", "_heal_unclean"),
        vocab=SCHEDULER_VOCAB,
    ),
    MirrorSpec(
        key="ipam_pool_scalar",
        path="swarmkit_tpu/allocator/ipam.py",
        class_name="_Pool",
        methods=("allocate", "reserve", "release"),
        vocab=_POOL_VOCAB,
        pair="ipam-pool",
        required=REQUIRED_POOL,
        capture_returns=True,
    ),
    MirrorSpec(
        key="ipam_pool_array",
        path="swarmkit_tpu/allocator/batched.py",
        class_name="_ArrayPool",
        methods=("allocate", "allocate_many", "free_count", "reserve",
                 "release"),
        vocab=_POOL_VOCAB,
        pair="ipam-pool",
        required=REQUIRED_POOL,
        capture_returns=True,
    ),
    MirrorSpec(
        key="ports_scalar",
        path="swarmkit_tpu/allocator/allocator.py",
        class_name="PortAllocator",
        methods=("allocate", "_find_dynamic", "release",
                 "release_except"),
        vocab=_PORTS_VOCAB,
        pair="port-alloc",
        required=REQUIRED_PORTS,
        capture_returns=True,
    ),
    MirrorSpec(
        key="ports_batched",
        path="swarmkit_tpu/allocator/batched.py",
        class_name="BatchedPorts",
        methods=("allocate", "_grant_dynamic_run", "_find_dynamic",
                 "_claim", "_unclaim", "release", "release_except"),
        vocab=_PORTS_VOCAB,
        pair="port-alloc",
        required=REQUIRED_PORTS,
        capture_returns=True,
    ),
    MirrorSpec(
        key="dispatcher_serve_leader",
        path="swarmkit_tpu/dispatcher/dispatcher.py",
        class_name="Dispatcher",
        methods=("assignments", "_full_assignment", "_incremental",
                 "_send_incrementals", "_serve_shard", "_serve_session"),
        vocab=_SERVE_VOCAB,
        pair="dispatcher-serve",
        required=REQUIRED_SERVE,
    ),
    MirrorSpec(
        key="dispatcher_serve_follower",
        path="swarmkit_tpu/dispatcher/follower.py",
        class_name="FollowerReadPlane",
        methods=("assignments", "_full_assignment",
                 "_send_incrementals", "_serve_session",
                 "_require_lease"),
        vocab=_SERVE_VOCAB,
        pair="dispatcher-serve",
        required=REQUIRED_SERVE | {"lease_gate"},
    ),
    MirrorSpec(
        key="orch_reconcile_scalar",
        path="swarmkit_tpu/orchestrator/replicated.py",
        class_name="ReplicatedOrchestrator",
        methods=("_reconcile_in_tx", "reconcile_many"),
        vocab=_ORCH_VOCAB,
        pair="orch-reconcile",
        required=REQUIRED_ORCH_RECONCILE | {"feed"},
    ),
    MirrorSpec(
        key="orch_reconcile_batched",
        path="swarmkit_tpu/orchestrator/batched.py",
        class_name="BatchedReconciler",
        methods=("decide_many", "_decide_scope", "_dirty_residue",
                 "_decide_scalar"),
        vocab=_ORCH_VOCAB,
        pair="orch-reconcile",
        required=REQUIRED_ORCH_RECONCILE | {"census", "fill", "victims"},
    ),
    MirrorSpec(
        key="orch_update_scalar",
        path="swarmkit_tpu/orchestrator/updater.py",
        class_name="Updater",
        methods=("_run", "_update_slot", "_dirty_slots",
                 "_create_replacement", "_shutdown_tasks", "_remove_task",
                 "_promote"),
        vocab=_ORCH_VOCAB,
        pair="orch-update",
        required=REQUIRED_ORCH_UPDATE,
    ),
    MirrorSpec(
        key="orch_update_planner",
        path="swarmkit_tpu/orchestrator/batched.py",
        class_name="UpdateWavePlanner",
        methods=("_step", "_step_init", "_step_rolling", "_step_drain",
                 "_start_flip", "_advance_slot", "_finish_slot",
                 "_abort_in_flight", "_finalize"),
        vocab=_ORCH_VOCAB,
        pair="orch-update",
        required=REQUIRED_ORCH_UPDATE,
    ),
    MirrorSpec(
        key="assign_wave_eager",
        path="swarmkit_tpu/store/memory.py",
        class_name="MemoryStore",
        methods=("_wave_verdicts", "_assign_in_tx"),
        vocab=_ASSIGN_VOCAB,
        pair="assign-wave",
        required=REQUIRED_ASSIGN,
        capture_returns=True,
    ),
    MirrorSpec(
        key="assign_wave_lazy",
        path="swarmkit_tpu/store/memory.py",
        class_name="MemoryStore",
        methods=("_wave_verdicts", "_assign_wave_lazy",
                 "_heal_stale_locked"),
        vocab=_ASSIGN_VOCAB,
        pair="assign-wave",
        required=REQUIRED_ASSIGN,
        capture_returns=True,
    ),
)


def _call_key(node: ast.Call) -> tuple[str, str]:
    """(qualified, bare) lookup keys for a call node. qualified is
    'recv.attr' when the receiver is a simple name (possibly through
    one level of attribute: self.worker.submit -> 'worker.submit')."""
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id, fn.id
    if isinstance(fn, ast.Attribute):
        bare = fn.attr
        recv = fn.value
        # self.<x>.attr -> '<x>.attr'; <name>.attr -> '<name>.attr'
        if isinstance(recv, ast.Attribute) \
                and isinstance(recv.value, ast.Name) \
                and recv.value.id == "self":
            return f"{recv.attr}.{bare}", bare
        if isinstance(recv, ast.Name):
            rid = recv.id if recv.id != "self" else ""
            return (f"{rid}.{bare}" if rid else bare), bare
        return bare, bare
    return "", ""


def extract_sequence(tree: ast.AST, spec: MirrorSpec) -> list[str]:
    """['method:event', ...] in lexical order, for spec.methods in the
    given order. Nested defs inside a method belong to that method
    (drain_serial & co are part of the tick body's protocol)."""
    cls = None
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == spec.class_name:
            cls = node
            break
    if cls is None:
        raise LookupError(
            f"{spec.path}: class {spec.class_name} not found")
    by_name = {n.name: n for n in cls.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    def dfs(node):
        """Pre-order, source order (ast.walk is BFS — useless for a
        readable protocol diff)."""
        yield node
        for child in ast.iter_child_nodes(node):
            yield from dfs(child)

    out: list[str] = []
    for mname in spec.methods:
        m = by_name.get(mname)
        if m is None:
            out.append(f"{mname}:<MISSING METHOD>")
            continue
        for node in dfs(m):
            if spec.capture_returns and isinstance(node, ast.Return):
                out.append(f"{mname}:return")
                continue
            if not isinstance(node, ast.Call):
                continue
            qual, bare = _call_key(node)
            ev = spec.vocab.get(qual)
            if ev is None:
                ev = spec.vocab.get(bare)
            if ev is not None:
                out.append(f"{mname}:{ev}")
    return out


def extract_from_source(source: str, spec: MirrorSpec) -> list[str]:
    return extract_sequence(ast.parse(source, filename=spec.path), spec)


# ------------------------------------------------------------ expected table
# Re-record ONLY together with a conscious review of BOTH mirrors:
#   python -m swarmkit_tpu.analysis --print-protocol
# prints the freshly-extracted sequences in checked-in form.
EXPECTED: dict[str, tuple[str, ...]] = {
    'tick_pipeline': (
        '_tick_traced:pull',
        '_tick_traced:nodes_clean',
        '_tick_traced:barrier',
        '_tick_traced:fold_pulled',
        '_tick_traced:submit_heavy',
        '_tick_traced:poison_rows',
        '_tick_traced:invalidate',
        '_tick_traced:commit_inline',
        '_tick_traced:barrier',
        '_tick_traced:commit_deferred',
        '_tick_traced:finish_pulled',
        '_tick_traced:commit_inline',
        '_tick_traced:complete',
        '_tick_traced:commit_inline',
        '_tick_traced:nodes_clean',
        '_tick_traced:drain_serial',
        '_tick_traced:finish_pulled',
        '_tick_traced:complete',
        '_tick_traced:hazard_check',
        '_tick_traced:drain_serial',
        '_tick_traced:commit_deferred',
        '_tick_traced:encode',
        '_tick_traced:needs_full_upload',
        '_tick_traced:drain_serial',
        '_tick_traced:encode',
        '_tick_traced:dispatch',
        '_tick_traced:commit_deferred',
        '_fold_pulled:fold_problem',
        '_fold_pulled:invalidate',
        '_fold_pulled:fold',
        '_fold_pulled:invalidate',
        '_fold_pulled:after_apply',
        '_complete:pull',
        '_complete:fold_pulled',
        '_heavy:commit_cb',
        '_heavy:restamp',
        '_commit:commit_heavy',
        '_barrier:barrier',
        '_barrier:barrier',
        'flush:barrier',
        'flush:complete',
        'flush:commit_inline',
        'barrier:barrier',
    ),
    'scheduler_tick': (
        '_tick_pipelined:nodes_clean',
        '_tick_pipelined:pull',
        '_tick_pipelined:barrier',
        '_tick_pipelined:heal_unclean',
        '_tick_pipelined:preassigned',
        '_tick_pipelined:backlog',
        '_tick_pipelined:preassigned',
        '_tick_pipelined:preassigned',
        '_tick_pipelined:pull',
        '_tick_pipelined:fold',
        '_tick_pipelined:after_apply',
        '_tick_pipelined:invalidate',
        '_tick_pipelined:submit_heavy',
        '_tick_pipelined:poison_rows',
        '_tick_pipelined:nodes_clean',
        '_tick_pipelined:encode',
        '_tick_pipelined:dispatch',
        '_tick_pipelined:submit_heavy',
        '_tick_pipelined:barrier',
        '_tick_pipelined:backlog',
        '_tick_pipelined:barrier',
        '_tick_pipelined:materialize',
        '_tick_pipelined:apply_decisions',
        '_tick_pipelined:restamp',
        '_tick_pipelined:poison_rows',
        '_tick_pipelined:invalidate',
        '_tick_pipelined:pull_discard',
        '_tick_pipelined:backlog',
        'flush_pipeline:tick_pipelined',
        'flush_pipeline:barrier',
        '_submit_heavy:commit_heavy',
        '_commit_heavy:materialize',
        '_commit_heavy:apply_decisions',
        '_commit_heavy:restamp',
        '_drain_commit_plane:heal_unclean',
        '_heal_unclean:poison_rows',
        '_heal_unclean:invalidate',
        '_heal_unclean:pull_discard',
    ),
    'ipam_pool_scalar': (
        'allocate:mark',
        'allocate:return',
        'allocate:error',
        'reserve:parse',
        'reserve:error',
        'reserve:mark',
        'release:unmark',
    ),
    'ipam_pool_array': (
        'allocate:return',
        'allocate:error',
        'allocate_many:return',
        'allocate_many:grant_order',
        'allocate_many:error',
        'allocate_many:return',
        'free_count:return',
        'reserve:parse',
        'reserve:error',
        'release:return',
        'release:parse',
        'release:return',
    ),
    'ports_scalar': (
        'allocate:owner_check',
        'allocate:return',
        'allocate:dynamic',
        'allocate:return',
        'allocate:return',
        '_find_dynamic:return',
        '_find_dynamic:return',
        'release_except:return',
    ),
    'ports_batched': (
        'allocate:owner_check',
        'allocate:return',
        'allocate:claim',
        'allocate:dynamic',
        'allocate:claim',
        'allocate:return',
        'allocate:return',
        '_grant_dynamic_run:grant_order',
        '_grant_dynamic_run:mask',
        '_grant_dynamic_run:return',
        '_find_dynamic:dynamic',
        '_find_dynamic:return',
        '_claim:mask',
        '_unclaim:mask',
        'release:unclaim',
        'release_except:unclaim',
        'release_except:return',
    ),
    'dispatcher_serve_leader': (
        'assignments:offer',
        '_full_assignment:snapshot',
        '_full_assignment:build',
        '_full_assignment:materialize',
        '_full_assignment:ship',
        '_full_assignment:ship',
        '_full_assignment:ship',
        '_full_assignment:commit_known',
        '_incremental:snapshot',
        '_incremental:build',
        '_incremental:materialize',
        '_incremental:diff',
        '_incremental:commit_known',
        '_send_incrementals:build',
        '_send_incrementals:snapshot',
        '_send_incrementals:serve_shard',
        '_serve_shard:serve',
        '_serve_shard:commit_known',
        '_serve_session:materialize',
        '_serve_session:diff',
        '_serve_session:offer',
        '_serve_session:offer',
        '_serve_session:ship',
    ),
    'dispatcher_serve_follower': (
        'assignments:lease_gate',
        'assignments:offer',
        '_full_assignment:snapshot',
        '_full_assignment:build',
        '_full_assignment:materialize',
        '_full_assignment:ship',
        '_full_assignment:ship',
        '_full_assignment:ship',
        '_full_assignment:commit_known',
        '_send_incrementals:lease_gate',
        '_send_incrementals:build',
        '_send_incrementals:snapshot',
        '_send_incrementals:serve',
        '_serve_session:materialize',
        '_serve_session:diff',
        '_serve_session:offer',
        '_serve_session:commit_known',
        '_serve_session:offer',
        '_serve_session:ship',
        '_require_lease:lease_gate',
    ),
    'orch_reconcile_scalar': (
        '_reconcile_in_tx:decide',
        '_reconcile_in_tx:feed',
        'reconcile_many:feed',
    ),
    'orch_reconcile_batched': (
        '_decide_scope:census',
        '_decide_scope:fill',
        '_decide_scope:victims',
        '_decide_scalar:decide',
    ),
    'orch_update_scalar': (
        '_run:status',
        '_run:monitor',
        '_run:threshold',
        '_run:dirty',
        '_run:threshold',
        '_run:monitor',
        '_run:threshold',
        '_run:verdict',
        '_run:verdict',
        '_update_slot:create',
        '_update_slot:shutdown',
        '_update_slot:remove',
        '_update_slot:remove',
        '_update_slot:create',
        '_update_slot:promote',
        '_dirty_slots:dirty',
        '_create_replacement:create',
        '_shutdown_tasks:shutdown',
        '_remove_task:remove',
        '_promote:promote',
    ),
    'orch_update_planner': (
        '_step_init:status',
        '_step_rolling:monitor',
        '_step_rolling:threshold',
        '_step_rolling:dirty',
        '_step_drain:monitor',
        '_step_drain:threshold',
        '_start_flip:create',
        '_start_flip:create',
        '_advance_slot:shutdown',
        '_advance_slot:remove',
        '_advance_slot:promote',
        '_abort_in_flight:remove',
        '_abort_in_flight:promote',
        '_finalize:verdict',
        '_finalize:threshold',
    ),
    'assign_wave_eager': (
        '_wave_verdicts:codes',
        '_wave_verdicts:return',
        '_assign_in_tx:return',
        '_assign_in_tx:patch',
        '_assign_in_tx:verdicts',
    ),
    'assign_wave_lazy': (
        '_wave_verdicts:codes',
        '_wave_verdicts:return',
        '_assign_wave_lazy:watcher_gate',
        '_assign_wave_lazy:return',
        '_assign_wave_lazy:intern',
        '_assign_wave_lazy:verdicts',
        '_assign_wave_lazy:scatter',
        '_assign_wave_lazy:watcher_gate',
        '_assign_wave_lazy:heal',
        '_assign_wave_lazy:publish',
        '_assign_wave_lazy:return',
        '_heal_stale_locked:return',
        '_heal_stale_locked:row_of',
        '_heal_stale_locked:patch',
        '_heal_stale_locked:return',
    ),
}


@dataclass
class DriftReport:
    diffs: dict          # mirror key -> unified diff text (only drifted)
    missing_common: dict  # mirror key -> sorted missing required events
    pair_of: dict = None  # mirror key -> pair name (report labels)

    @property
    def clean(self) -> bool:
        return not self.diffs and not self.missing_common

    def render(self) -> str:
        if self.clean:
            return ("mirror drift: clean (all registered pairs match "
                    "the table)")
        pair_of = self.pair_of or {}
        out = []
        for key, diff in self.diffs.items():
            pair = pair_of.get(key, "tick")
            out.append(
                f"protocol drift in mirror {key!r} (pair {pair!r}) — "
                "this protocol lives in TWO implementations that must "
                "change in lockstep; land the change in BOTH members, "
                "then re-record with "
                "`python -m swarmkit_tpu.analysis --print-protocol`:")
            out.append(diff)
        for key, missing in self.missing_common.items():
            out.append(
                f"mirror {key!r} lost required protocol events: "
                f"{', '.join(missing)}")
        return "\n".join(out)


def check_drift(root: Path,
                sources: dict[str, str] | None = None,
                expected: dict[str, tuple[str, ...]] | None = None,
                specs: tuple | None = None,
                ) -> DriftReport:
    """Diff each registered mirror's extracted sequence against the
    expected table. `sources` overrides file contents per mirror key
    (fixture tests); `expected` overrides the table (recording flows);
    `specs` narrows the registry (the --changed-only scope — always
    whole PAIRS, never a single member)."""
    expected = EXPECTED if expected is None else expected
    diffs: dict[str, str] = {}
    missing_common: dict[str, list[str]] = {}
    for spec in (MIRRORS if specs is None else specs):
        if sources is not None and spec.key in sources:
            src = sources[spec.key]
        else:
            src = (root / spec.path).read_text()
        seq = extract_from_source(src, spec)
        want = list(expected.get(spec.key, ()))
        if seq != want:
            diff = "\n".join(difflib.unified_diff(
                want, seq, fromfile=f"{spec.key} (expected table)",
                tofile=f"{spec.key} ({spec.path})", lineterm=""))
            diffs[spec.key] = diff
        events = {s.split(":", 1)[1] for s in seq}
        miss = sorted(spec.required - events)
        if miss:
            missing_common[spec.key] = miss
    return DriftReport(diffs=diffs, missing_common=missing_common,
                       pair_of={s.key: s.pair for s in MIRRORS})


def record(root: Path) -> str:
    """The checked-in form of the freshly-extracted table (the
    --print-protocol flow)."""
    lines = ["EXPECTED: dict[str, tuple[str, ...]] = {"]
    for spec in MIRRORS:
        src = (root / spec.path).read_text()
        seq = extract_from_source(src, spec)
        lines.append(f"    {spec.key!r}: (")
        for s in seq:
            lines.append(f"        {s!r},")
        lines.append("    ),")
    lines.append("}")
    return "\n".join(lines)
