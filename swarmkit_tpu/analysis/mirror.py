"""Mirrored-implementation drift checker.

The pipelined tick protocol lives in TWO implementations that must
change in lockstep (CLAUDE.md async-commit invariant): the reusable
`TickPipeline` (ops/pipeline.py) and the production
`Scheduler._tick_pipelined` (scheduler/scheduler.py). A barrier moved,
a poison dropped, or a drain trigger added in one mirror and not the
other is exactly the class of bug convention alone has to catch today.

This module extracts, from each mirror's AST, the lexically-ordered
sequence of PROTOCOL calls — the barrier/pull/fold/poison/restamp/
submit/encode/dispatch vocabulary — normalized to a shared canonical
event language, and diffs it against the checked-in expected table
below. A change landing in one mirror fails `tests/test_lint_clean.py`
with a readable unified diff; the author then either updates BOTH
mirrors or consciously re-records the table (and the diff shows the
reviewer exactly which protocol step moved).

Lexical order is the contract here, not runtime order: the extraction
is deterministic, and every protocol-relevant statement in these
methods executes at most once per trigger, so source order is a
faithful proxy the test can pin.

Beyond the per-mirror sequences, REQUIRED_COMMON pins the event KINDS
both mirrors must contain — a one-sided removal of (say) every poison
call fails even if someone re-records that mirror's table without
noticing the asymmetry.
"""
from __future__ import annotations

import ast
import difflib
from dataclasses import dataclass
from pathlib import Path

# ---------------------------------------------------------------- vocabulary
# call-name -> canonical event. Keys match either the bare attribute /
# function name ("fold_counts") or a receiver-qualified form ("h.get")
# when the bare name is too generic to key on (dict.get, worker.submit).
_COMMON_VOCAB = {
    "fold_counts": "fold",
    "fold_problem": "fold_problem",
    "after_apply": "after_apply",
    "invalidate": "invalidate",
    "needs_full_upload": "needs_full_upload",
    "restamp_counts": "restamp",
    "force_numeric_reencode": "poison_rows",
    "poison_all_numeric": "poison_all",
    "nodes_clean": "nodes_clean",
    "encode": "encode",
    "schedule_async": "dispatch",
}

PIPELINE_VOCAB = dict(_COMMON_VOCAB, **{
    "_barrier": "barrier",
    "_pull_oldest": "pull",
    "_fold_pulled": "fold_pulled",
    "_complete": "complete",
    "_heavy": "commit_heavy",
    "_commit": "commit_inline",
    "commit_cb": "commit_cb",
    "_hazards": "hazard_check",
    "worker.submit": "submit_heavy",
    "worker.barrier": "barrier",
    "finish_pulled": "finish_pulled",
    "commit_deferred": "commit_deferred",
    "drain_serial": "drain_serial",
})

SCHEDULER_VOCAB = dict(_COMMON_VOCAB, **{
    "worker.barrier": "barrier",
    "_drain_commit_plane": "barrier",
    "h.get": "pull",
    "h2.get": "pull_discard",
    "_submit_heavy": "submit_heavy",
    "_commit_heavy": "commit_heavy",
    "_heal_unclean": "heal_unclean",
    "_process_preassigned": "preassigned",
    "_schedule_backlog": "backlog",
    "materialize_orders": "materialize",
    "_apply_decisions": "apply_decisions",
    "_tick_pipelined": "tick_pipelined",
})

# Event kinds BOTH mirrors must exhibit somewhere in their scope: a
# one-sided disappearance of any of these is protocol drift even if the
# per-mirror table is re-recorded to match.
REQUIRED_COMMON = frozenset({
    "barrier", "pull", "fold", "after_apply", "invalidate",
    "poison_rows", "restamp", "submit_heavy", "nodes_clean",
    "encode", "dispatch",
})


@dataclass(frozen=True)
class MirrorSpec:
    key: str
    path: str                    # repo-relative posix
    class_name: str
    methods: tuple               # extraction scope, in this order
    vocab: dict


MIRRORS: tuple[MirrorSpec, ...] = (
    MirrorSpec(
        key="tick_pipeline",
        path="swarmkit_tpu/ops/pipeline.py",
        class_name="TickPipeline",
        methods=("tick", "_tick_traced", "_pull_oldest", "_fold_pulled",
                 "_complete", "_heavy", "_commit", "_barrier", "flush",
                 "barrier"),
        vocab=PIPELINE_VOCAB,
    ),
    MirrorSpec(
        key="scheduler_tick",
        path="swarmkit_tpu/scheduler/scheduler.py",
        class_name="Scheduler",
        methods=("_tick_pipelined", "flush_pipeline", "_submit_heavy",
                 "_commit_heavy", "_drain_commit_plane", "_heal_unclean"),
        vocab=SCHEDULER_VOCAB,
    ),
)


def _call_key(node: ast.Call) -> tuple[str, str]:
    """(qualified, bare) lookup keys for a call node. qualified is
    'recv.attr' when the receiver is a simple name (possibly through
    one level of attribute: self.worker.submit -> 'worker.submit')."""
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id, fn.id
    if isinstance(fn, ast.Attribute):
        bare = fn.attr
        recv = fn.value
        # self.<x>.attr -> '<x>.attr'; <name>.attr -> '<name>.attr'
        if isinstance(recv, ast.Attribute) \
                and isinstance(recv.value, ast.Name) \
                and recv.value.id == "self":
            return f"{recv.attr}.{bare}", bare
        if isinstance(recv, ast.Name):
            rid = recv.id if recv.id != "self" else ""
            return (f"{rid}.{bare}" if rid else bare), bare
        return bare, bare
    return "", ""


def extract_sequence(tree: ast.AST, spec: MirrorSpec) -> list[str]:
    """['method:event', ...] in lexical order, for spec.methods in the
    given order. Nested defs inside a method belong to that method
    (drain_serial & co are part of the tick body's protocol)."""
    cls = None
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == spec.class_name:
            cls = node
            break
    if cls is None:
        raise LookupError(
            f"{spec.path}: class {spec.class_name} not found")
    by_name = {n.name: n for n in cls.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    def dfs(node):
        """Pre-order, source order (ast.walk is BFS — useless for a
        readable protocol diff)."""
        yield node
        for child in ast.iter_child_nodes(node):
            yield from dfs(child)

    out: list[str] = []
    for mname in spec.methods:
        m = by_name.get(mname)
        if m is None:
            out.append(f"{mname}:<MISSING METHOD>")
            continue
        for node in dfs(m):
            if not isinstance(node, ast.Call):
                continue
            qual, bare = _call_key(node)
            ev = spec.vocab.get(qual)
            if ev is None:
                ev = spec.vocab.get(bare)
            if ev is not None:
                out.append(f"{mname}:{ev}")
    return out


def extract_from_source(source: str, spec: MirrorSpec) -> list[str]:
    return extract_sequence(ast.parse(source, filename=spec.path), spec)


# ------------------------------------------------------------ expected table
# Re-record ONLY together with a conscious review of BOTH mirrors:
#   python -m swarmkit_tpu.analysis --print-protocol
# prints the freshly-extracted sequences in checked-in form.
EXPECTED: dict[str, tuple[str, ...]] = {
    'tick_pipeline': (
        '_tick_traced:pull',
        '_tick_traced:nodes_clean',
        '_tick_traced:barrier',
        '_tick_traced:fold_pulled',
        '_tick_traced:submit_heavy',
        '_tick_traced:poison_rows',
        '_tick_traced:invalidate',
        '_tick_traced:commit_inline',
        '_tick_traced:barrier',
        '_tick_traced:commit_deferred',
        '_tick_traced:finish_pulled',
        '_tick_traced:commit_inline',
        '_tick_traced:complete',
        '_tick_traced:commit_inline',
        '_tick_traced:nodes_clean',
        '_tick_traced:drain_serial',
        '_tick_traced:finish_pulled',
        '_tick_traced:complete',
        '_tick_traced:hazard_check',
        '_tick_traced:drain_serial',
        '_tick_traced:commit_deferred',
        '_tick_traced:encode',
        '_tick_traced:needs_full_upload',
        '_tick_traced:drain_serial',
        '_tick_traced:encode',
        '_tick_traced:dispatch',
        '_tick_traced:commit_deferred',
        '_fold_pulled:fold_problem',
        '_fold_pulled:invalidate',
        '_fold_pulled:fold',
        '_fold_pulled:invalidate',
        '_fold_pulled:after_apply',
        '_complete:pull',
        '_complete:fold_pulled',
        '_heavy:commit_cb',
        '_heavy:restamp',
        '_commit:commit_heavy',
        '_barrier:barrier',
        '_barrier:barrier',
        'flush:barrier',
        'flush:complete',
        'flush:commit_inline',
        'barrier:barrier',
    ),
    'scheduler_tick': (
        '_tick_pipelined:nodes_clean',
        '_tick_pipelined:pull',
        '_tick_pipelined:barrier',
        '_tick_pipelined:heal_unclean',
        '_tick_pipelined:preassigned',
        '_tick_pipelined:backlog',
        '_tick_pipelined:preassigned',
        '_tick_pipelined:preassigned',
        '_tick_pipelined:pull',
        '_tick_pipelined:fold',
        '_tick_pipelined:after_apply',
        '_tick_pipelined:invalidate',
        '_tick_pipelined:submit_heavy',
        '_tick_pipelined:poison_rows',
        '_tick_pipelined:nodes_clean',
        '_tick_pipelined:encode',
        '_tick_pipelined:dispatch',
        '_tick_pipelined:submit_heavy',
        '_tick_pipelined:barrier',
        '_tick_pipelined:backlog',
        '_tick_pipelined:barrier',
        '_tick_pipelined:materialize',
        '_tick_pipelined:apply_decisions',
        '_tick_pipelined:restamp',
        '_tick_pipelined:poison_rows',
        '_tick_pipelined:invalidate',
        '_tick_pipelined:pull_discard',
        '_tick_pipelined:backlog',
        'flush_pipeline:tick_pipelined',
        'flush_pipeline:barrier',
        '_submit_heavy:commit_heavy',
        '_commit_heavy:materialize',
        '_commit_heavy:apply_decisions',
        '_commit_heavy:restamp',
        '_drain_commit_plane:heal_unclean',
        '_heal_unclean:poison_rows',
        '_heal_unclean:invalidate',
        '_heal_unclean:pull_discard',
    ),
}


@dataclass
class DriftReport:
    diffs: dict          # mirror key -> unified diff text (only drifted)
    missing_common: dict  # mirror key -> sorted missing REQUIRED_COMMON

    @property
    def clean(self) -> bool:
        return not self.diffs and not self.missing_common

    def render(self) -> str:
        if self.clean:
            return "mirror drift: clean (both tick mirrors match the table)"
        out = []
        for key, diff in self.diffs.items():
            out.append(
                f"protocol drift in mirror {key!r} — the tick protocol "
                "lives in TWO implementations (TickPipeline and "
                "Scheduler._tick_pipelined); land the change in BOTH, "
                "then re-record with "
                "`python -m swarmkit_tpu.analysis --print-protocol`:")
            out.append(diff)
        for key, missing in self.missing_common.items():
            out.append(
                f"mirror {key!r} lost required protocol events: "
                f"{', '.join(missing)}")
        return "\n".join(out)


def check_drift(root: Path,
                sources: dict[str, str] | None = None,
                expected: dict[str, tuple[str, ...]] | None = None,
                ) -> DriftReport:
    """Diff each mirror's extracted sequence against the expected table.
    `sources` overrides file contents per mirror key (fixture tests);
    `expected` overrides the table (recording flows)."""
    expected = EXPECTED if expected is None else expected
    diffs: dict[str, str] = {}
    missing_common: dict[str, list[str]] = {}
    for spec in MIRRORS:
        if sources is not None and spec.key in sources:
            src = sources[spec.key]
        else:
            src = (root / spec.path).read_text()
        seq = extract_from_source(src, spec)
        want = list(expected.get(spec.key, ()))
        if seq != want:
            diff = "\n".join(difflib.unified_diff(
                want, seq, fromfile=f"{spec.key} (expected table)",
                tofile=f"{spec.key} ({spec.path})", lineterm=""))
            diffs[spec.key] = diff
        events = {s.split(":", 1)[1] for s in seq}
        miss = sorted(REQUIRED_COMMON - events)
        if miss:
            missing_common[spec.key] = miss
    return DriftReport(diffs=diffs, missing_common=missing_common)


def record(root: Path) -> str:
    """The checked-in form of the freshly-extracted table (the
    --print-protocol flow)."""
    lines = ["EXPECTED: dict[str, tuple[str, ...]] = {"]
    for spec in MIRRORS:
        src = (root / spec.path).read_text()
        seq = extract_from_source(src, spec)
        lines.append(f"    {spec.key!r}: (")
        for s in seq:
            lines.append(f"        {s!r},")
        lines.append("    ),")
    lines.append("}")
    return "\n".join(lines)
