"""CLI entry: `python -m swarmkit_tpu.analysis [options] [ROOT]`.

Exit codes (pinned by tests/test_lint_clean.py):

    0   clean — no lint/dataflow findings (modulo pragmas) and every
        registered mirror pair matches the checked-in protocol table
    1   findings — one per line (or a JSON document with --json)
    2   internal error — the analysis itself crashed (traceback on
        stderr); distinct from "the tree has findings" so CI can tell
        a broken gate from a dirty tree

Options:

    --print-protocol   print the freshly extracted mirror table in
                       checked-in form (the re-record flow after a
                       conscious both-members change)
    --json             machine-readable findings: {"findings": [...],
                       "mirror": {...}, "rules": N, "clean": bool}
    --changed-only     lint only files reported changed by git
                       (`git status --porcelain`), and check only the
                       mirror pairs whose member files changed — the
                       edit-loop mode. Every rule is per-file, so the
                       scoped pass agrees with the full pass on every
                       shared file (tier-1's scope-soundness guard
                       pins it); falls back to the full pass when git
                       is unavailable.
"""
from __future__ import annotations

import argparse
import json
import subprocess
import sys
import traceback
from pathlib import Path

from . import lint, mirror

EXIT_CLEAN = 0
EXIT_FINDINGS = 1
EXIT_INTERNAL = 2


def changed_files(root: Path) -> list[str] | None:
    """ROOT-relative .py paths with uncommitted changes (staged,
    unstaged, untracked), or None when git is unavailable / not a
    repo (caller falls back to the full pass). `git status` paths are
    TOPLEVEL-relative — when `root` sits below the git toplevel they
    must be re-anchored, or every path fails the scope filter and a
    dirty tree silently passes."""
    try:
        proc = subprocess.run(
            ["git", "status", "--porcelain", "-uall"],
            cwd=str(root), capture_output=True, text=True, timeout=30)
        top = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"],
            cwd=str(root), capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if proc.returncode != 0 or top.returncode != 0:
        return None
    toplevel = Path(top.stdout.strip())
    root_res = root.resolve()
    out: list[str] = []
    for line in proc.stdout.splitlines():
        if len(line) < 4:
            continue
        path = line[3:].strip()
        if " -> " in path:                  # rename: take the new side
            path = path.split(" -> ", 1)[1]
        path = path.strip('"')
        if not path.endswith(".py"):
            continue
        try:
            rel = (toplevel / path).resolve().relative_to(root_res)
        except ValueError:
            continue                        # changed, but outside root
        out.append(rel.as_posix())
    return out


def run(root: Path, changed_only: bool = False) -> dict:
    """One full (or git-scoped) analysis pass; returns the result
    document the CLI renders as text or JSON."""
    scope: list[str] | None = None
    if changed_only:
        scope = changed_files(root)
    if scope is None:
        findings = lint.lint_tree(root)
        specs = mirror.MIRRORS
    else:
        in_tree = [p for p in scope
                   if p.startswith(("swarmkit_tpu/", "tests/"))]
        findings = lint.lint_files(root, in_tree)
        changed = set(in_tree)
        # a pair is re-checked when ANY member file changed: a
        # one-sided edit must fail even though the other member's
        # file is untouched
        pairs = {s.pair for s in mirror.MIRRORS if s.path in changed}
        specs = tuple(s for s in mirror.MIRRORS if s.pair in pairs)
    if specs:
        drift = mirror.check_drift(root, specs=specs)
    else:
        drift = mirror.DriftReport(diffs={}, missing_common={})
    return {
        "clean": not findings and drift.clean,
        "findings": [
            {"rule": f.rule, "path": f.path, "line": f.line,
             "message": f.message} for f in findings],
        "mirror": {
            "clean": drift.clean,
            "diffs": dict(drift.diffs),
            "missing_common": {k: list(v)
                               for k, v in drift.missing_common.items()},
        },
        "rules": len(lint.all_rules()),
        "scoped": scope is not None,
        "scope": sorted(scope) if scope is not None else None,
        "_render": ([f.render() for f in findings], drift.render()),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m swarmkit_tpu.analysis")
    ap.add_argument("root", nargs="?", default=None,
                    help="repo root (default: auto-detect from package)")
    ap.add_argument("--print-protocol", action="store_true",
                    help="print the extracted mirror protocol table "
                         "(paste into analysis/mirror.py EXPECTED)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings on stdout")
    ap.add_argument("--changed-only", action="store_true",
                    help="lint only git-changed files (+ the mirror "
                         "pairs they belong to)")
    args = ap.parse_args(argv)

    root = Path(args.root) if args.root else \
        Path(__file__).resolve().parents[2]
    try:
        if args.print_protocol:
            print(mirror.record(root))
            return EXIT_CLEAN

        doc = run(root, changed_only=args.changed_only)
        finding_lines, drift_text = doc.pop("_render")
        if args.as_json:
            print(json.dumps(doc, indent=2, sort_keys=True))
        else:
            for line in finding_lines:
                print(line)
            print(drift_text)
            if not finding_lines:
                scope_note = " (changed-only scope)" if doc["scoped"] \
                    else ""
                print(f"lint: clean ({doc['rules']} rules over "
                      f"swarmkit_tpu/ + tests/{scope_note})")
        return EXIT_CLEAN if doc["clean"] else EXIT_FINDINGS
    except Exception:
        traceback.print_exc()
        return EXIT_INTERNAL


if __name__ == "__main__":
    sys.exit(main())
