"""CLI entry: `python -m swarmkit_tpu.analysis [--print-protocol] [ROOT]`.

Exit 0 when the tree is clean (lint findings modulo pragmas == 0 and
both tick mirrors match the checked-in protocol table); exit 1 with one
finding per line otherwise. `--print-protocol` prints the freshly
extracted mirror table in checked-in form (the re-record flow after a
conscious both-mirror change).
"""
from __future__ import annotations

import argparse
import sys
from pathlib import Path

from . import lint, mirror


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m swarmkit_tpu.analysis")
    ap.add_argument("root", nargs="?", default=None,
                    help="repo root (default: auto-detect from package)")
    ap.add_argument("--print-protocol", action="store_true",
                    help="print the extracted mirror protocol table "
                         "(paste into analysis/mirror.py EXPECTED)")
    args = ap.parse_args(argv)

    root = Path(args.root) if args.root else \
        Path(__file__).resolve().parents[2]
    if args.print_protocol:
        print(mirror.record(root))
        return 0

    failed = False
    findings = lint.lint_tree(root)
    for f in findings:
        print(f.render())
    if findings:
        failed = True
    drift = mirror.check_drift(root)
    print(drift.render())
    if not drift.clean:
        failed = True
    if not findings:
        print(f"lint: clean ({len(lint.RULES)} rules over "
              "swarmkit_tpu/ + tests/)")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
