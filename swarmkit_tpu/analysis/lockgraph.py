"""Runtime lock-order detector: the dynamic third of the analysis plane.

The dispatcher-lock-vs-`store.view` deadlock (PR 4, found live) is the
motivating bug class: two locks acquired in opposite orders on two
threads deadlock only under the right interleaving, which no AST rule
can see. This module turns every lock acquisition in an ARMED test run
into an edge of a global acquisition-order graph and reports

  * cycles — lock A held while acquiring B on one thread, B held while
    acquiring A on another: a potential deadlock even if this run never
    interleaved into one;
  * the specific "dispatcher lock acquired while a `store.view` callback
    is open" hazard (the PR 4 inversion: RPC paths hold the dispatcher
    lock ACROSS store.view, so store->dispatcher is the deadly order).

Discipline mirrors utils/failpoints.py / utils/trace.py exactly:

  * DISARMED cost is one module-global truthiness test. `make_lock()`
    and `make_rlock()` return a *plain* `threading.Lock`/`RLock` when
    `_STATE` is None — production acquires stay native C, zero wrapper
    allocations (bench.py's `lint_plane` row pins this).
  * Armed per-test via `armed()`/`arm()`/`disarm()`; the conftest arms
    the daemon/dispatcher/chaos tiers and FAILS tests that leak an
    armed detector.
  * Locks created while disarmed stay plain forever (module-global
    registry locks, import-time singletons): the detector covers locks
    created inside the armed window, which per-test arming makes the
    entire object graph under test.

Edges are keyed by lock *instance*, not name — three raft nodes in one
process each own a storage lock named "raft.storage", and node A's
storage held while touching node B's transport is a same-name edge that
is NOT a self-deadlock. A cycle among concrete instances is a genuine
inversion. Names label the report.

The detector's own bookkeeping takes only a private leaf lock (edge-set
mutation) and thread-local held-stacks — it can never participate in a
cycle it would report.

See docs/static_analysis.md for the arming contract and rule table.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager
from dataclasses import dataclass, field

# None = disarmed (the ONE module-global truthiness test on every
# factory call and view hook); a _GraphState while armed.
_STATE: "_GraphState | None" = None
_ARM_LOCK = threading.Lock()

# Lock names whose acquisition inside an open store.view callback is a
# known deadlock hazard (the PR 4 inversion). Extend via arm(hazard_names=).
# The follower read plane holds its lock ACROSS store.view exactly like
# the dispatcher does, so it shares the inversion class (ISSUE 13).
DEFAULT_HAZARD_NAMES = frozenset({"dispatcher.lock",
                                  "dispatcher.follower.lock"})

# Name PREFIXES with the same hazard semantics: the sharded fan-out
# plane's locks are indexed ("dispatcher.shard0.lock", ...), so the
# detector keys on the prefix instead of enumerating every shard
# (ISSUE 13). The log fan-out plane's shard locks (ISSUE 20) share
# the inversion class. Extend via arm(hazard_prefixes=).
DEFAULT_HAZARD_PREFIXES = ("dispatcher.shard", "logbroker.shard")


@dataclass
class Edge:
    """held -> acquired, witnessed on `thread` (first witness kept)."""

    held_id: int
    held_name: str
    acq_id: int
    acq_name: str
    thread: str


@dataclass
class Report:
    cycles: list = field(default_factory=list)    # [[name, ...], ...]
    hazards: list = field(default_factory=list)   # [str, ...]
    edges: int = 0
    locks: int = 0

    @property
    def clean(self) -> bool:
        return not self.cycles and not self.hazards

    def render(self) -> str:
        if self.clean:
            return (f"lockgraph: clean ({self.locks} locks, "
                    f"{self.edges} order edges)")
        out = []
        for cyc in self.cycles:
            out.append("lock-order cycle: " + " -> ".join(cyc))
        out.extend(self.hazards)
        return "\n".join(out)


class _GraphState:
    """One armed session: the acquisition-order graph + hazard log."""

    def __init__(self, hazard_names=DEFAULT_HAZARD_NAMES,
                 hazard_prefixes=DEFAULT_HAZARD_PREFIXES):
        self.hazard_names = frozenset(hazard_names)
        self.hazard_prefixes = tuple(hazard_prefixes)
        self._mu = threading.Lock()             # leaf: guards the sets below
        self._edges: dict[tuple[int, int], Edge] = {}
        self._locks: dict[int, str] = {}        # id(tracked) -> name
        self._keep: list = []                   # strong refs: ids stay unique
        self._hazards: list[str] = []
        self._tls = threading.local()

    # ---------------------------------------------------------- per-thread
    def _held(self) -> list:
        h = getattr(self._tls, "held", None)
        if h is None:
            h = self._tls.held = []
        return h

    def _view_depth(self) -> int:
        return getattr(self._tls, "views", 0)

    def view_enter(self) -> None:
        self._tls.views = self._view_depth() + 1

    def view_exit(self) -> None:
        self._tls.views = max(0, self._view_depth() - 1)

    # ------------------------------------------------------------- recording
    def register(self, lock: "_TrackedLock") -> None:
        with self._mu:
            self._locks[id(lock)] = lock.name
            self._keep.append(lock)

    def on_acquired(self, lock: "_TrackedLock") -> None:
        """Called AFTER the inner lock is held (first acquisition only
        for RLocks)."""
        held = self._held()
        if (lock.name in self.hazard_names
                or (self.hazard_prefixes
                    and lock.name.startswith(self.hazard_prefixes))) \
                and self._view_depth() > 0:
            tname = threading.current_thread().name
            with self._mu:
                self._hazards.append(
                    f"hazard: {lock.name!r} acquired inside an open "
                    f"store.view callback (thread {tname}) — the PR 4 "
                    f"dispatcher/store inversion")
        if held:
            tname = threading.current_thread().name
            with self._mu:
                for h in held:
                    key = (id(h), id(lock))
                    if key not in self._edges:
                        self._edges[key] = Edge(
                            id(h), h.name, id(lock), lock.name, tname)
        held.append(lock)

    def on_released(self, lock: "_TrackedLock") -> None:
        held = self._held()
        # out-of-order release is legal (hand-over-hand): drop the last
        # occurrence, not necessarily the top
        for i in range(len(held) - 1, -1, -1):
            if held[i] is lock:
                del held[i]
                return

    # --------------------------------------------------------------- report
    def report(self) -> Report:
        with self._mu:
            edges = list(self._edges.values())
            names = dict(self._locks)
            hazards = list(self._hazards)
        adj: dict[int, set[int]] = {}
        for e in edges:
            adj.setdefault(e.held_id, set()).add(e.acq_id)
        cycles = []
        seen_cycles = set()
        # iterative DFS with color marking; a back edge closes a cycle
        color: dict[int, int] = {}          # 0 absent/white, 1 grey, 2 black
        for root in list(adj):
            if color.get(root):
                continue
            stack = [(root, iter(sorted(adj.get(root, ()))))]
            color[root] = 1
            path = [root]
            while stack:
                node, it = stack[-1]
                advanced = False
                for nxt in it:
                    c = color.get(nxt, 0)
                    if c == 1:              # back edge: cycle
                        cyc = path[path.index(nxt):] + [nxt]
                        key = frozenset(cyc)
                        if key not in seen_cycles:
                            seen_cycles.add(key)
                            cycles.append(
                                [names.get(i, f"lock@{i:#x}") for i in cyc])
                    elif c == 0:
                        color[nxt] = 1
                        stack.append((nxt, iter(sorted(adj.get(nxt, ())))))
                        path.append(nxt)
                        advanced = True
                        break
                if not advanced:
                    color[node] = 2
                    stack.pop()
                    path.pop()
        return Report(cycles=cycles, hazards=hazards,
                      edges=len(edges), locks=len(names))


class _TrackedLock:
    """Context-manager wrapper around a real Lock/RLock. Only the FIRST
    acquisition / LAST release of a reentrant lock records (inner depth
    tracked per thread); the inner primitive still provides the actual
    mutual exclusion, so tracked and plain locks are interchangeable.

    Known blind spot (documented, like the Condition one in
    docs/static_analysis.md): a plain Lock used as a CROSS-THREAD gate
    (acquire on thread A, release on thread B — legal for Lock) would
    leave the gate on A's held-stack and record phantom order edges.
    Every site in this tree uses `with`, which cannot split threads; if
    a gate pattern ever appears, use threading.Event or teach this
    class owner tracking first."""

    __slots__ = ("_inner", "name", "_state", "_reentrant", "_depth")

    def __init__(self, inner, name: str, state: _GraphState,
                 reentrant: bool):
        self._inner = inner
        self.name = name
        self._state = state
        self._reentrant = reentrant
        self._depth = threading.local()
        state.register(self)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            d = getattr(self._depth, "n", 0)
            self._depth.n = d + 1
            if d == 0:
                self._state.on_acquired(self)
        return ok

    def release(self) -> None:
        d = getattr(self._depth, "n", 1) - 1
        self._depth.n = d
        if d == 0:
            self._state.on_released(self)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # ---------------------------------------------- Condition protocol
    # threading.Condition(lock) snapshots these three methods when they
    # exist, so a tracked lock can back a Condition (ISSUE 12: the
    # raw-condition rule routes every Condition over a factory lock).
    # wait() fully releases the lock (all recursion levels) and
    # re-acquires on wake — the tracker must mirror that, or the
    # held-stack would keep phantom edges across the wait.
    def _release_save(self):
        d = getattr(self._depth, "n", 0)
        self._depth.n = 0
        if d:
            self._state.on_released(self)
        if self._reentrant:
            return (self._inner._release_save(), d)
        self._inner.release()
        return (None, d)

    def _acquire_restore(self, saved):
        inner_saved, d = saved
        if self._reentrant:
            self._inner._acquire_restore(inner_saved)
        else:
            self._inner.acquire()
        self._depth.n = d
        if d:
            self._state.on_acquired(self)

    def _is_owned(self):
        if self._reentrant:
            return self._inner._is_owned()
        # plain Lock: owned iff held and not re-acquirable by us
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True


# ----------------------------------------------------------------- factory
def make_lock(name: str = "lock"):
    """The factory seam every `threading.Lock()` site in swarmkit_tpu
    routes through (lint rule `raw-lock` enforces it). Disarmed: returns
    the plain primitive — native-speed acquires, zero extra allocation.
    `_STATE` is snapshotted ONCE: a disarm racing in from another thread
    (conftest teardown vs a server thread creating a connection lock)
    must not hand the constructor a None state."""
    s = _STATE
    if s is None:
        return threading.Lock()
    return _TrackedLock(threading.Lock(), name, s, reentrant=False)


def make_rlock(name: str = "rlock"):
    s = _STATE
    if s is None:
        return threading.RLock()
    return _TrackedLock(threading.RLock(), name, s, reentrant=True)


# --------------------------------------------------------------- view hook
def view_enter() -> None:
    """store/memory.py calls these around a view callback (guarded by
    `if lockgraph._STATE is not None` — the disarmed truthiness test)."""
    s = _STATE
    if s is not None:
        s.view_enter()


def view_exit() -> None:
    s = _STATE
    if s is not None:
        s.view_exit()


# ----------------------------------------------------------------- arming
def arm(hazard_names=DEFAULT_HAZARD_NAMES,
        hazard_prefixes=DEFAULT_HAZARD_PREFIXES) -> _GraphState:
    global _STATE
    with _ARM_LOCK:
        _STATE = _GraphState(hazard_names, hazard_prefixes)
        return _STATE


def disarm() -> None:
    global _STATE
    with _ARM_LOCK:
        _STATE = None


def active() -> bool:
    return _STATE is not None


def report() -> Report:
    """Report for the CURRENT armed session (empty Report if disarmed)."""
    s = _STATE
    return s.report() if s is not None else Report()


@contextmanager
def armed(hazard_names=DEFAULT_HAZARD_NAMES,
          hazard_prefixes=DEFAULT_HAZARD_PREFIXES):
    """`with lockgraph.armed() as state: ...` — always disarms on exit;
    the caller asserts on `state.report()`."""
    s = arm(hazard_names, hazard_prefixes)
    try:
        yield s
    finally:
        disarm()
