"""Batched scheduling backends over an EncodedProblem.

Both backends consume the same encoder output and the same canonical spread
semantics; they differ only in the fill engine:

  * `cpu_schedule_encoded` — numpy + greedy heap fill (the oracle);
  * `ops.placement.schedule_encoded` — the jitted TPU water-fill kernel.

Placement parity between them is the judged property (BASELINE.md north
star). `materialize` turns per-(group, node) counts into the deterministic
task→node map both backends share: a group's tasks, sorted by id, zip with
the canonical slot order (spread.slot_order).
"""
from __future__ import annotations

from operator import itemgetter

import numpy as np

from ..native import hostops as _hostops
from ..utils import failpoints
from .encode import UNLIMITED, VOL_TOPO_MOUNTS, EncodedProblem
from .nodeinfo import NodeInfo, task_reservations
from .spread import GroupFill, binpack_fill, greedy_fill, tree_fill


def _group_caps(p: EncodedProblem, gi: int, avail: np.ndarray,
                svc: np.ndarray, port_used: np.ndarray) -> np.ndarray:
    """Dynamic per-node capacity for group gi — numpy mirror of the kernel's
    step() capacity computation."""
    N = avail.shape[0]
    need = p.need_res[gi]
    caps = np.full(N, UNLIMITED, np.int64)
    for r in range(need.shape[0]):
        if need[r] > 0:
            caps = np.minimum(caps, avail[:, r] // need[r])
    if p.max_replicas[gi] > 0:
        caps = np.minimum(caps, p.max_replicas[gi] - svc)
    if p.has_ports[gi]:
        conflict = (p.group_ports[gi][None, :] & port_used).any(axis=1)
        caps = np.minimum(caps, np.where(conflict, 0, 1))
    return np.clip(caps, 0, UNLIMITED)


def _static_legs(p: EncodedProblem) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """The static mask's component legs — (cons_ok, plat_ok, missing),
    each [G, N] — shared by cpu_static_mask and cpu_filter_explain."""
    cols = np.clip(p.constraints[:, :, 0], 0, None)
    ops_ = p.constraints[:, :, 1]
    vals = p.constraints[:, :, 2]
    padded = p.constraints[:, :, 0] < 0
    nv = p.node_val[:, cols]                       # [N, G, C]
    hit = nv == vals[None]
    ok = np.where(ops_[None] == 0, hit, ~hit)
    cons_ok = np.all(ok | padded[None], axis=2).T  # [G, N]

    pr = p.plat_req
    row_valid = pr[:, :, 0] > -2
    has_plat = row_valid.any(axis=1)
    os_ok = (pr[:, :, 0][:, :, None] == 0) | (
        pr[:, :, 0][:, :, None] == p.node_plat[:, 0][None, None, :])
    arch_ok = (pr[:, :, 1][:, :, None] == 0) | (
        pr[:, :, 1][:, :, None] == p.node_plat[:, 1][None, None, :])
    plat_hit = (os_ok & arch_ok & row_valid[:, :, None]).any(axis=1)
    plat_ok = np.where(has_plat[:, None], plat_hit, True)

    missing = (p.req_plugins[:, None, :] & ~p.node_plugins[None, :, :]).any(axis=2)
    return cons_ok, plat_ok, missing


def cpu_static_mask(p: EncodedProblem) -> np.ndarray:
    """numpy mirror of ops.placement.build_static_mask."""
    cons_ok, plat_ok, missing = _static_legs(p)
    out = p.ready[None, :] & cons_ok & plat_ok & ~missing & p.extra_mask
    vt = getattr(p, "vol_topo", None)
    if vt is not None and vt.shape[1] > 0:
        out = out & _cpu_vol_topo_ok(p.node_val, vt)
    return out


# reference Pipeline order: DEFAULT_FILTERS + the appended VolumesFilter
FILTER_LEGS = ("ready", "resource", "plugin", "constraint", "platform",
               "hostport", "max_replicas", "volumes")


def cpu_filter_explain(p: EncodedProblem) -> np.ndarray:
    """Per-filter failure counts from the ENCODED arrays:
    int64[G, len(FILTER_LEGS)].

    Mirror of the reference Pipeline's short-circuit `_failures` tally
    (scheduler/filters.py): each ineligible node is charged to the FIRST
    failing leg in DEFAULT_FILTERS (+ Volumes) order, evaluated at the
    PRE-FILL state the Pipeline sees (avail_res / svc_count0 /
    port_used0). Enablement needs no side channel — a group that never
    enabled a filter has an empty leg (zero need → zero resource fails,
    no host ports → no conflicts, cap 0 → unlimited). extra_mask residue
    is charged to `volumes` (the encoder's zero-candidate blanking and
    host-side volume fallback both land there); clusters routing
    NON-volume residue through extra_mask (node.ip constraints) would
    misattribute those rows to it.
    """
    G, N = p.extra_mask.shape
    cons_ok, plat_ok, missing = _static_legs(p)
    vt = getattr(p, "vol_topo", None)
    vol_ok = (_cpu_vol_topo_ok(p.node_val, vt)
              if vt is not None and vt.shape[1] > 0 else np.ones((G, N), bool))
    vol_ok = vol_ok & p.extra_mask
    fails = (
        np.broadcast_to(~p.ready[None, :], (G, N)),
        (p.avail_res[None, :, :] < p.need_res[:, None, :]).any(axis=2),
        missing,
        ~cons_ok,
        ~plat_ok,
        (p.group_ports[:, None, :] & p.port_used0[None, :, :]).any(axis=2),
        (p.max_replicas[:, None] > 0)
        & (p.svc_count0[p.svc_idx] >= p.max_replicas[:, None]),
        ~vol_ok,
    )
    counts = np.zeros((G, len(FILTER_LEGS)), np.int64)
    alive = np.ones((G, N), bool)
    for li, f in enumerate(fails):
        hit = alive & f
        counts[:, li] = hit.sum(axis=1)
        alive &= ~hit
    return counts


def _cpu_vol_topo_ok(node_val: np.ndarray, vol_topo: np.ndarray) -> np.ndarray:
    """numpy mirror of ops.placement._vol_topo_ok: a node passes a group's
    volume leg when EVERY mount has SOME candidate row all of whose
    (key, value) pairs match the node's columns. Padded keys (-1) match
    anything; a looked-up value id of -1 matches nothing (no node carries
    that value). Mount ids beyond the group's rows impose no constraint —
    zero-candidate mounts were blanked via extra_mask at encode time."""
    G, VA, W = vol_topo.shape
    N = node_val.shape[0]
    mount = vol_topo[:, :, 0]
    row_ok = np.ones((G, VA, N), bool)
    for s in range((W - 1) // 2):
        k = vol_topo[:, :, 1 + 2 * s]
        v = vol_topo[:, :, 2 + 2 * s]
        nv = node_val[:, np.clip(k, 0, None)]          # [N, G, VA]
        ok = (k < 0)[None] | (nv == v[None])
        row_ok &= np.transpose(ok, (1, 2, 0))
    vol_ok = np.ones((G, N), bool)
    for m in range(VOL_TOPO_MOUNTS):
        is_m = mount == m
        has_m = is_m.any(axis=1)
        m_ok = (row_ok & is_m[:, :, None]).any(axis=1)
        vol_ok &= np.where(has_m[:, None], m_ok, True)
    return vol_ok


def cpu_schedule_encoded(p: EncodedProblem) -> np.ndarray:
    """Sequential-groups greedy fill; returns counts int32[G, N]."""
    G, N = p.extra_mask.shape
    static_mask = cpu_static_mask(p)
    totals = p.total0.astype(np.int64).copy()
    svc_counts = p.svc_count0.astype(np.int64).copy()
    avail = p.avail_res.astype(np.int64).copy()
    port_used = p.port_used0.copy()
    out = np.zeros((G, N), np.int32)
    for gi in range(G):
        svc = svc_counts[p.svc_idx[gi]]
        caps = _group_caps(p, gi, avail, svc, port_used)
        g = GroupFill(
            n_tasks=int(p.n_tasks[gi]),
            eligible=static_mask[gi].tolist(),
            capacity=caps.tolist(),
            penalty=p.penalty[gi].tolist(),
            svc_count=svc.tolist(),
            total_count=totals.tolist(),
        )
        if getattr(p, "strategy", "spread") == "binpack":
            # binpack ignores spread preferences: flat fullest-first fill
            counts = np.array(binpack_fill(g), np.int32)
        else:
            lmax = 0 if p.spread_rank is None else p.spread_rank.shape[1]
            if lmax:
                level_ranks = [p.spread_rank[gi, li].tolist()
                               for li in range(lmax)]
                counts = np.array(tree_fill(g, level_ranks), np.int32)
            else:
                counts = np.array(greedy_fill(g), np.int32)
        out[gi] = counts
        totals += counts
        svc_counts[p.svc_idx[gi]] += counts
        avail -= counts[:, None].astype(np.int64) * p.need_res[gi][None, :]
        port_used |= p.group_ports[gi][None, :] & (counts > 0)[:, None]
    return out


def tpu_schedule_encoded(p: EncodedProblem) -> np.ndarray:
    # deferred: pulling in jax is a multi-second import; daemon processes
    # that never cross the TPU batching threshold should not pay it
    from ..ops import placement as placement_ops

    return placement_ops.schedule_encoded(p)


def materialize_orders(p: EncodedProblem, counts: np.ndarray) -> list:
    """counts[G, N] → per-group canonical slot order (node indices),
    deterministic across backends.

    Vectorized slot ordering: a group's filled slots sort by
    (key_at_slot, total_at_slot, node_idx) — the order greedy filled them.
    All slot tuples are distinct (within a node both key and total strictly
    increase per slot; across nodes the index differs), so the numpy lexsort
    reproduces `spread.slot_order` exactly. The group's id-sorted tasks zip
    with its order; tasks beyond the order length are unplaced and stay
    PENDING."""
    from .spread import PENALTY_BASE

    N = len(p.node_ids)
    node_arange = np.arange(N)
    totals = p.total0.astype(np.int64).copy()
    svc_counts = p.svc_count0.astype(np.int64).copy()
    orders: list[np.ndarray] = []
    for gi in range(len(p.groups)):
        c = counts[gi].astype(np.int64)
        placed = int(c.sum())
        if placed:
            svc = svc_counts[p.svc_idx[gi]]
            if getattr(p, "strategy", "spread") == "binpack":
                # binpack slot order = nodes in INITIAL key order
                # (penalty, -svc, -total, idx), each repeated counts[i]
                # times — every slot on a node sorts before any slot on
                # the next node (spread.binpack_slot_order)
                pen = np.where(p.penalty[gi], 1, 0)
                order_nodes = np.lexsort(
                    (node_arange, -totals, -svc, pen))
                orders.append(np.repeat(order_nodes, c[order_nodes]))
                totals += c
                svc_counts[p.svc_idx[gi]] += c
                continue
            base_k = np.where(p.penalty[gi], PENALTY_BASE, 0) + svc
            idx = np.repeat(node_arange, c)                       # [placed]
            j = np.arange(placed) - np.repeat(np.cumsum(c) - c, c)
            key = base_k[idx] + j
            tot = totals[idx] + j
            # per-group 3-key lexsorts measured FASTER than one global
            # batched sort at every probed shape (5 ms vs 19 ms at
            # 100k x 10k quiet: the ~5k-row per-group sorts stay cache-
            # resident; a fused [T]-sized 4-key radix does not) — keep
            # the simple loop
            orders.append(idx[np.lexsort((idx, tot, key))])
            totals += c
            svc_counts[p.svc_idx[gi]] += c
        else:
            orders.append(node_arange[:0])
    return orders


def group_needs_per_task_add(t0) -> bool:
    """True when a group's bookkeeping can't be bulked: generic-resource
    claims mutate per-task pools and host-published ports maintain the
    node's port set — both need the full `NodeInfo.add_task` path."""
    return bool(task_reservations(t0.spec).generic
                or NodeInfo._host_ports(t0))


def _add_serial(info, tasks) -> int:
    """Per-task oracle path (collision-segment fallback for both the
    native and Python bulk walks)."""
    return sum(1 for t in tasks if info.add_task(t))


def apply_placements(infos: list, placed_groups: list) -> int:
    """Bulk NodeInfo bookkeeping for one committed scheduler wave.
    placed_groups: (t0, tasks, node_idx[, ids]) per group — tasks[i] was
    placed on infos[node_idx[i]]; t0 is any task carrying the group's
    shared spec content; optional ids is the parallel id list built while
    the tasks were cache-hot (TaskGroup.ids) — with it the native walk
    never dereferences a task object. State lands bit-identical to
    calling `add_task` per
    task — mutations counter included (the encoder fingerprint contract)
    — at O(nodes + cells) Python cost instead of O(tasks)
    attribute-chasing per placement (the reference pays that walk in
    updateNodeInfo, manager/scheduler/scheduler.go:330-346; typical big
    waves degenerate to ~1 task per (group, node) cell, so per-cell
    bulking alone doesn't pay either).

    Caller contract (what the scheduler's commit guarantees): a group's
    tasks share spec CONTENT (same (service_id, spec_version) group) and
    have desired_state <= COMPLETE (active). Groups with generic
    reservations or host-published ports take the full per-task path
    (their claims mutate per-task pools). Defensive residue: a node whose
    incoming ids collide with tasks already on it falls back to per-task
    add_task for its whole segment; a None info (node removed between
    encode and commit) is skipped, uncounted."""
    # failpoint `commit.walk`: a crash at the native-walk stage boundary
    # (before any NodeInfo mutates — the all-or-nothing point)
    failpoints.fp("commit.walk")
    # validate EVERYTHING before mutating anything: a mid-wave raise
    # would leave NodeInfo bookkeeping half-applied with no heal path
    checked: list[tuple] = []
    for entry in placed_groups:
        t0, tasks, nidx = entry[0], entry[1], entry[2]
        ids = entry[3] if len(entry) > 3 else None
        nidx = np.asarray(nidx, np.int64)
        if len(tasks) != len(nidx):
            # a silent zip-truncation here would book the wrong tasks
            # onto nodes once groups concatenate — fail loudly instead
            raise ValueError(
                f"apply_placements: group {t0.service_id!r} has "
                f"{len(tasks)} tasks but {len(nidx)} node indices")
        if len(nidx) and (int(nidx.min()) < 0
                          or int(nidx.max()) >= len(infos)):
            # a leaked unplaced sentinel (-1) would silently wrap to
            # infos[-1] in the per-task branch below
            raise IndexError(
                f"apply_placements: group {t0.service_id!r} node index "
                f"out of range for {len(infos)} nodes")
        if ids is None:
            ids = [t.id for t in tasks]     # cold-but-correct fallback
        elif len(ids) != len(tasks):
            raise ValueError(
                f"apply_placements: group {t0.service_id!r} ids/tasks "
                "length mismatch")
        if len(tasks):
            checked.append((t0, tasks, nidx, ids))

    n_added = 0
    plain: list[tuple] = []
    for t0, tasks, nidx, ids in checked:
        if group_needs_per_task_add(t0):
            for t, ni in zip(tasks, nidx.tolist()):
                info = infos[ni]
                if info is not None and info.add_task(t):
                    n_added += 1
        else:
            plain.append((t0, tasks, nidx, ids))
    if not plain:
        return n_added

    if _hostops is not None and hasattr(_hostops, "apply_wave"):
        # whole-wave native path: per-group lists go straight in; the C
        # side counting-sorts node-major (group-stable — identical order
        # to the argsort concatenation below) and walks segments in one
        # pass, so the wave never builds concatenated Python lists or
        # pays an O(T log T) sort (the two stages that bounded the
        # commit at the north-star shape alongside the walk itself)
        entries = []
        for t0, tasks, nidx, ids in plain:
            res = task_reservations(t0.spec)
            entries.append((
                tasks if isinstance(tasks, list) else list(tasks),
                ids if isinstance(ids, list) else list(ids),
                np.ascontiguousarray(nidx, np.int64),
                int(res.memory_bytes or 0), int(res.nano_cpus or 0),
                t0.service_id))
        return n_added + _hostops.apply_wave(infos, entries, _add_serial)

    # exact int64 per-node aggregates, one vector op per group
    N = len(infos)
    mem_acc = np.zeros(N, np.int64)
    cpu_acc = np.zeros(N, np.int64)
    tasks_all: list = []
    ids_all: list = []
    nodes_parts: list[np.ndarray] = []
    gi_parts: list[np.ndarray] = []
    svc_of: list[str] = []
    for gi, (t0, tasks, nidx, ids) in enumerate(plain):
        res = task_reservations(t0.spec)
        svc_of.append(t0.service_id)
        cg = np.bincount(nidx, minlength=N)
        if res.memory_bytes:
            mem_acc += cg * res.memory_bytes
        if res.nano_cpus:
            cpu_acc += cg * res.nano_cpus
        tasks_all.extend(tasks)
        ids_all.extend(ids)
        nodes_parts.append(nidx)
        gi_parts.append(np.full(len(nidx), gi, np.int64))

    nodes_all = np.concatenate(nodes_parts)
    oi = np.argsort(nodes_all, kind="stable")     # node-major, group-stable
    nodes_srt = nodes_all[oi]

    if _hostops is not None:
        # native segment walk (native/_hostops.c): same semantics as the
        # Python walk below; with the parallel id list the happy path
        # never dereferences a task object at all (ids + dict only)
        starts = np.flatnonzero(np.diff(nodes_srt, prepend=-1))
        i64 = lambda a: np.ascontiguousarray(a, np.int64)  # noqa: E731
        return n_added + _hostops.apply_segments(
            infos, tasks_all, ids_all, i64(oi), i64(nodes_srt),
            i64(np.append(starts, len(nodes_srt))), i64(mem_acc),
            i64(cpu_acc), i64(np.concatenate(gi_parts)[oi]), svc_of,
            _add_serial)

    # itemgetter gather, NOT a numpy object array: filling one inspects
    # every element for the sequence protocol (~1.3 s/M tasks measured)
    oi_l = oi.tolist()
    tasks_srt = (list(itemgetter(*oi_l)(tasks_all)) if len(oi_l) > 1
                 else [tasks_all[oi_l[0]]])
    ids_srt = (list(itemgetter(*oi_l)(ids_all)) if len(oi_l) > 1
               else [ids_all[oi_l[0]]])
    svc_arr = np.empty(len(plain), object)
    svc_arr[:] = svc_of
    svc_srt = svc_arr[np.concatenate(gi_parts)[oi]].tolist()

    starts = np.flatnonzero(np.diff(nodes_srt, prepend=-1))
    seg_bounds = np.append(starts, len(nodes_srt)).tolist()
    seg_nodes = nodes_srt[starts].tolist()
    mem_l, cpu_l = mem_acc.tolist(), cpu_acc.tolist()
    for si, node in enumerate(seg_nodes):
        a, b = seg_bounds[si], seg_bounds[si + 1]
        info = infos[node]
        if info is None:
            continue
        ids = ids_srt[a:b]
        if not info.tasks.keys().isdisjoint(ids):
            # collision (e.g. a healed double-commit): full per-task path
            # for this node — it does its own counter/resource/service
            # bookkeeping, so skip every bulk update below
            n_added += _add_serial(info, tasks_srt[a:b])
            continue
        k = b - a
        before = len(info.tasks)
        info.tasks.update(zip(ids, tasks_srt[a:b]))
        if len(info.tasks) - before != k:
            # duplicate id WITHIN the wave (contract breach): the dict
            # dedups but the counters below would double-count — undo
            # the inserts and heal through the serial path, whose re-add
            # logic counts each id once (bit-identical to the oracle)
            for i in ids:
                info.tasks.pop(i, None)
            n_added += _add_serial(info, tasks_srt[a:b])
            continue
        info.mutations += k
        info.active_tasks_count += k
        ar = info.available_resources
        ar.memory_bytes -= mem_l[node]
        ar.nano_cpus -= cpu_l[node]
        # one C-speed multiset fold per segment (why by-service counts
        # are a Counter): each task contributes its group's service name
        info.active_tasks_count_by_service.update(svc_srt[a:b])
        n_added += k
    return n_added


def apply_wave(infos: list, groups: list, orders: list) -> int:
    """One scheduler wave's NodeInfo bookkeeping: per group, the id-sorted
    tasks zip with the canonical slot order (materialize_orders output);
    tasks past the order length are unplaced. infos is indexed by the
    problem's node order (None = node gone). Returns tasks added —
    `== counts.sum()` iff the wave applied cleanly (the apply_counts
    contract)."""
    placed_groups = []
    for g, order in zip(groups, orders):
        k = len(order)
        if k:
            ids = g.task_ids() if hasattr(g, "task_ids") else None
            placed_groups.append(
                (g.tasks[0], g.tasks[:k] if k < len(g.tasks) else g.tasks,
                 order, ids[:k] if ids is not None and k < len(ids)
                 else ids))
    return apply_placements(infos, placed_groups)


def materialize(p: EncodedProblem, counts: np.ndarray) -> dict[str, str]:
    """counts[G, N] → {task_id: node_id} (materialize_orders + id zip)."""
    assignments: dict[str, str] = {}
    node_ids_arr = np.array(p.node_ids, dtype=object)
    for group, order in zip(p.groups, materialize_orders(p, counts)):
        if len(order):
            chosen = node_ids_arr[order].tolist()
            assignments.update(zip((t.id for t in group.tasks), chosen))
    return assignments
