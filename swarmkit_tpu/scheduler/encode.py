"""Dictionary-encoding of cluster state into dense arrays for the TPU kernel.

The reference's scheduler walks Go maps and compares strings per (task, node)
pair (manager/scheduler/scheduler.go:694-921, filter.go). The TPU backend
instead interns every string host-side — constraint keys/values, platforms,
plugin names, host ports — into integer vocabularies, and ships dense int32
tables to the device. All O(G×N) work (constraint matching, platform/plugin
gating, spread water-fill) happens inside the jitted kernel
(`swarmkit_tpu.ops.placement.schedule_groups`); host work is O(nodes + tasks).

The encoder is INCREMENTAL (SURVEY.md §7 / round-1 verdict #6): an
`IncrementalEncoder` keeps its vocabularies and dense per-node rows across
ticks and re-encodes only nodes whose `NodeInfo.fingerprint` changed (plus
node-set adds/removes); the small group-side tables are rebuilt per tick.
Vocabulary direction makes the cache sound: NODES intern their attribute
values / plugins / ports / platforms into grow-only vocabularies, and group
constraints LOOK UP (a miss encodes as -1, which can never equal a node's
id ≥ 0) — so a constraint value first seen at tick t never invalidates a
node row encoded at tick t-k. The one-shot `encode()` wrapper runs a fresh
encoder over everything, and is what the property tests randomize against.

Quantization spec (part of this framework's scheduling semantics, applied to
BOTH backends so they stay bit-identical):
  * CPU  reservations → milli-cores, task needs rounded up, node capacity down;
  * memory            → 4 KiB pages, same rounding;
which guarantees the batched path never overcommits a node.

Host-only predicates that don't reduce to interned-int equality (node.ip
IP/CIDR math — reference constraint.go:127-146 — and unparseable constraint
sets) are folded into a per-group `extra_mask` correction column, per
SURVEY.md §7's guidance on strings/IP math.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field

import numpy as np

from ..api.types import normalize_arch
from . import constraint as constraint_mod
from .filters import PluginFilter, ReadyFilter
from .nodeinfo import NodeInfo

UNLIMITED = 1 << 30
OP_EQ = 0
OP_NEQ = 1

CPU_QUANTUM = 1_000_000      # nano-cpus per milli-core
MEM_QUANTUM = 4096           # bytes per page

# CSI volume/topology kernel rows (ISSUE 19): a row is one candidate-
# volume × accessible-topology alternative of one mount — (mount_idx,
# key_col, val_id, ...) over csi pseudo-key node columns. The static
# bounds cap the kernel's [G, VA, N] working set; groups past them take
# the host-side check_volumes_on_node fallback walk.
VOL_TOPO_SEGS = 4      # segment pairs per row: driver presence + ≤3 topo
VOL_TOPO_MOUNTS = 4    # distinct CSI mounts encodable per group
VOL_TOPO_MAX_ALT = 8   # total rows per group before host fallback


class Vocab:
    """String interner. id 0 is reserved for the empty string."""

    def __init__(self):
        self._ids: dict[str, int] = {"": 0}

    def id(self, s: str) -> int:
        return self._ids.setdefault(s, len(self._ids))

    def lookup(self, s: str) -> int:
        """-1 when unseen: an unseen constraint value can never equal a node
        value id, and -1 != every valid id keeps != semantics right."""
        return self._ids.get(s, -1)

    def __len__(self):
        return len(self._ids)


@dataclass
class TaskGroup:
    """One (service_id, spec_version) scheduling group — all tasks identical."""

    service_id: str
    spec_version: int
    tasks: list  # api.objects.Task, sorted by id
    # parallel id list (same string objects). Build it WHERE the tasks are
    # constructed/sorted (they are cache-hot there): the wave-commit walk
    # keys on ids, and reading N ids off cold task objects is the walk's
    # dominant miss chain. Lazily derived when absent (correct, just cold).
    ids: list | None = None

    @property
    def key(self) -> tuple[str, int]:
        return (self.service_id, self.spec_version)

    def task_ids(self) -> list:
        if self.ids is None or len(self.ids) != len(self.tasks):
            self.ids = [t.id for t in self.tasks]
        return self.ids

    @property
    def spec(self):
        return self.tasks[0].spec


@dataclass
class EncodedProblem:
    """Device-ready staging arrays (numpy, host)."""

    node_ids: list[str]
    group_keys: list[tuple[str, int]]
    service_ids: list[str]
    groups: list[TaskGroup] = field(repr=False, default_factory=list)
    # the NodeInfo objects in row order at encode time (a snapshot of the
    # encoder's row list). Commit paths index it directly instead of
    # rebuilding a node-id -> info map per wave — at 10k nodes that map
    # rebuild was a measurable slice of every steady wave. `infos_seq`
    # stamps the encoder's row-object generation: a commit may trust
    # row_infos ONLY while it equals the encoder's current infos_seq
    # (an O(1) check) — any node replacement/remap in between bumps it,
    # and the commit falls back to resolving live objects by id.
    row_infos: list = field(repr=False, default=None)
    infos_seq: int = -1

    # node side
    ready: np.ndarray = None          # bool[N]
    avail_res: np.ndarray = None      # int32[N, R]
    total0: np.ndarray = None         # int32[N]
    svc_count0: np.ndarray = None     # int32[S, N]
    node_val: np.ndarray = None       # int32[N, K] interned value per key col
    node_plat: np.ndarray = None      # int32[N, 2] (os_id, arch_id)
    node_plugins: np.ndarray = None   # bool[N, PL]
    port_used0: np.ndarray = None     # bool[N, PV]

    # group side
    n_tasks: np.ndarray = None        # int32[G]
    svc_idx: np.ndarray = None        # int32[G]
    need_res: np.ndarray = None       # int32[G, R]
    max_replicas: np.ndarray = None   # int32[G]; 0 == unlimited
    constraints: np.ndarray = None    # int32[G, C, 3] (key_col, op, val); col<0 pad
    plat_req: np.ndarray = None       # int32[G, P, 2]; (-2,-2) pad row; 0 wildcard
    req_plugins: np.ndarray = None    # bool[G, PL]
    has_ports: np.ndarray = None      # bool[G]
    group_ports: np.ndarray = None    # bool[G, PV]
    penalty: np.ndarray = None        # bool[G, N]
    extra_mask: np.ndarray = None     # bool[G, N] host-side corrections
    # spread preferences (nodeset.go tree): node's branch id per level —
    # contiguous ranks of the label-value PATH PREFIX, lexicographically
    # sorted, so children of one parent occupy a contiguous id range;
    # levels past a group's preference count repeat the last real level
    # (a self-parented pour is a no-op)
    spread_rank: np.ndarray = None    # int32[G, LMAX, N]; LMAX may be 0

    # device-resident path (ops.resident): group -> PERSISTENT service row
    # in the encoder's grow-only service matrix, and that matrix's current
    # row count. svc_idx/svc_count0 above use TICK-LOCAL rows instead.
    svc_idx_persistent: np.ndarray = None  # int32[G]
    n_svc_rows: int = 0
    # True when any group's service had no persistent row yet at encode
    # time (row numbers are hypothetical until a fold allocates them) —
    # a deep pipeline must not dispatch AHEAD of such a wave, because a
    # later wave's hypothetical numbering would clash with it
    has_hypo_rows: bool = False
    # cheap dispatch gates (ops/resident.py): at 100k–1M nodes the
    # `penalty.any()` / `extra_mask.all()` scans are O(G·N) per tick, so
    # builders that KNOW the answer stamp it here. None = unknown, the
    # consumer scans. Conservative values (nonzero=True / all=False when
    # actually clean) are SAFE — they only ship the real array instead of
    # the placeholder, never change results.
    penalty_nonzero: bool | None = None
    extra_mask_all: bool | None = None
    # strategy seam (ISSUE 19): which scoring kernel consumers dispatch —
    # "spread" (default), "binpack" (prefer-fullest, flat), or "topology"
    # (spread with the encoder-prepended topology level; kernels treat it
    # as spread). pad_buckets MUST copy it — it changes dispatch.
    strategy: str = "spread"
    # CSI volume/topology feasibility rows (ops/placement._vol_topo_ok):
    # int32[G, VA, 1 + 2*VOL_TOPO_SEGS] of (mount, k0, v0, ...), -1 pad;
    # VA == 0 when no group mounts CSI volumes (the leg compiles away)
    vol_topo: np.ndarray = None
    # O(1) dispatch gate like penalty_nonzero: True = some group has
    # vol-topo rows, False = provably none, None = unknown (consumer
    # checks the array shape)
    vol_topo_any: bool | None = None


_INT32_MAX = (1 << 31) - 1


# Canonical positional order of EncodedProblem arrays as consumed by
# ops.placement.schedule_groups — the ONE place the positional-arg contract
# lives; bench, the graft entry, and the mesh sharder all derive from it.
KERNEL_ARG_FIELDS = (
    "ready", "node_val", "node_plat", "node_plugins", "extra_mask",
    "constraints", "plat_req", "req_plugins", "avail_res", "total0",
    "svc_count0", "n_tasks", "svc_idx", "need_res", "max_replicas",
    "penalty", "has_ports", "group_ports", "port_used0", "spread_rank",
    "vol_topo",
)


def _empty_vol_topo(G: int) -> np.ndarray:
    return np.full((G, 0, 1 + 2 * VOL_TOPO_SEGS), -1, np.int32)


def kernel_args(p: "EncodedProblem") -> tuple:
    """The problem's arrays in schedule_groups' positional order (numpy).
    A hand-built problem may predate the vol_topo field (None): that is
    the empty table (no CSI mounts anywhere)."""
    out = []
    for f in KERNEL_ARG_FIELDS:
        v = getattr(p, f, None)
        if v is None and f == "vol_topo":
            v = _empty_vol_topo(p.extra_mask.shape[0])
        out.append(np.asarray(v))
    return tuple(out)


def _bucket(n: int, floor: int = 1) -> int:
    b = max(n, floor, 1)
    return 1 << (b - 1).bit_length()


def pad_buckets(p: "EncodedProblem") -> "EncodedProblem":
    """Pad every kernel dimension to its power-of-two bucket so the jitted
    program compiles once per bucket, not once per exact problem shape
    (SURVEY.md §7 'bucket-and-pad, pre-warm compile cache').

    Padding is semantics-free: phantom nodes are not ready (never eligible,
    zero capacity, zero totals — they contribute nothing to branch
    aggregates), phantom groups have zero tasks and an all-false extra_mask,
    and padded spread levels replicate each group's last real level (a
    self-parented pour is a no-op). Callers slice results back to the real
    [G, N] window."""
    G, N = p.extra_mask.shape
    S = p.svc_count0.shape[0]
    K = p.node_val.shape[1]
    PL = p.node_plugins.shape[1]
    PV = p.port_used0.shape[1]
    R = p.avail_res.shape[1]
    C = p.constraints.shape[1]
    P = p.plat_req.shape[1]
    LMAX = p.spread_rank.shape[1]
    vt = p.vol_topo if p.vol_topo is not None else _empty_vol_topo(G)
    VA = vt.shape[1]
    Gp, Np, Sp = _bucket(G), _bucket(N), _bucket(S)
    Kp, PLp, PVp, Rp = _bucket(K), _bucket(PL), _bucket(PV), _bucket(R)
    Lp = _bucket(LMAX) if LMAX else 0
    Vp = _bucket(VA) if VA else 0
    if (Gp, Np, Sp, Kp, PLp, PVp, Rp, Lp, Vp) == (
            G, N, S, K, PL, PV, R, LMAX, VA):
        if p.vol_topo is None:
            p.vol_topo = vt     # normalize for positional consumers
        return p

    def pad(a: np.ndarray, shape: tuple, fill=0) -> np.ndarray:
        out = np.full(shape, fill, a.dtype)
        out[tuple(slice(0, s) for s in a.shape)] = a
        return out

    q = EncodedProblem(node_ids=p.node_ids, group_keys=p.group_keys,
                       service_ids=p.service_ids, groups=p.groups,
                       row_infos=p.row_infos, infos_seq=p.infos_seq)
    q.strategy = p.strategy             # changes dispatch: must survive
    q.vol_topo_any = p.vol_topo_any     # pad rows are -1 (no mount): safe
    q.ready = pad(p.ready, (Np,), False)
    q.total0 = pad(p.total0, (Np,))
    q.avail_res = pad(p.avail_res, (Np, Rp))
    q.svc_count0 = pad(p.svc_count0, (Sp, Np))
    q.node_val = pad(p.node_val, (Np, Kp))
    q.node_plat = pad(p.node_plat, (Np, 2))
    q.node_plugins = pad(p.node_plugins, (Np, PLp), False)
    q.port_used0 = pad(p.port_used0, (Np, PVp), False)
    q.n_tasks = pad(p.n_tasks, (Gp,))
    q.svc_idx = pad(p.svc_idx, (Gp,))
    q.need_res = pad(p.need_res, (Gp, Rp))
    q.max_replicas = pad(p.max_replicas, (Gp,))
    q.constraints = pad(p.constraints, (Gp, C, 3), -1)
    q.plat_req = pad(p.plat_req, (Gp, P, 2), -2)
    q.req_plugins = pad(p.req_plugins, (Gp, PLp), False)
    q.has_ports = pad(p.has_ports, (Gp,), False)
    q.group_ports = pad(p.group_ports, (Gp, PVp), False)
    q.penalty = pad(p.penalty, (Gp, Np), False)
    q.extra_mask = pad(p.extra_mask, (Gp, Np), False)
    sr = np.zeros((Gp, Lp, Np), np.int32)
    if LMAX:
        sr[:G, :LMAX, :N] = p.spread_rank
        if Lp > LMAX:
            # replicate each group's deepest real level into padded levels
            sr[:G, LMAX:, :N] = p.spread_rank[:, LMAX - 1:LMAX, :]
    q.spread_rank = sr
    # phantom vol-topo rows are all -1: mount -1 belongs to no real
    # mount, so they never tighten any group's feasibility
    q.vol_topo = np.full((Gp, Vp, vt.shape[2]), -1, np.int32)
    if VA:
        q.vol_topo[:G, :VA] = vt
    return q


def quantize_need(res) -> tuple[int, int]:
    cpu = -(-res.nano_cpus // CPU_QUANTUM) if res.nano_cpus > 0 else 0
    mem = -(-res.memory_bytes // MEM_QUANTUM) if res.memory_bytes > 0 else 0
    return min(cpu, _INT32_MAX), min(mem, _INT32_MAX)


def quantize_avail(res) -> tuple[int, int]:
    cpu = max(res.nano_cpus // CPU_QUANTUM, 0)
    mem = max(res.memory_bytes // MEM_QUANTUM, 0)
    return min(cpu, _INT32_MAX), min(mem, _INT32_MAX)


def _canon_value(key_lower: str, value: str) -> str:
    """Comparable form of an attribute value: case-folded (the reference
    compares case-insensitively, constraint.go:84-104). node.ip never reaches
    here — IP/CIDR math stays host-side in extra_mask."""
    return value.lower()


_PREDEFINED_KEYS = {
    "node.id", "node.hostname", "node.role",
    "node.platform.os", "node.platform.arch",
}


def _canon_key(key: str) -> str | None:
    """Canonical vocab form of a constraint key: predefined keys case-fold
    whole; label keys case-fold only the prefix — label *names* stay
    case-sensitive (reference constraint.go:175 'label itself is case
    sensitive'). None == unknown key, which matches no node regardless of
    operator (constraint.go default case)."""
    lk = key.lower()
    if lk in _PREDEFINED_KEYS or lk == "node.ip":
        return lk
    for prefix in (constraint_mod.NODE_LABEL_PREFIX,
                   constraint_mod.ENGINE_LABEL_PREFIX):
        if lk.startswith(prefix) and len(key) > len(prefix):
            return prefix + key[len(prefix):]
    return None


def _node_attr_value(node, ck: str) -> str:
    _, cands = constraint_mod.node_attribute(node, ck)
    return cands[0] if cands else ""


# csi pseudo-keys (ISSUE 19 vol-topo kernel rows): node columns carrying
# per-driver presence and accessible-topology segments. `_canon_key`
# never emits a "csi." prefix (predefined keys + label prefixes only),
# so these can't collide with constraint key columns. Driver names with
# "/" would alias topo keys — CSI driver names are reverse-DNS, no "/".
def _csi_presence_key(driver: str) -> str:
    return "csi.node/" + driver


def _csi_topo_key(driver: str, seg: str) -> str:
    return "csi.topo/" + driver + "/" + seg


def _node_key_value(node, ck: str) -> str:
    """Comparable (vocab) value of node key column `ck`. csi.* pseudo-key
    values carry an '=' prefix so a node missing the driver/segment
    (empty string, vocab id 0) can never equal a real required value;
    topology segment values stay case-SENSITIVE (volumes.go compares
    exactly). Everything else is a constraint attribute, case-folded per
    `_canon_value`."""
    if ck.startswith("csi.node/"):
        driver = ck[len("csi.node/"):]
        desc = node.description
        if desc is None:
            return ""
        if (desc.csi_info or {}).get(driver) is not None \
                or driver in (desc.csi_plugins or ()):
            return "=1"
        return ""
    if ck.startswith("csi.topo/"):
        driver, _, seg = ck[len("csi.topo/"):].partition("/")
        desc = node.description
        ninfo = ((desc.csi_info or {}) if desc else {}).get(driver)
        if ninfo is None:
            return ""
        val = (ninfo.accessible_topology or {}).get(seg)
        return "" if val is None else "=" + val
    return _canon_value(ck, _node_attr_value(node, ck))


def _node_label(node, kind: str, label: str) -> str:
    if kind == "node":
        labels = node.spec.annotations.labels or {}
    else:
        desc = node.description
        labels = (desc.engine_labels or {}) if desc else {}
    return labels.get(label, "")


class IncrementalEncoder:
    """Persistent encoder: node-side dense rows and all vocabularies survive
    across ticks; `encode()` re-encodes only dirty nodes (fingerprint delta,
    adds, removes) and rebuilds the O(G) group tables. Steady-state host cost
    per tick is O(dirty nodes + groups + N numpy copies), not O(N × K Python).

    ZERO-SCAN fast path (`tracked=True`, round 6): even with zero dirty
    rows, the fingerprint scan itself — sort the infos by id, compare
    the id list, read (created_seq, mutations) off every NodeInfo — is
    an O(N) Python pass per encode() plus another per nodes_clean(),
    and at 10k nodes it dominates the steady tick's host tail. In
    tracked mode the caller FEEDS an explicit dirty set instead:

      * `mark_replaced(info)` — the caller swapped in a new NodeInfo
        object for an existing node id (full string re-encode);
      * `mark_numeric(info)` — an in-place mutation (add/remove task,
        failure) on the same object (numeric columns only);
      * `mark_node_set_changed()` — a node was added or removed (next
        encode falls back to the full sort + fingerprint scan, which
        re-syncs rows and clears every mark);
      * `force_numeric_reencode` / `poison_all_numeric` mark their rows
        themselves, so the existing heal paths need no extra calls.

    A steady encode with no marks then touches NO NodeInfo at all and
    performs 0 fingerprint scans (`fp_scans` counts them — the op-count
    guard's counter); nodes_clean() degrades to a flag check. The
    contract cuts both ways: in tracked mode EVERY NodeInfo mutation
    between encodes must arrive via a mark or via the wave-commit path
    (whose restamp_counts keeps fingerprints reconciled) — an unmarked
    mutation is invisible until the next full scan. The production
    Scheduler routes all of its mutation sites through marks;
    tests/test_steady_fastpath.py fuzzes tracked-vs-scan parity.
    """

    def __init__(self, max_constraints: int = 8, max_platforms: int = 4,
                 tracked: bool = False, strategy: str = "spread",
                 topology: str | None = None):
        self.max_constraints = max_constraints
        self.max_platforms = max_platforms
        self.tracked = tracked
        # strategy seam (ISSUE 19): stamped onto every emitted problem.
        # "topology" is spread with the configured axis as the OUTERMOST
        # spread level of EVERY group — the existing prefix-rank tree and
        # _tree_water_fill handle it unchanged (and nesting stays sound:
        # prepending a level keeps one parent per child segment).
        self.strategy = strategy
        self._topo_pair: tuple[str, str] | None = None
        if strategy == "topology":
            d = topology or ""
            dl = d.lower()
            for prefix, kind in ((constraint_mod.NODE_LABEL_PREFIX, "node"),
                                 (constraint_mod.ENGINE_LABEL_PREFIX,
                                  "engine")):
                if dl.startswith(prefix) and len(d) > len(prefix):
                    self._topo_pair = (kind, d[len(prefix):])
                    break
            if self._topo_pair is None:
                raise ValueError(
                    "strategy='topology' needs a label topology axis, "
                    "e.g. topology='node.labels.zone'")
        elif strategy not in ("spread", "binpack"):
            raise ValueError(f"unknown placement strategy: {strategy!r}")
        # tracked-mode dirty feed: node id -> NodeInfo (the CURRENT
        # object — a replaced node's mark carries the replacement)
        self._mark_full: dict[str, NodeInfo] = {}
        self._mark_numeric: dict[str, NodeInfo] = {}
        self._mark_set_changed = True       # ids unknown until first sync
        self._mark_all_numeric = False
        # observability / op-count guard: O(N) fingerprint scans taken
        # (encode's sync and nodes_clean both count) and the seconds the
        # last encode spent in sort + scan (the tick.dirty_scan stage)
        self.fp_scans = 0
        self.last_scan_s = 0.0
        # row-object generation: bumped whenever any row's NodeInfo
        # object may have been swapped (remap, replaced-object sync,
        # mark_replaced) — the problem.row_infos currentness stamp
        self.infos_seq = 0
        # spread-table cache: steady ticks re-derive an IDENTICAL
        # [G, LMAX, N] rank table from unchanged label columns — at scale
        # that rebuild is the encode's largest allocation. Keyed by the
        # groups' spread specs + N + a label-column generation stamp
        # (bumped by any full re-encode/remap — numeric dirt never
        # touches labels); a hit re-emits the SAME array object, which the
        # resident group-table cache turns into an O(1) identity hit.
        self._spread_cache: tuple | None = None
        self._label_gen = 0
        # vol-topo table cache (ISSUE 19): mirrors _spread_cache — a
        # steady tick re-emits the SAME array object so the resident
        # group-table cache gets an O(1) identity hit. Keyed by the row
        # CONTENT (column ids + vocab value ids), so vocab growth or
        # usage churn rebuilds; the empty table is cached per G.
        self._voltopo_cache: tuple | None = None
        self._voltopo_empty: dict[int, np.ndarray] = {}

        self.key_cols: dict[str, int] = {}   # canonical constraint key -> col
        self.val_vocab = Vocab()
        self.plugin_vocab = Vocab()
        self.port_vocab = Vocab()
        self.os_vocab = Vocab()
        self.arch_vocab = Vocab()
        self.kinds: list[str] = []           # generic resource kinds, grow-only

        # node tables, rows sorted by node id (the canonical tie-break order)
        self._ids: list[str] = []
        self._idx: dict[str, int] = {}
        self._infos: list[NodeInfo] = []
        # fingerprints as parallel arrays (vectorized restamp in apply_counts)
        self._fp_seq = np.full(0, -1, np.int64)
        self._fp_mut = np.zeros(0, np.int64)
        self.ready = np.zeros(0, bool)
        self.total0 = np.zeros(0, np.int32)
        self.node_plat = np.zeros((0, 2), np.int32)
        self.node_val = np.zeros((0, 0), np.int32)
        self.avail_res = np.zeros((0, 2), np.int32)
        # raw (unquantized) cpu/mem mirrors: lets apply_counts subtract
        # reservations exactly the way NodeInfo.add_task does, then re-derive
        # the quantized columns vectorized
        self._raw_avail = np.zeros((0, 2), np.int64)
        self.node_plugins = np.zeros((0, 1), bool)
        self.port_used = np.zeros((0, 1), bool)
        # service activity counts as a matrix [services-ever-seen, N]
        self._svc_mat = np.zeros((0, 0), np.int32)
        self._svc_row: dict[str, int] = {}
        self._failure_ids: set[str] = set()
        self._label_cols: dict[tuple[str, str], np.ndarray] = {}  # object[N]

        self._rf = ReadyFilter()
        self.last_dirty = 0   # observability: rows re-encoded by last call
        self.last_full = 0    # ... of which took the full (string) path
        # device-resident sync (ops.resident): row indices re-encoded by
        # the last encode() and whether the node-id row mapping changed
        self.last_dirty_rows: np.ndarray = np.zeros(0, np.int64)
        self.last_remap = False
        # hot-path id caches: avoid per-row f-string + dict churn
        self._default_plug_ids = [self.plugin_vocab.id(f"{t}/{n}")
                                  for t, n in PluginFilter.DEFAULT_PLUGINS]
        self._plug_id: dict[tuple[str, str], int] = {}
        self._port_id: dict[tuple[str, int], int] = {}

    # ------------------------------------------------------------ node sync
    def _sync_nodes(self, infos: list[NodeInfo]) -> tuple[set[int], set[int]]:
        """Align cached rows with the (sorted) info list; returns
        (full_dirty, numeric_dirty) row index sets. A replaced NodeInfo
        (new created_seq — node spec/description may have changed) takes the
        full string path; in-place mutations (add/remove task, failures —
        same created_seq, bumped mutation counter) only touch the numeric
        columns: totals, resources, service counts, ports, failures.
        Removals compact rows."""
        new_ids = [i.node.id for i in infos]
        dirty: set[int] = set()
        self.last_remap = new_ids != self._ids
        if new_ids != self._ids:
            old_idx = self._idx
            keep_src: list[int] = []
            keep_dst: list[int] = []
            for d, nid in enumerate(new_ids):
                s = old_idx.get(nid)
                if s is None:
                    dirty.add(d)
                else:
                    keep_src.append(s)
                    keep_dst.append(d)
            n_new = len(new_ids)

            def remap(arr: np.ndarray, fill=0) -> np.ndarray:
                out = np.full((n_new,) + arr.shape[1:], fill, arr.dtype)
                if keep_src:
                    out[keep_dst] = arr[keep_src]
                return out

            self.ready = remap(self.ready, False)
            self.total0 = remap(self.total0)
            self.node_plat = remap(self.node_plat)
            self.node_val = remap(self.node_val)
            self.avail_res = remap(self.avail_res)
            self._raw_avail = remap(self._raw_avail)
            self.node_plugins = remap(self.node_plugins, False)
            self.port_used = remap(self.port_used, False)
            self._fp_seq = remap(self._fp_seq, -1)
            self._fp_mut = remap(self._fp_mut)
            svc_new = np.zeros((self._svc_mat.shape[0], n_new), np.int32)
            if keep_src:
                svc_new[:, keep_dst] = self._svc_mat[:, keep_src]
            self._svc_mat = svc_new
            for key in list(self._label_cols):
                out = np.full(n_new, "", object)
                if keep_src:
                    out[keep_dst] = self._label_cols[key][keep_src]
                self._label_cols[key] = out
            for nid in set(self._ids) - set(new_ids):
                self._failure_ids.discard(nid)
            self._ids = new_ids
            self._idx = {nid: i for i, nid in enumerate(new_ids)}
        self._infos = infos
        numeric: set[int] = set()
        fp_seq, fp_mut = self._fp_seq, self._fp_mut
        for i, info in enumerate(infos):
            if i in dirty:
                continue
            if fp_seq[i] != info.created_seq:
                dirty.add(i)         # replaced object: full re-encode
            elif fp_mut[i] != info.mutations:
                numeric.add(i)       # same object, counters moved
        if dirty or self.last_remap:
            # some row's OBJECT changed (replacement, add/remove): any
            # older problem's row_infos snapshot may now hold dead
            # objects — invalidate the commit-side reuse stamp
            self.infos_seq += 1
        return dirty, numeric

    # ------------------------------------------------- tracked dirty feed
    def mark_replaced(self, info: NodeInfo) -> None:
        """Tracked-mode feed: the caller replaced an EXISTING node's
        NodeInfo object wholesale (spec/description churn). The next
        encode re-runs the full string path for that row. No-op when
        untracked (the fingerprint scan catches it anyway)."""
        if self.tracked:
            self._mark_full[info.node.id] = info
            self.infos_seq += 1     # older row_infos now hold the dead
            #                         object: commit-side reuse falls back

    def mark_numeric(self, info: NodeInfo) -> None:
        """Tracked-mode feed: an in-place mutation (add/remove task,
        recorded failure) on the SAME NodeInfo object — only the numeric
        columns re-derive. No-op when untracked."""
        if self.tracked:
            self._mark_numeric[info.node.id] = info

    def mark_node_set_changed(self) -> None:
        """Tracked-mode feed: a node was added or removed. The next
        encode takes the full sort + fingerprint scan (which realigns
        rows and supersedes every pending mark)."""
        if self.tracked:
            self._mark_set_changed = True
            self.infos_seq += 1

    def _tracked_clean(self) -> bool:
        return not (self._mark_set_changed or self._mark_all_numeric
                    or self._mark_full or self._mark_numeric)

    def _clear_marks(self) -> None:
        self._mark_set_changed = False
        self._mark_all_numeric = False
        self._mark_full.clear()
        self._mark_numeric.clear()

    def _tracked_dirty(self, node_infos) -> tuple[set, set] | None:
        """Resolve the tracked marks to (full, numeric) row sets against
        the cached rows — the zero-scan path. Returns None when the fast
        path is not applicable (set changed, length drifted, or a marked
        id is unknown) and the caller must fall back to the full scan."""
        if self._mark_set_changed or len(node_infos) != len(self._ids):
            return None
        idx = self._idx
        dirty: set[int] = set()
        for nid, info in self._mark_full.items():
            i = idx.get(nid)
            if i is None:
                return None          # marked node unknown: re-sync
            self._infos[i] = info
            dirty.add(i)
        if self._mark_all_numeric:
            numeric = set(range(len(self._ids))) - dirty
        else:
            numeric = set()
            for nid, info in self._mark_numeric.items():
                i = idx.get(nid)
                if i is None:
                    return None
                if nid in self._mark_full:
                    # the row was ALSO replaced this batch: the full mark
                    # carries the latest object and its string re-encode
                    # subsumes the numeric one — a numeric mark recorded
                    # before the replacement holds the dead object, and
                    # trusting it below would resurrect stale rows
                    continue
                if self._infos[i] is not info:
                    # marked numeric but the object was swapped: treat as
                    # a replacement (defensive — string columns may have
                    # moved too)
                    self._infos[i] = info
                    self.infos_seq += 1
                    dirty.add(i)
                elif i not in dirty:
                    numeric.add(i)
        return dirty, numeric

    # --------------------------------------------------------- column growth
    def _ensure_key(self, ck: str) -> None:
        if ck in self.key_cols:
            return
        col = len(self.key_cols)
        self.key_cols[ck] = col
        n = len(self._ids)
        self.node_val = np.concatenate(
            [self.node_val, np.zeros((n, 1), np.int32)], axis=1)
        for i, info in enumerate(self._infos):
            self.node_val[i, col] = self.val_vocab.id(
                _node_key_value(info.node, ck))

    def _ensure_kind(self, kind: str) -> None:
        if kind in self.kinds:
            return
        self.kinds.append(kind)
        n = len(self._ids)
        col = np.zeros((n, 1), np.int32)
        for i, info in enumerate(self._infos):
            have = info.available_resources.generic.get(kind, 0)
            have += len(info.available_resources.named_generic.get(kind, ()))
            col[i, 0] = have
        self.avail_res = np.concatenate([self.avail_res, col], axis=1)

    def _grow_bool_cols(self) -> None:
        n = len(self._ids)
        for attr, vocab in (("node_plugins", self.plugin_vocab),
                            ("port_used", self.port_vocab)):
            arr = getattr(self, attr)
            want = max(len(vocab), 1)
            if arr.shape[1] < want:
                pad = np.zeros((n, want - arr.shape[1]), bool)
                setattr(self, attr, np.concatenate([arr, pad], axis=1))

    # ------------------------------------------------------------- node rows
    def _port_ids(self, ports) -> list[int]:
        cache = self._port_id
        out = []
        for key in ports:
            pid = cache.get(key)
            if pid is None:
                pid = self.port_vocab.id(f"{key[0]}:{key[1]}")
                cache[key] = pid
            out.append(pid)
        return out

    def _svc_row_for(self, service_id: str) -> int:
        row = self._svc_row.get(service_id)
        if row is None:
            row = len(self._svc_row)
            self._svc_row[service_id] = row
            if row >= self._svc_mat.shape[0]:
                grow = max(8, self._svc_mat.shape[0])
                self._svc_mat = np.concatenate(
                    [self._svc_mat,
                     np.zeros((grow, len(self._ids)), np.int32)], axis=0)
        return row

    def _encode_row_numeric(self, i: int, info: NodeInfo) -> None:
        """Refresh the columns in-place mutation can touch: totals, resources,
        service counts, host ports, failure set. String-valued columns
        (labels, platform, plugins, constraint attributes) only change when
        the NodeInfo object is replaced, which takes `_encode_row`."""
        nid = info.node.id
        self.total0[i] = info.active_tasks_count
        avail = info.available_resources
        self._raw_avail[i, 0] = avail.nano_cpus
        self._raw_avail[i, 1] = avail.memory_bytes
        row = self.avail_res[i]
        c = avail.nano_cpus // CPU_QUANTUM
        m = avail.memory_bytes // MEM_QUANTUM
        row[0] = c if 0 < c < _INT32_MAX else (0 if c <= 0 else _INT32_MAX)
        row[1] = m if 0 < m < _INT32_MAX else (0 if m <= 0 else _INT32_MAX)
        if self.kinds:
            generic = avail.generic
            named = avail.named_generic
            for j, kind in enumerate(self.kinds):
                row[2 + j] = (generic.get(kind, 0)
                              + len(named.get(kind, ())))

        if info.used_host_ports:
            port_ids = self._port_ids(info.used_host_ports)
            self._grow_bool_cols()
            self.port_used[i] = False
            self.port_used[i, port_ids] = True
        else:
            self.port_used[i] = False

        by_svc = info.active_tasks_count_by_service
        if by_svc or self._svc_mat.shape[0]:
            self._svc_mat[:, i] = 0
            for s, cnt in by_svc.items():
                if cnt:
                    # bind the row FIRST: _svc_row_for may replace _svc_mat
                    row_s = self._svc_row_for(s)
                    self._svc_mat[row_s, i] = cnt

        if info.recent_failures:
            self._failure_ids.add(nid)
        else:
            self._failure_ids.discard(nid)
        self._fp_seq[i] = info.created_seq
        self._fp_mut[i] = info.mutations

    def _encode_rows_numeric_bulk(self, rows: list[int], infos_all) -> None:
        """Vectorized `_encode_row_numeric` over many rows — the scalar
        columns (totals, raw + quantized cpu/mem, fingerprints) gather
        via np.fromiter and quantize in one vector pass; only the
        irregular pieces (generic kinds, host ports, per-service counts,
        the failure set) stay per-row Python. Bit-identical to the
        scalar path (tests/test_steady_fastpath.py pins it); the win is
        the crash-heal regime, where poison_all_numeric re-derives every
        row at once."""
        idx = np.asarray(rows, np.int64)
        infos = [infos_all[i] for i in rows]
        n = len(infos)
        self.total0[idx] = np.fromiter(
            (i.active_tasks_count for i in infos), np.int64, n
        ).astype(np.int32)
        cpus = np.fromiter(
            (i.available_resources.nano_cpus for i in infos), np.int64, n)
        mems = np.fromiter(
            (i.available_resources.memory_bytes for i in infos), np.int64, n)
        self._raw_avail[idx, 0] = cpus
        self._raw_avail[idx, 1] = mems
        self.avail_res[idx, 0] = np.clip(
            cpus // CPU_QUANTUM, 0, _INT32_MAX).astype(np.int32)
        self.avail_res[idx, 1] = np.clip(
            mems // MEM_QUANTUM, 0, _INT32_MAX).astype(np.int32)
        self.port_used[idx] = False
        if self._svc_mat.shape[0]:
            self._svc_mat[:, idx] = 0
        kinds = self.kinds
        failure_add = self._failure_ids.add
        failure_discard = self._failure_ids.discard
        for i, info in zip(rows, infos):
            avail = info.available_resources
            if kinds:
                row = self.avail_res[i]
                generic = avail.generic
                named = avail.named_generic
                for j, kind in enumerate(kinds):
                    row[2 + j] = (generic.get(kind, 0)
                                  + len(named.get(kind, ())))
            if info.used_host_ports:
                port_ids = self._port_ids(info.used_host_ports)
                self._grow_bool_cols()
                self.port_used[i, port_ids] = True
            for s, cnt in info.active_tasks_count_by_service.items():
                if cnt:
                    row_s = self._svc_row_for(s)
                    self._svc_mat[row_s, i] = cnt
            if info.recent_failures:
                failure_add(info.node.id)
            else:
                failure_discard(info.node.id)
        self._fp_seq[idx] = np.fromiter(
            (i.created_seq for i in infos), np.int64, n)
        self._fp_mut[idx] = np.fromiter(
            (i.mutations for i in infos), np.int64, n)

    def _encode_row(self, i: int, info: NodeInfo) -> None:
        node = info.node
        self.ready[i] = self._rf.check(info)
        for ck, col in self.key_cols.items():
            self.node_val[i, col] = self.val_vocab.id(
                _node_key_value(node, ck))
        desc = node.description
        if desc and desc.platform:
            self.node_plat[i, 0] = self.os_vocab.id(desc.platform.os.lower())
            self.node_plat[i, 1] = self.arch_vocab.id(
                normalize_arch(desc.platform.architecture))
        else:
            self.node_plat[i] = 0
        plug_ids = list(self._default_plug_ids)
        plugins = (desc.plugins if desc else None) or []
        if plugins:
            cache = self._plug_id
            for key in plugins:
                pid = cache.get(key)
                if pid is None:
                    pid = self.plugin_vocab.id(f"{key[0]}/{key[1]}")
                    cache[key] = pid
                plug_ids.append(pid)
        self._grow_bool_cols()
        self.node_plugins[i] = False
        self.node_plugins[i, plug_ids] = True

        for (kind, label), col in self._label_cols.items():
            col[i] = _node_label(node, kind, label)
        self._encode_row_numeric(i, info)

    def _label_col(self, kind: str, label: str) -> np.ndarray:
        col = self._label_cols.get((kind, label))
        if col is None:
            col = np.array(
                [_node_label(info.node, kind, label) for info in self._infos]
                or [], dtype=object)
            if col.shape != (len(self._infos),):
                col = np.full(len(self._infos), "", object)
            self._label_cols[(kind, label)] = col
        return col

    # ------------------------------------------------------ vol-topo tables
    def _voltopo_tables(self, groups, volume_set):
        """Resolve csi-mounting groups to kernel vol-topo rows (ISSUE 19).

        Returns (rows_per_group, fallback_groups, infeasible_groups). A
        row is (mount_idx, key_col, val_id, ...) over csi pseudo-key
        columns: the driver-presence pair plus the sorted topology
        segments of ONE (candidate volume, accessible-topology
        alternative). Node-independent candidate legs — availability,
        pending delete, sharing=="none" in use — filter host-side here
        (volumes.go isVolumeAvailableOnNode order); segment values LOOK
        UP (encoder contract: a value no node carries resolves to -1,
        matching nothing). What rows can't express sends the group to
        the check_volumes_on_node fallback walk: pinned single-scope
        volumes (usable only on its current nodes), > VOL_TOPO_MOUNTS
        mounts, a topology with > VOL_TOPO_SEGS-1 segments, or
        > VOL_TOPO_MAX_ALT total rows. A mount with NO usable candidate
        at all makes the group infeasible outright (extra_mask blank).
        """
        rows_per_group: list[list[tuple[int, ...]]] = [[] for _ in groups]
        fallback: set[int] = set()
        infeasible: set[int] = set()
        if volume_set is None:
            return rows_per_group, fallback, infeasible
        from ..csi.volumes import task_csi_mounts

        with volume_set._lock:
            for gi, g in enumerate(groups):
                mounts = task_csi_mounts(g.tasks[0])
                if not mounts:
                    continue
                if len(mounts) > VOL_TOPO_MOUNTS:
                    fallback.add(gi)
                    continue
                rows: list[tuple[int, ...]] = []
                bail = done = False
                for mi, m in enumerate(mounts):
                    m_rows: list[tuple[int, ...]] = []
                    for v in volume_set._candidates(m.source):
                        if v.spec.availability != "active" \
                                or v.pending_delete:
                            continue
                        u = volume_set.usage.get(v.id)
                        mode = v.spec.access_mode
                        if mode.sharing == "none" and u is not None \
                                and u.tasks:
                            continue
                        if mode.scope == "single" and u is not None \
                                and u.nodes:
                            bail = True     # pinned to node IDS, not a
                            break           # (driver, topology) predicate
                        driver = v.spec.driver
                        pname = _csi_presence_key(driver)
                        self._ensure_key(pname)
                        ppair = (self.key_cols[pname],
                                 self.val_vocab.lookup("=1"))
                        info = v.volume_info
                        topos = (info.accessible_topology
                                 if info is not None else [])
                        if not topos:
                            m_rows.append((mi,) + ppair)
                            continue
                        for topo in topos:
                            if len(topo) > VOL_TOPO_SEGS - 1:
                                bail = True
                                break
                            row = [mi, *ppair]
                            for k in sorted(topo):
                                kname = _csi_topo_key(driver, k)
                                self._ensure_key(kname)
                                row.append(self.key_cols[kname])
                                row.append(self.val_vocab.lookup(
                                    "=" + topo[k]))
                            m_rows.append(tuple(row))
                        if bail:
                            break
                    if bail:
                        break
                    if not m_rows:
                        # no usable candidate for this mount: no node can
                        # ever satisfy the group (check_volumes_on_node
                        # would answer False everywhere)
                        infeasible.add(gi)
                        done = True
                        break
                    rows.extend(m_rows)
                if bail or (not done and len(rows) > VOL_TOPO_MAX_ALT):
                    fallback.add(gi)
                elif not done:
                    rows_per_group[gi] = rows
        return rows_per_group, fallback, infeasible

    def _voltopo_emit(self, rows_per_group, G: int) -> np.ndarray:
        VA = max((len(r) for r in rows_per_group), default=0)
        if VA == 0:
            arr = self._voltopo_empty.get(G)
            if arr is None:
                arr = _empty_vol_topo(G)
                self._voltopo_empty[G] = arr
            return arr
        key = tuple(tuple(rs) for rs in rows_per_group)
        cached = self._voltopo_cache
        if cached is not None and cached[0] == key:
            return cached[1]
        arr = np.full((G, VA, 1 + 2 * VOL_TOPO_SEGS), -1, np.int32)
        for gi, rs in enumerate(rows_per_group):
            for ri, row in enumerate(rs):
                arr[gi, ri, :len(row)] = row
        self._voltopo_cache = (key, arr)
        return arr

    # --------------------------------------------------------- placement fold
    def apply_counts(self, p: EncodedProblem, counts: np.ndarray) -> bool:
        """Fold a tick's own applied placements back into the cached rows —
        vectorized, no per-node Python — and restamp fingerprints so the next
        tick sees no dirty rows from placements the scheduler itself made.

        Contract: called immediately after the scheduler applied EXACTLY one
        `NodeInfo.add_task` per placed task of this tick (counts[g, n] tasks
        of group g onto node n), with no other NodeInfo mutations in between;
        `p` must be the problem this encoder emitted for the tick. add_task
        bumps `mutations` once per call, so the new fingerprint per node is
        (created_seq, mutations + placed_on_node) — anything else that moved
        the counters shows up as a mismatch next tick and re-encodes (safe).
        Returns False (caller should skip folding) when node sets diverged.

        Pipelined split (ops/pipeline.py): `fold_counts` is the array fold
        alone — everything the NEXT encode() needs — and `restamp_counts`
        is the fingerprint stamp, legal only once the add_task loop ran.
        Between the two calls the encoder arrays are ahead of the NodeInfo
        objects; `encode()` is safe in that window ONLY while no node row
        is dirty (`nodes_clean`), because a dirty row would re-encode from
        the not-yet-updated info and clobber the fold.
        """
        if not self.fold_counts(p, counts):
            return False
        self.restamp_counts(p, counts)
        return True

    def nodes_clean(self, infos) -> bool:
        """Read-only dirty check: True iff `encode(infos, …)` would find
        zero dirty rows and no remap. The pipelined tick driver uses
        this to decide whether encode() may run before the deferred
        add_task/restamp of the previous wave.

        Tracked mode answers from the mark flags alone — O(1), no
        NodeInfo reads, and therefore legal while a background heavy
        commit is still bumping mutation counters (the encode/commit
        overlap's gate). Untracked mode pays the full fingerprint scan.
        """
        if self.tracked:
            if not self._tracked_clean():
                return False
            infos = infos if hasattr(infos, "__len__") else list(infos)
            return len(infos) == len(self._ids)
        infos = sorted(infos, key=lambda i: i.node.id)
        if [i.node.id for i in infos] != self._ids:
            return False
        self.fp_scans += 1
        n = len(infos)
        seq = np.fromiter((i.created_seq for i in infos), np.int64, n)
        mut = np.fromiter((i.mutations for i in infos), np.int64, n)
        return bool(np.array_equal(seq, self._fp_seq)
                    and np.array_equal(mut, self._fp_mut))

    def force_numeric_reencode(self, rows: np.ndarray) -> None:
        """Poison `rows`' numeric fingerprints so the next encode()
        re-derives their numeric columns from the NodeInfo objects.

        The pipelined unclean-commit heal needs this: an optimistic
        fold_counts cannot be reverted row-wise, and a node whose decided
        placements ALL failed to commit never had its mutation counter
        bumped — its fingerprint still matches, so without poisoning the
        phantom reservations would persist and break oracle parity."""
        rows = np.asarray(rows, np.int64)
        if rows.size:
            self._fp_mut[rows] -= 1
            if self.tracked:
                # the zero-scan path never reads fingerprints: the heal
                # must also land in the mark feed
                for r in rows.tolist():
                    self._mark_numeric[self._ids[r]] = self._infos[r]

    def poison_all_numeric(self) -> None:
        """Crash-path heal: poison EVERY row's numeric fingerprint. The
        async commit worker can die before it even enters the job (so no
        wave was recorded for the targeted heal) — any row may then
        carry an optimistic fold no add_task ever backed."""
        self._fp_mut -= 1
        if self.tracked:
            self._mark_all_numeric = True

    def restamp_counts(self, p: EncodedProblem, counts: np.ndarray) -> bool:
        """Fingerprint half of apply_counts: stamp the add_task mutation
        bumps. Call exactly once per folded tick, after the add_task loop."""
        if p.node_ids != self._ids:
            return False
        placed = counts.astype(np.int64).sum(axis=0)
        if placed.any():
            self._fp_mut += placed
        return True

    def fold_counts(self, p: EncodedProblem, counts: np.ndarray) -> bool:
        """Array half of apply_counts: fold this tick's placements into the
        cached node tables (totals, resources, service counts, ports)
        WITHOUT touching fingerprints — see apply_counts docstring."""
        if p.node_ids != self._ids:
            return False
        counts64 = counts.astype(np.int64)
        placed = counts64.sum(axis=0)                     # [N]
        if not placed.any():
            return True
        G = counts.shape[0]
        self.total0 += placed.astype(np.int32)

        raw_need = np.zeros((G, 2), np.int64)
        for gi, g in enumerate(p.groups):
            res = g.spec.resources.reservations
            raw_need[gi, 0] = res.nano_cpus
            raw_need[gi, 1] = res.memory_bytes
        self._raw_avail -= counts64.T @ raw_need
        q = self._raw_avail[:, 0] // CPU_QUANTUM
        self.avail_res[:, 0] = np.clip(q, 0, _INT32_MAX)
        q = self._raw_avail[:, 1] // MEM_QUANTUM
        self.avail_res[:, 1] = np.clip(q, 0, _INT32_MAX)
        if self.kinds:
            gen_need = np.asarray(p.need_res[:, 2:], np.int64)
            if gen_need.any():
                # no clamp: mirrors _encode_row_numeric's unclamped read of
                # the generic pools so a later re-encode agrees bit-for-bit.
                # Slice to the problem's width: the kind vocab may have
                # grown (append-only) since this wave was encoded.
                k = 2 + gen_need.shape[1]
                used = counts64.T @ gen_need              # [N, kinds]
                self.avail_res[:, 2:k] = (
                    self.avail_res[:, 2:k].astype(np.int64) - used
                ).astype(np.int32)

        for gi, g in enumerate(p.groups):
            row = self._svc_row_for(g.service_id)
            self._svc_mat[row] += counts[gi].astype(np.int32)
            if p.has_ports[gi]:
                pids = np.flatnonzero(p.group_ports[gi])
                self.port_used[np.ix_(counts[gi] > 0, pids)] = True
        return True

    # ------------------------------------------------------------------ tick
    def encode(
        self,
        node_infos,
        groups: list[TaskGroup],
        now: float | None = None,
        volume_set=None,
    ) -> EncodedProblem:
        groups = sorted(groups, key=lambda g: g.key)
        t_scan = time.perf_counter()
        resolved = None
        if self.tracked:
            if not hasattr(node_infos, "__len__"):
                node_infos = list(node_infos)
            resolved = self._tracked_dirty(node_infos)
        if resolved is not None:
            # zero-scan fast path: dirty rows come from the mark feed;
            # the caller's list is only length-checked (same node set by
            # the tracked contract) — no sort, no id compare, no
            # per-node fingerprint reads
            dirty, numeric_dirty = resolved
            node_infos = self._infos
            self.last_remap = False
        else:
            node_infos = sorted(node_infos, key=lambda i: i.node.id)
            dirty, numeric_dirty = self._sync_nodes(node_infos)
            self.fp_scans += 1
        self._clear_marks()     # scan or mark resolution consumed them
        self.last_scan_s = time.perf_counter() - t_scan
        if dirty or self.last_remap:
            # full re-encodes rewrite label columns: spread ranks derived
            # from them may no longer match (numeric dirt never can)
            self._label_gen += 1
        N, G = len(node_infos), len(groups)

        # ------------------------------------------------ parse constraints
        parsed: list[list[constraint_mod.Constraint] | None] = []
        for g in groups:
            exprs = g.spec.placement.constraints
            if not exprs:
                parsed.append([])
                continue
            try:
                parsed.append(constraint_mod.parse(exprs))
            except constraint_mod.InvalidConstraint:
                parsed.append(None)  # unparseable → group matches nothing

        # ------------------------- group-side vocab / column growth (rare)
        for cs in parsed:
            for c in cs or []:
                ck = _canon_key(c.key)
                if ck is None or ck == "node.ip":
                    continue  # unknown → extra_mask; node.ip → host-side
                self._ensure_key(ck)
        for kind in sorted({k for g in groups
                            for k in g.spec.resources.reservations.generic}):
            self._ensure_kind(kind)

        # CSI vol-topo kernel rows (ISSUE 19): resolved EARLY — the csi
        # pseudo-key columns they intern must exist before the node_val
        # copy below picks up K
        vt_rows, vt_fallback, vt_infeasible = self._voltopo_tables(
            groups, volume_set)

        plugin_filter = PluginFilter()
        group_plugin_reqs: list[list[int]] = []
        for g in groups:
            reqs: list[int] = []
            if plugin_filter.set_task(g.tasks[0]):
                for drv in plugin_filter._volume_drivers:
                    reqs.append(self.plugin_vocab.id(f"Volume/{drv}"))
                for drv in plugin_filter._network_drivers:
                    reqs.append(self.plugin_vocab.id(f"Network/{drv}"))
                if plugin_filter._log_driver:
                    reqs.append(
                        self.plugin_vocab.id(f"Log/{plugin_filter._log_driver}"))
            group_plugin_reqs.append(reqs)

        # group ports must get columns even when no node uses them yet:
        # two groups publishing the same fresh port conflict through the
        # kernel's port_used updates within one tick
        group_port_lists: list[list[int]] = []
        for g in groups:
            ports = []
            endpoint = getattr(g.tasks[0], "endpoint", None)
            spec_ports = endpoint.ports if endpoint else []
            for pc in spec_ports:
                if pc.publish_mode == "host" and pc.published_port != 0:
                    ports.append(
                        self.port_vocab.id(f"{pc.protocol}:{pc.published_port}"))
            group_port_lists.append(ports)
        self._grow_bool_cols()

        # ------------------------------------------------- dirty node rows
        self.last_dirty = len(dirty) + len(numeric_dirty)
        self.last_full = len(dirty)
        self.last_dirty_rows = np.fromiter(
            sorted(dirty | numeric_dirty), np.int64,
            count=len(dirty | numeric_dirty))
        for i in sorted(dirty):
            self._encode_row(i, node_infos[i])
        if len(numeric_dirty) >= 64:
            # crash heals (poison_all_numeric) and mass churn re-derive
            # thousands of rows at once: the scalar per-row path pays
            # ~20 Python ops per row where a fromiter gather pays ~3
            self._encode_rows_numeric_bulk(sorted(numeric_dirty),
                                           node_infos)
        else:
            for i in sorted(numeric_dirty):
                self._encode_row_numeric(i, node_infos[i])

        # ------------------------------------------------------------ emit
        p = EncodedProblem(
            node_ids=list(self._ids),
            group_keys=[g.key for g in groups],
            service_ids=sorted({g.service_id for g in groups}),
            groups=groups,
            row_infos=list(self._infos),
            infos_seq=self.infos_seq,
        )
        p.strategy = self.strategy
        svc_row = {s: i for i, s in enumerate(p.service_ids)}
        S = max(len(p.service_ids), 1)

        # node side: copies — rows mutate in place on later ticks, the
        # emitted problem must stay self-consistent for its consumer
        p.ready = self.ready.copy()
        p.total0 = self.total0.copy()
        p.node_val = self.node_val.copy()
        p.node_plat = self.node_plat.copy()
        p.node_plugins = self.node_plugins.copy()
        p.port_used0 = self.port_used.copy()
        p.avail_res = self.avail_res.copy()
        p.svc_count0 = np.zeros((S, N), np.int32)
        for s, row in svc_row.items():
            mrow = self._svc_row.get(s)
            if mrow is not None:
                p.svc_count0[row] = self._svc_mat[mrow]

        # ------------------------------------------------ group-side tables
        K = max(len(self.key_cols), 1)
        if p.node_val.shape[1] < K:
            p.node_val = np.concatenate(
                [p.node_val, np.zeros((N, K - p.node_val.shape[1]), np.int32)],
                axis=1)
        R = 2 + len(self.kinds)
        PL = p.node_plugins.shape[1]
        PV = p.port_used0.shape[1]

        p.n_tasks = np.array([len(g.tasks) for g in groups] or [],
                             np.int32).reshape(G)
        p.svc_idx = np.array([svc_row[g.service_id] for g in groups] or [],
                             np.int32).reshape(G)
        # persistent service rows for the device-resident path: the device
        # carries the encoder's grow-only service matrix, so its kernel
        # indexes by persistent row, not the tick-local svc_idx. Groups
        # LOOK UP (the encoder contract) — a service with no row yet gets
        # a HYPOTHETICAL one: the row apply_counts will allocate if this
        # tick's placements land, numbered in group order exactly like
        # apply_counts' _svc_row_for loop, so device and host agree.
        # Until then the row holds zeros on both sides.
        hypo: dict[str, int] = {}
        rows = []
        for g in groups:
            r = self._svc_row.get(g.service_id)
            if r is None:
                r = hypo.get(g.service_id)
                if r is None:
                    r = len(self._svc_row) + len(hypo)
                    hypo[g.service_id] = r
            rows.append(r)
        p.svc_idx_persistent = np.array(rows or [], np.int32).reshape(G)
        p.n_svc_rows = len(self._svc_row) + len(hypo)
        p.has_hypo_rows = bool(hypo)
        p.need_res = np.zeros((G, R), np.int32)
        p.max_replicas = np.zeros(G, np.int32)
        C = self.max_constraints
        p.constraints = np.full((G, C, 3), -1, np.int32)
        p.plat_req = np.full((G, self.max_platforms, 2), -2, np.int32)
        p.req_plugins = np.zeros((G, PL), bool)
        p.has_ports = np.zeros(G, bool)
        p.group_ports = np.zeros((G, PV), bool)
        p.penalty = np.zeros((G, N), bool)
        p.extra_mask = np.ones((G, N), bool)
        # exact-or-conservative dispatch gates: True/False the moment a
        # write lands (ops/resident.py skips its O(G·N) scans on these)
        extra_all = True
        pen_any = False

        group_row = {g.key: i for i, g in enumerate(groups)}

        for gi, g in enumerate(groups):
            res = g.spec.resources.reservations
            cpu, mem = quantize_need(res)
            p.need_res[gi, 0], p.need_res[gi, 1] = cpu, mem
            for j, kind in enumerate(self.kinds):
                p.need_res[gi, 2 + j] = res.generic.get(kind, 0)
            p.max_replicas[gi] = g.spec.placement.max_replicas

            cs = parsed[gi]
            if cs is None:
                p.extra_mask[gi, :] = False
                extra_all = False
            else:
                ci = 0
                for c in cs:
                    ck = _canon_key(c.key)
                    if ck is None:
                        # unknown key matches no node, regardless of operator
                        # (reference constraint.go default case)
                        p.extra_mask[gi, :] = False
                        extra_all = False
                        continue
                    if ck == "node.ip":
                        extra_all = False       # conservative: may write
                        for n, info in enumerate(node_infos):
                            if not constraint_mod._match_ip(
                                    c, info.node.status.addr or ""):
                                p.extra_mask[gi, n] = False
                        continue
                    if ci >= C:
                        # overflow constraints evaluated host-side (rare)
                        extra_all = False       # conservative: may write
                        for n, info in enumerate(node_infos):
                            _, cands = constraint_mod.node_attribute(
                                info.node, ck)
                            if not c.match(*cands):
                                p.extra_mask[gi, n] = False
                        continue
                    p.constraints[gi, ci] = (
                        self.key_cols[ck],
                        OP_EQ if c.operator == constraint_mod.EQ else OP_NEQ,
                        self.val_vocab.lookup(_canon_value(ck, c.exp)),
                    )
                    ci += 1

            platforms = g.spec.placement.platforms
            for pi, plat in enumerate(platforms[:self.max_platforms]):
                wos = plat.os.lower()
                warch = (normalize_arch(plat.architecture)
                         if plat.architecture else "")
                p.plat_req[gi, pi, 0] = self.os_vocab.lookup(wos) if wos else 0
                p.plat_req[gi, pi, 1] = (self.arch_vocab.lookup(warch)
                                         if warch else 0)

            for pid in group_plugin_reqs[gi]:
                p.req_plugins[gi, pid] = True
            for pid in group_port_lists[gi]:
                p.group_ports[gi, pid] = True
            p.has_ports[gi] = bool(group_port_lists[gi])

        # ------------------------------------------------- spread preferences
        # (nodeset.go:50-124) resolve each group's spread descriptors to label
        # lookups; a non-label descriptor is skipped without consuming a
        # level, and a missing label buckets the node under "" (own branch)
        def _spread_labels(g: TaskGroup) -> list[tuple[str, str]]:
            out = []
            for pref in g.spec.placement.preferences:
                d = pref.spread_descriptor
                dl = d.lower()
                for prefix, kind in ((constraint_mod.NODE_LABEL_PREFIX, "node"),
                                     (constraint_mod.ENGINE_LABEL_PREFIX,
                                      "engine")):
                    if dl.startswith(prefix) and len(d) > len(prefix):
                        out.append((kind, d[len(prefix):]))
                        break
            return out

        group_spread = [_spread_labels(g) for g in groups]
        if self._topo_pair is not None:
            # topology strategy (ISSUE 19): the configured axis becomes
            # the OUTERMOST level of every group — prefix ranks stay
            # properly nested, and the tree kernel/oracle are unchanged
            group_spread = [[self._topo_pair] + s for s in group_spread]
        LMAX = max((len(s) for s in group_spread), default=0)
        skey = (tuple(tuple(s) for s in group_spread), N, LMAX,
                self._label_gen)
        cached = self._spread_cache
        if LMAX and cached is not None and cached[0] == skey:
            # steady tick, unchanged labels: re-emit the SAME array object
            # — the resident group-table cache gates on identity, so both
            # the O(G·L·N) rebuild and the device re-upload are skipped.
            # Consumers treat emitted spread tables as read-only.
            p.spread_rank = cached[1]
        else:
            p.spread_rank = np.zeros((G, LMAX, N), np.int32)
            if LMAX:
                # rank value paths per (group, level) in numpy over the
                # cached per-label value columns — host work O(N) per
                # distinct label
                for gi, spread in enumerate(group_spread):
                    if not spread:
                        continue
                    prefix = np.zeros(N, np.int64)
                    for li, (kind, label) in enumerate(spread):
                        vals = self._label_col(kind, label)
                        # ids ordered by value string => prefix ranks sort
                        # lexicographically level by level
                        _, col = np.unique(vals, return_inverse=True)
                        combo = prefix * (int(col.max(initial=0)) + 1) + col
                        # contiguous ranks preserving (prefix, value) order
                        _, ranks = np.unique(combo, return_inverse=True)
                        p.spread_rank[gi, li] = ranks.astype(np.int32)
                        prefix = ranks.astype(np.int64)
                    for li in range(len(spread), LMAX):
                        p.spread_rank[gi, li] = \
                            p.spread_rank[gi, len(spread) - 1]
                self._spread_cache = (skey, p.spread_rank)

        # penalties: only iterate nodes that actually recorded failures
        for nid in self._failure_ids:
            i = self._idx.get(nid)
            if i is None:
                continue
            info = node_infos[i]
            for fkey in list(info.recent_failures):
                gi = group_row.get(fkey)
                if gi is not None and info.penalized(fkey, now):
                    p.penalty[gi, i] = True
                    pen_any = True

        # CSI volume feasibility (ISSUE 19): the common shape — driver
        # presence + accessible-topology match — rides the kernel's
        # vol_topo rows (built above; ops/placement._vol_topo_ok). What
        # rows can't express (see _voltopo_tables) keeps the host-side
        # check_volumes_on_node extra_mask walk, still the oracle; a
        # mount with NO usable candidate blanks the group outright.
        for gi in vt_infeasible:
            p.extra_mask[gi, :] = False
            extra_all = False
        if vt_fallback:
            for gi in sorted(vt_fallback):
                probe = groups[gi].tasks[0]
                extra_all = False               # conservative: may write
                for n, info in enumerate(node_infos):
                    if p.extra_mask[gi, n] and \
                            not volume_set.check_volumes_on_node(info, probe):
                        p.extra_mask[gi, n] = False
        p.vol_topo = self._voltopo_emit(vt_rows, G)
        p.vol_topo_any = bool(p.vol_topo.shape[1])

        p.penalty_nonzero = pen_any
        p.extra_mask_all = extra_all
        return p


def encode(
    node_infos: list[NodeInfo],
    groups: list[TaskGroup],
    now: float | None = None,
    max_constraints: int = 8,
    max_platforms: int = 4,
    volume_set=None,
    strategy: str = "spread",
    topology: str | None = None,
) -> EncodedProblem:
    """One-shot encode: a fresh IncrementalEncoder over the full cluster."""
    enc = IncrementalEncoder(max_constraints=max_constraints,
                             max_platforms=max_platforms,
                             strategy=strategy, topology=topology)
    return enc.encode(node_infos, groups, now=now, volume_set=volume_set)


def fold_problem(p_next: EncodedProblem, p_prev: EncodedProblem,
                 counts_prev: np.ndarray) -> bool:
    """Fold a still-uncommitted earlier wave's placements into a LATER
    emitted problem, in the kernel's QUANTIZED domain.

    A depth-D tick pipeline (ops/pipeline.py) encodes wave k before the
    host has pulled/folded waves k-D+1..k-1, so p_next's node snapshot
    is stale by those waves — but the device kernel is NOT: its in-scan
    carry already folded them (quantized needs, exactly what the CPU
    oracle's sequential-group fold does). Applying that same fold to the
    emitted arrays makes the oracle fill and the slot materialization on
    p_next bit-match the kernel again:

        total0     += counts.sum(groups)
        avail_res  -= counts^T @ need_res        (quantized, unclamped —
                                                  mirrors the oracle's
                                                  in-fill subtraction)
        port_used0 |= group ports of placed nodes
        svc_count0 += counts, joined by SERVICE ID (tick-local rows
                      differ between problems)

    Group-side vocab GROWTH between the encodes (new generic kinds, new
    port ids — both append-only) is fine: the earlier wave's tables are
    prefix-compatible and fold into the leading columns. Returns False
    only when the node set changed — the caller must then drain to the
    serial order. Fingerprints and the encoder's own arrays are
    untouched: this mutates only the emitted problem's copies.
    """
    if (p_next.node_ids != p_prev.node_ids
            or p_next.avail_res.shape[1] < p_prev.need_res.shape[1]
            or p_next.port_used0.shape[1] < p_prev.group_ports.shape[1]):
        return False
    c = np.asarray(counts_prev, np.int64)
    placed = c.sum(axis=0)
    if not placed.any():
        return True
    p_next.total0 = (p_next.total0.astype(np.int64)
                     + placed).astype(np.int32)
    r_prev = p_prev.need_res.shape[1]
    p_next.avail_res[:, :r_prev] = (
        p_next.avail_res[:, :r_prev].astype(np.int64)
        - c.T @ p_prev.need_res.astype(np.int64)).astype(np.int32)
    for gi in np.flatnonzero(p_prev.has_ports):
        pids = np.flatnonzero(p_prev.group_ports[gi])
        if pids.size:
            p_next.port_used0[np.ix_(c[gi] > 0, pids)] = True

    acc: dict[str, np.ndarray] = {}
    for gj, g in enumerate(p_prev.groups):
        if c[gj].any():
            cur = acc.get(g.service_id)
            acc[g.service_id] = c[gj] if cur is None else cur + c[gj]
    if acc:
        next_row = {g.service_id: int(p_next.svc_idx[i])
                    for i, g in enumerate(p_next.groups)}
        for sid, vec in acc.items():
            r = next_row.get(sid)
            if r is not None:
                p_next.svc_count0[r] = (
                    p_next.svc_count0[r].astype(np.int64)
                    + vec).astype(np.int32)
    return True
