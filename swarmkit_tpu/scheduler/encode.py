"""Dictionary-encoding of cluster state into dense arrays for the TPU kernel.

The reference's scheduler walks Go maps and compares strings per (task, node)
pair (manager/scheduler/scheduler.go:694-921, filter.go). The TPU backend
instead interns every string host-side — constraint keys/values, platforms,
plugin names, host ports — into integer vocabularies, and ships dense int32
tables to the device. All O(G×N) work (constraint matching, platform/plugin
gating, spread water-fill) happens inside the jitted kernel
(`swarmkit_tpu.ops.placement.schedule_groups`); host work is O(nodes + tasks).

Quantization spec (part of this framework's scheduling semantics, applied to
BOTH backends so they stay bit-identical):
  * CPU  reservations → milli-cores, task needs rounded up, node capacity down;
  * memory            → 4 KiB pages, same rounding;
which guarantees the batched path never overcommits a node.

Host-only predicates that don't reduce to interned-int equality (node.ip
IP/CIDR math — reference constraint.go:127-146 — and unparseable constraint
sets) are folded into a per-group `extra_mask` correction column, per
SURVEY.md §7's guidance on strings/IP math.
"""
from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..api.types import normalize_arch
from . import constraint as constraint_mod
from .filters import PluginFilter, ReadyFilter
from .nodeinfo import NodeInfo

UNLIMITED = 1 << 30
OP_EQ = 0
OP_NEQ = 1

CPU_QUANTUM = 1_000_000      # nano-cpus per milli-core
MEM_QUANTUM = 4096           # bytes per page


class Vocab:
    """String interner. id 0 is reserved for the empty string."""

    def __init__(self):
        self._ids: dict[str, int] = {"": 0}

    def id(self, s: str) -> int:
        return self._ids.setdefault(s, len(self._ids))

    def lookup(self, s: str) -> int:
        """-1 when unseen: an unseen node value can never equal a constraint
        value id, and -1 != every valid id keeps != semantics right."""
        return self._ids.get(s, -1)

    def __len__(self):
        return len(self._ids)


@dataclass
class TaskGroup:
    """One (service_id, spec_version) scheduling group — all tasks identical."""

    service_id: str
    spec_version: int
    tasks: list  # api.objects.Task, sorted by id

    @property
    def key(self) -> tuple[str, int]:
        return (self.service_id, self.spec_version)

    @property
    def spec(self):
        return self.tasks[0].spec


@dataclass
class EncodedProblem:
    """Device-ready staging arrays (numpy, host)."""

    node_ids: list[str]
    group_keys: list[tuple[str, int]]
    service_ids: list[str]
    groups: list[TaskGroup] = field(repr=False, default_factory=list)

    # node side
    ready: np.ndarray = None          # bool[N]
    avail_res: np.ndarray = None      # int32[N, R]
    total0: np.ndarray = None         # int32[N]
    svc_count0: np.ndarray = None     # int32[S, N]
    node_val: np.ndarray = None       # int32[N, K] interned value per key col
    node_plat: np.ndarray = None      # int32[N, 2] (os_id, arch_id)
    node_plugins: np.ndarray = None   # bool[N, PL]
    port_used0: np.ndarray = None     # bool[N, PV]

    # group side
    n_tasks: np.ndarray = None        # int32[G]
    svc_idx: np.ndarray = None        # int32[G]
    need_res: np.ndarray = None       # int32[G, R]
    max_replicas: np.ndarray = None   # int32[G]; 0 == unlimited
    constraints: np.ndarray = None    # int32[G, C, 3] (key_col, op, val); col<0 pad
    plat_req: np.ndarray = None       # int32[G, P, 2]; (-2,-2) pad row; 0 wildcard
    req_plugins: np.ndarray = None    # bool[G, PL]
    has_ports: np.ndarray = None      # bool[G]
    group_ports: np.ndarray = None    # bool[G, PV]
    penalty: np.ndarray = None        # bool[G, N]
    extra_mask: np.ndarray = None     # bool[G, N] host-side corrections
    # spread preferences (nodeset.go tree): node's branch id per level —
    # contiguous ranks of the label-value PATH PREFIX, lexicographically
    # sorted, so children of one parent occupy a contiguous id range;
    # levels past a group's preference count repeat the last real level
    # (a self-parented pour is a no-op)
    spread_rank: np.ndarray = None    # int32[G, LMAX, N]; LMAX may be 0


_INT32_MAX = (1 << 31) - 1


# Canonical positional order of EncodedProblem arrays as consumed by
# ops.placement.schedule_groups — the ONE place the positional-arg contract
# lives; bench, the graft entry, and the mesh sharder all derive from it.
KERNEL_ARG_FIELDS = (
    "ready", "node_val", "node_plat", "node_plugins", "extra_mask",
    "constraints", "plat_req", "req_plugins", "avail_res", "total0",
    "svc_count0", "n_tasks", "svc_idx", "need_res", "max_replicas",
    "penalty", "has_ports", "group_ports", "port_used0", "spread_rank",
)


def kernel_args(p: "EncodedProblem") -> tuple:
    """The problem's arrays in schedule_groups' positional order (numpy)."""
    return tuple(np.asarray(getattr(p, f)) for f in KERNEL_ARG_FIELDS)


def quantize_need(res) -> tuple[int, int]:
    cpu = -(-res.nano_cpus // CPU_QUANTUM) if res.nano_cpus > 0 else 0
    mem = -(-res.memory_bytes // MEM_QUANTUM) if res.memory_bytes > 0 else 0
    return min(cpu, _INT32_MAX), min(mem, _INT32_MAX)


def quantize_avail(res) -> tuple[int, int]:
    cpu = max(res.nano_cpus // CPU_QUANTUM, 0)
    mem = max(res.memory_bytes // MEM_QUANTUM, 0)
    return min(cpu, _INT32_MAX), min(mem, _INT32_MAX)


def _canon_value(key_lower: str, value: str) -> str:
    """Comparable form of an attribute value: case-folded (the reference
    compares case-insensitively, constraint.go:84-104). node.ip never reaches
    here — IP/CIDR math stays host-side in extra_mask."""
    return value.lower()


_PREDEFINED_KEYS = {
    "node.id", "node.hostname", "node.role",
    "node.platform.os", "node.platform.arch",
}


def _canon_key(key: str) -> str | None:
    """Canonical vocab form of a constraint key: predefined keys case-fold
    whole; label keys case-fold only the prefix — label *names* stay
    case-sensitive (reference constraint.go:175 'label itself is case
    sensitive'). None == unknown key, which matches no node regardless of
    operator (constraint.go default case)."""
    lk = key.lower()
    if lk in _PREDEFINED_KEYS or lk == "node.ip":
        return lk
    for prefix in (constraint_mod.NODE_LABEL_PREFIX,
                   constraint_mod.ENGINE_LABEL_PREFIX):
        if lk.startswith(prefix) and len(key) > len(prefix):
            return prefix + key[len(prefix):]
    return None


def encode(
    node_infos: list[NodeInfo],
    groups: list[TaskGroup],
    now: float | None = None,
    max_constraints: int = 8,
    max_platforms: int = 4,
    volume_set=None,
) -> EncodedProblem:
    node_infos = sorted(node_infos, key=lambda i: i.node.id)
    groups = sorted(groups, key=lambda g: g.key)
    N, G = len(node_infos), len(groups)

    p = EncodedProblem(
        node_ids=[i.node.id for i in node_infos],
        group_keys=[g.key for g in groups],
        service_ids=sorted({g.service_id for g in groups}),
        groups=groups,
    )
    svc_row = {s: i for i, s in enumerate(p.service_ids)}
    S = max(len(p.service_ids), 1)

    # ------------------------------------------------ parse group constraints
    parsed: list[list[constraint_mod.Constraint] | None] = []
    for g in groups:
        exprs = g.spec.placement.constraints
        if not exprs:
            parsed.append([])
            continue
        try:
            parsed.append(constraint_mod.parse(exprs))
        except constraint_mod.InvalidConstraint:
            parsed.append(None)  # unparseable → group matches nothing

    # ---------------------------------------------------------- vocabularies
    key_vocab: dict[str, int] = {}     # lowered constraint key -> column
    val_vocab = Vocab()
    plugin_vocab = Vocab()
    port_vocab = Vocab()
    os_vocab, arch_vocab = Vocab(), Vocab()

    for cs in parsed:
        for c in cs or []:
            ck = _canon_key(c.key)
            if ck is None or ck == "node.ip":
                continue  # unknown → extra_mask; node.ip → host-side
            key_vocab.setdefault(ck, len(key_vocab))
            val_vocab.id(_canon_value(ck, c.exp))

    plugin_filter = PluginFilter()
    group_plugin_reqs: list[list[int]] = []
    for g in groups:
        reqs: list[int] = []
        if plugin_filter.set_task(g.tasks[0]):
            for drv in plugin_filter._volume_drivers:
                reqs.append(plugin_vocab.id(f"Volume/{drv}"))
            for drv in plugin_filter._network_drivers:
                reqs.append(plugin_vocab.id(f"Network/{drv}"))
            if plugin_filter._log_driver:
                reqs.append(plugin_vocab.id(f"Log/{plugin_filter._log_driver}"))
        group_plugin_reqs.append(reqs)

    group_port_lists: list[list[int]] = []
    for g in groups:
        ports = []
        endpoint = getattr(g.tasks[0], "endpoint", None)
        spec_ports = endpoint.ports if endpoint else []
        for pc in spec_ports:
            if pc.publish_mode == "host" and pc.published_port != 0:
                ports.append(port_vocab.id(f"{pc.protocol}:{pc.published_port}"))
        group_port_lists.append(ports)

    K = max(len(key_vocab), 1)
    PL = max(len(plugin_vocab), 1)
    PV = max(len(port_vocab), 1)

    # ------------------------------------------------------- node-side tables
    p.ready = np.zeros(N, bool)
    p.total0 = np.zeros(N, np.int32)
    p.node_val = np.full((N, K), -1, np.int32)
    p.node_plat = np.zeros((N, 2), np.int32)
    p.node_plugins = np.zeros((N, PL), bool)
    p.port_used0 = np.zeros((N, PV), bool)

    kinds = sorted({k for g in groups for k in g.spec.resources.reservations.generic})
    R = 2 + len(kinds)
    p.avail_res = np.zeros((N, R), np.int32)
    p.svc_count0 = np.zeros((S, N), np.int32)

    rf = ReadyFilter()
    default_plugin_ids = [
        plugin_vocab.lookup(f"{t}/{n}") for t, n in PluginFilter.DEFAULT_PLUGINS
    ]
    for n, info in enumerate(node_infos):
        p.ready[n] = rf.check(info)
        p.total0[n] = info.active_tasks_count
        cpu, mem = quantize_avail(info.available_resources)
        p.avail_res[n, 0], p.avail_res[n, 1] = cpu, mem
        for j, kind in enumerate(kinds):
            have = info.available_resources.generic.get(kind, 0)
            have += len(info.available_resources.named_generic.get(kind, ()))
            p.avail_res[n, 2 + j] = have
        for s, cnt in info.active_tasks_count_by_service.items():
            row = svc_row.get(s)
            if row is not None:
                p.svc_count0[row, n] = cnt
        for ck, col in key_vocab.items():
            kind_, candidates = constraint_mod.node_attribute(info.node, ck)
            if kind_ == "unknown":  # unreachable for canonical keys; guard
                p.node_val[n, col] = -1
            else:
                p.node_val[n, col] = val_vocab.lookup(
                    _canon_value(ck, candidates[0]))
        desc = info.node.description
        if desc and desc.platform:
            p.node_plat[n, 0] = os_vocab.id(desc.platform.os.lower())
            p.node_plat[n, 1] = arch_vocab.id(normalize_arch(desc.platform.architecture))
        for t, name in (desc.plugins if desc else []):
            pid = plugin_vocab.lookup(f"{t}/{name}")
            if pid >= 0:
                p.node_plugins[n, pid] = True
        for pid in default_plugin_ids:
            if pid >= 0:
                p.node_plugins[n, pid] = True
        for proto, port in info.used_host_ports:
            pid = port_vocab.lookup(f"{proto}:{port}")
            if pid >= 0:
                p.port_used0[n, pid] = True

    # ------------------------------------------------------ group-side tables
    p.n_tasks = np.array([len(g.tasks) for g in groups] or [], np.int32).reshape(G)
    p.svc_idx = np.array([svc_row[g.service_id] for g in groups] or [],
                         np.int32).reshape(G)
    p.need_res = np.zeros((G, R), np.int32)
    p.max_replicas = np.zeros(G, np.int32)
    C = max_constraints
    p.constraints = np.full((G, C, 3), -1, np.int32)
    p.plat_req = np.full((G, max_platforms, 2), -2, np.int32)
    p.req_plugins = np.zeros((G, PL), bool)
    p.has_ports = np.zeros(G, bool)
    p.group_ports = np.zeros((G, PV), bool)
    p.penalty = np.zeros((G, N), bool)
    p.extra_mask = np.ones((G, N), bool)

    group_row = {g.key: i for i, g in enumerate(groups)}

    for gi, g in enumerate(groups):
        res = g.spec.resources.reservations
        cpu, mem = quantize_need(res)
        p.need_res[gi, 0], p.need_res[gi, 1] = cpu, mem
        for j, kind in enumerate(kinds):
            p.need_res[gi, 2 + j] = res.generic.get(kind, 0)
        p.max_replicas[gi] = g.spec.placement.max_replicas

        cs = parsed[gi]
        if cs is None:
            p.extra_mask[gi, :] = False
        else:
            ci = 0
            for c in cs:
                ck = _canon_key(c.key)
                if ck is None:
                    # unknown key matches no node, regardless of operator
                    # (reference constraint.go default case)
                    p.extra_mask[gi, :] = False
                    continue
                if ck == "node.ip":
                    for n, info in enumerate(node_infos):
                        if not constraint_mod._match_ip(
                                c, info.node.status.addr or ""):
                            p.extra_mask[gi, n] = False
                    continue
                if ci >= C:
                    # overflow constraints evaluated host-side (rare)
                    for n, info in enumerate(node_infos):
                        _, cands = constraint_mod.node_attribute(info.node, ck)
                        if not c.match(*cands):
                            p.extra_mask[gi, n] = False
                    continue
                p.constraints[gi, ci] = (
                    key_vocab[ck],
                    OP_EQ if c.operator == constraint_mod.EQ else OP_NEQ,
                    val_vocab.lookup(_canon_value(ck, c.exp)),
                )
                ci += 1

        platforms = g.spec.placement.platforms
        for pi, plat in enumerate(platforms[:max_platforms]):
            wos = plat.os.lower()
            warch = normalize_arch(plat.architecture) if plat.architecture else ""
            p.plat_req[gi, pi, 0] = os_vocab.lookup(wos) if wos else 0
            p.plat_req[gi, pi, 1] = arch_vocab.lookup(warch) if warch else 0

        for pid in group_plugin_reqs[gi]:
            p.req_plugins[gi, pid] = True
        for pid in group_port_lists[gi]:
            p.group_ports[gi, pid] = True
        p.has_ports[gi] = bool(group_port_lists[gi])

    # ------------------------------------------------- spread preferences
    # (nodeset.go:50-124) resolve each group's spread descriptors to label
    # lookups; a non-label descriptor is skipped without consuming a level,
    # and a missing label buckets the node under "" (its own branch)
    def _spread_labels(g: TaskGroup) -> list[tuple[str, str]]:
        out = []
        for pref in g.spec.placement.preferences:
            d = pref.spread_descriptor
            dl = d.lower()
            for prefix, kind in ((constraint_mod.NODE_LABEL_PREFIX, "node"),
                                 (constraint_mod.ENGINE_LABEL_PREFIX,
                                  "engine")):
                if dl.startswith(prefix) and len(d) > len(prefix):
                    out.append((kind, d[len(prefix):]))
                    break
        return out

    group_spread = [_spread_labels(g) for g in groups]
    LMAX = max((len(s) for s in group_spread), default=0)
    p.spread_rank = np.zeros((G, LMAX, N), np.int32)
    if LMAX:
        # a node's value for a (kind, label) is group-independent: intern
        # each distinct label column ONCE as an int array, then rank value
        # paths per (group, level) in numpy — keeps host work O(N) per
        # distinct label, not O(G × L × N) Python loops
        label_ids: dict[tuple[str, str], np.ndarray] = {}

        def label_col(kind: str, label: str) -> np.ndarray:
            col = label_ids.get((kind, label))
            if col is not None:
                return col
            values = []
            for info in node_infos:
                node = info.node
                if kind == "node":
                    labels = node.spec.annotations.labels or {}
                else:
                    desc = node.description
                    labels = (desc.engine_labels or {}) if desc else {}
                values.append(labels.get(label, ""))
            # ids ordered by value string => prefix ranks sort
            # lexicographically level by level
            uniq = sorted(set(values))
            to_id = {v: i for i, v in enumerate(uniq)}
            col = np.array([to_id[v] for v in values], np.int32)
            label_ids[(kind, label)] = col
            return col

        for gi, spread in enumerate(group_spread):
            if not spread:
                continue
            prefix = np.zeros(N, np.int64)
            for li, (kind, label) in enumerate(spread):
                col = label_col(kind, label)
                combo = prefix * (int(col.max(initial=0)) + 1) + col
                # contiguous ranks preserving (prefix, value) order
                _, ranks = np.unique(combo, return_inverse=True)
                p.spread_rank[gi, li] = ranks.astype(np.int32)
                prefix = ranks.astype(np.int64)
            for li in range(len(spread), LMAX):
                p.spread_rank[gi, li] = p.spread_rank[gi, len(spread) - 1]

    # penalties: only iterate nodes that actually recorded failures
    for n, info in enumerate(node_infos):
        for skey in list(info.recent_failures):
            gi = group_row.get(skey)
            if gi is not None and info.penalized(skey, now):
                p.penalty[gi, n] = True

    # CSI volume feasibility: host-side extra_mask correction, like node.ip
    # (scheduler/volumes.go isVolumeAvailableOnNode is string/set logic on
    # small cardinalities — not worth a kernel column)
    if volume_set is not None:
        from ..csi.volumes import task_csi_mounts

        for gi, g in enumerate(groups):
            probe = g.tasks[0]
            if not task_csi_mounts(probe):
                continue
            for n, info in enumerate(node_infos):
                if p.extra_mask[gi, n] and not volume_set.check_volumes_on_node(
                    info, probe
                ):
                    p.extra_mask[gi, n] = False

    return p
