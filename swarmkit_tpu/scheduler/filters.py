"""The scheduler's per-node gate: the filter pipeline.

Behavioral re-derivation of manager/scheduler/filter.go + pipeline.go.
Each filter declares whether it's enabled for a task (`set_task`) and then
gates candidate nodes (`check`). `Pipeline.process` short-circuits on the
first failing filter and tallies per-filter failure counts so `explain` can
produce the reference's "no suitable node" message ordering
(pipeline.go:84-103 sorts by failure count).

This chain is the exact boolean column set the TPU backend fuses into one
(task_group × node) mask kernel (swarmkit_tpu/ops/placement.py); the CPU
implementation here is the parity oracle.
"""
from __future__ import annotations

from typing import Protocol

from ..api.types import NodeAvailability, NodeStatusState, normalize_arch
from . import constraint as constraint_mod
from .nodeinfo import NodeInfo


class Filter(Protocol):
    def set_task(self, task) -> bool: ...
    def check(self, node: NodeInfo) -> bool: ...
    def explain(self, nodes: int) -> str: ...


class ReadyFilter:
    """reference: filter.go:31-51."""

    def set_task(self, task) -> bool:
        return True

    def check(self, node: NodeInfo) -> bool:
        n = node.node
        return (n.status.state == NodeStatusState.READY
                and n.spec.availability == NodeAvailability.ACTIVE)

    def explain(self, nodes: int) -> str:
        return "1 node not available for new tasks" if nodes == 1 else (
            f"{nodes} nodes not available for new tasks")


class ResourceFilter:
    """reference: filter.go:55-101."""

    def set_task(self, task) -> bool:
        r = task.spec.resources.reservations
        self._res = r
        return bool(r.nano_cpus or r.memory_bytes or r.generic)

    def check(self, node: NodeInfo) -> bool:
        avail = node.available_resources
        if self._res.nano_cpus > avail.nano_cpus:
            return False
        if self._res.memory_bytes > avail.memory_bytes:
            return False
        for kind, qty in self._res.generic.items():
            have = avail.generic.get(kind, 0) + len(avail.named_generic.get(kind, ()))
            if qty > have:
                return False
        return True

    def explain(self, nodes: int) -> str:
        if nodes == 1:
            return "insufficient resources on 1 node"
        return f"insufficient resources on {nodes} nodes"


class PluginFilter:
    """Volume/network/log drivers must exist on the node (filter.go:104-216).

    Node plugins are (type, name) pairs in NodeDescription.plugins; the
    implicit default engine plugins are always considered present.
    """

    DEFAULT_PLUGINS = {("Volume", "local"), ("Network", "bridge"),
                       ("Network", "host"), ("Network", "overlay"),
                       ("Log", "json-file")}

    def set_task(self, task) -> bool:
        self._volume_drivers: set[str] = set()
        self._network_drivers: set[str] = set()
        self._log_driver: str | None = None
        runtime = task.spec.runtime
        if runtime is not None:
            for m in runtime.mounts:
                # mounts carry "driver/source" convention; plain sources use
                # the default local driver
                if "/" in m.source:
                    self._volume_drivers.add(m.source.split("/", 1)[0])
        for net in task.networks or []:
            drv = getattr(net, "driver", None)
            if drv:
                self._network_drivers.add(drv)
        if task.spec.log_driver:
            self._log_driver = task.spec.log_driver.get("name")
        return bool(self._volume_drivers or self._network_drivers or self._log_driver)

    def check(self, node: NodeInfo) -> bool:
        desc = node.node.description
        plugins = set(desc.plugins) if desc else set()
        plugins |= self.DEFAULT_PLUGINS
        for drv in self._volume_drivers:
            if ("Volume", drv) not in plugins:
                return False
        for drv in self._network_drivers:
            if ("Network", drv) not in plugins:
                return False
        if self._log_driver and ("Log", self._log_driver) not in plugins:
            return False
        return True

    def explain(self, nodes: int) -> str:
        if nodes == 1:
            return "missing plugin on 1 node"
        return f"missing plugin on {nodes} nodes"


class ConstraintFilter:
    """reference: filter.go:219-251."""

    def set_task(self, task) -> bool:
        exprs = task.spec.placement.constraints
        if not exprs:
            return False
        try:
            self._constraints = constraint_mod.parse(exprs)
        except constraint_mod.InvalidConstraint:
            self._constraints = None  # unparseable → filter everything
        return True

    def check(self, node: NodeInfo) -> bool:
        if self._constraints is None:
            return False
        return constraint_mod.node_matches(self._constraints, node.node)

    def explain(self, nodes: int) -> str:
        if nodes == 1:
            return "scheduling constraints not satisfied on 1 node"
        return f"scheduling constraints not satisfied on {nodes} nodes"


class PlatformFilter:
    """reference: filter.go:254-320 (with x86_64→amd64, aarch64→arm64)."""

    def set_task(self, task) -> bool:
        self._platforms = task.spec.placement.platforms
        return bool(self._platforms)

    def check(self, node: NodeInfo) -> bool:
        desc = node.node.description
        if desc is None or desc.platform is None:
            return False
        node_os = desc.platform.os.lower()
        node_arch = normalize_arch(desc.platform.architecture)
        for p in self._platforms:
            want_os = p.os.lower()
            want_arch = normalize_arch(p.architecture) if p.architecture else ""
            # empty fields act as wildcards (reference behavior)
            if (not want_os or want_os == node_os) and (
                    not want_arch or want_arch == node_arch):
                return True
        return False

    def explain(self, nodes: int) -> str:
        if nodes == 1:
            return "unsupported platform on 1 node"
        return f"unsupported platform on {nodes} nodes"


class HostPortFilter:
    """reference: filter.go:323-361."""

    def set_task(self, task) -> bool:
        self._ports: list[tuple[str, int]] = []
        endpoint = getattr(task, "endpoint", None)
        spec_ports = []
        if endpoint is not None:
            spec_ports = endpoint.ports
        for p in spec_ports:
            if p.publish_mode == "host" and p.published_port != 0:
                self._ports.append((p.protocol, p.published_port))
        return bool(self._ports)

    def check(self, node: NodeInfo) -> bool:
        return not any(p in node.used_host_ports for p in self._ports)

    def explain(self, nodes: int) -> str:
        if nodes == 1:
            return "host-mode port already in use on 1 node"
        return f"host-mode port already in use on {nodes} nodes"


class MaxReplicasFilter:
    """reference: filter.go:364-386."""

    def set_task(self, task) -> bool:
        self._task = task
        return task.spec.placement.max_replicas > 0

    def check(self, node: NodeInfo) -> bool:
        return (node.active_tasks_count_by_service.get(self._task.service_id, 0)
                < self._task.spec.placement.max_replicas)

    def explain(self, nodes: int) -> str:
        if nodes == 1:
            return "max replicas per node limit exceed on 1 node"
        return f"max replicas per node limit exceed on {nodes} nodes"


class VolumesFilter:
    """CSI volume availability (filter.go:388-447). Full topology-aware
    matching lives in scheduler/volumes.py; when no volume set is wired in,
    tasks that mount CSI ("group/…" prefixed cluster) volumes pass trivially."""

    def __init__(self, volume_set=None):
        self._vs = volume_set

    def set_task(self, task) -> bool:
        self._task = task
        if self._vs is None:
            return False
        runtime = task.spec.runtime
        mounts = runtime.mounts if runtime else []
        return any(m.source for m in mounts)

    def check(self, node: NodeInfo) -> bool:
        return self._vs.check_volumes_on_node(node, self._task)

    def explain(self, nodes: int) -> str:
        if nodes == 1:
            return "cannot fulfill requested volumes on 1 node"
        return f"cannot fulfill requested volumes on {nodes} nodes"


DEFAULT_FILTERS = (
    ReadyFilter, ResourceFilter, PluginFilter, ConstraintFilter,
    PlatformFilter, HostPortFilter, MaxReplicasFilter,
)


class Pipeline:
    """reference: pipeline.go:9-103."""

    def __init__(self, volume_set=None):
        self._filters: list[Filter] = [f() for f in DEFAULT_FILTERS]
        self._filters.append(VolumesFilter(volume_set))
        self._enabled: list[Filter] = []
        self._failures: dict[Filter, int] = {}

    def set_task(self, task) -> None:
        self._enabled = [f for f in self._filters if f.set_task(task)]
        self._failures = {f: 0 for f in self._enabled}

    def process(self, node: NodeInfo) -> bool:
        for f in self._enabled:
            if not f.check(node):
                self._failures[f] += 1
                return False
        return True

    def explain(self) -> str:
        if not any(self._failures.values()):
            return ""
        parts = sorted(
            ((count, f) for f, count in self._failures.items() if count),
            key=lambda pair: (-pair[0], type(pair[1]).__name__),
        )
        return "; ".join(f.explain(count) for count, f in parts)
