"""Canonical spread-placement semantics (the parity oracle).

The reference's spread strategy (manager/scheduler/scheduler.go:694-921)
round-robins a task group over nodes ordered by the `nodeLess` comparator —
recent-failure penalty, then per-service active count, then total active
count (scheduler.go:708-735) — re-filtering each node after every assignment.
Go map iteration makes the reference nondeterministic across runs; per
SURVEY.md §7 we instead define a *canonical deterministic ordering* — ties
break by node index — and implement it twice:

  * here: greedy heap fill (the oracle, and the default small-tick path);
  * ops/placement.py: a closed-form water-fill kernel on TPU that provably
    emits identical placements (greedy with uniform (+1,+1) increments equals
    taking the globally smallest slots in sorted order).

A node's *capacity* within one group fill folds in the dynamic filters the
reference re-checks mid-fill (scheduler.go:910): resource depletion,
max-replicas, and host-port exclusivity.
"""
from __future__ import annotations

import heapq
from dataclasses import dataclass

PENALTY_BASE = 1 << 20  # lexicographic linearization of (penalty, svc_count)


@dataclass
class GroupFill:
    """One (service, spec_version) task group's placement problem against a
    fixed node table. All arrays are parallel over nodes."""

    n_tasks: int
    eligible: list[bool]     # static mask: ready/constraint/platform/plugin
    capacity: list[int]      # per-node cap: resources // need, max-replicas, ports
    penalty: list[bool]      # >=5 recent failures for this service version
    svc_count: list[int]     # active tasks of this service on the node
    total_count: list[int]   # total active tasks on the node


def greedy_fill(g: GroupFill) -> list[int]:
    """Assign g.n_tasks over nodes; returns per-node counts.

    Greedy: repeatedly give one task to the smallest node by
    (penalty*B + svc_count, total_count, node_idx); each assignment increments
    both svc_count and total_count and consumes one unit of capacity.
    """
    n = len(g.eligible)
    counts = [0] * n
    heap: list[tuple[int, int, int]] = []
    key = [0] * n
    tot = list(g.total_count)
    for i in range(n):
        if g.eligible[i] and g.capacity[i] > 0:
            key[i] = (PENALTY_BASE if g.penalty[i] else 0) + g.svc_count[i]
            heapq.heappush(heap, (key[i], tot[i], i))
    remaining = g.n_tasks
    while remaining > 0 and heap:
        k, t, i = heapq.heappop(heap)
        counts[i] += 1
        remaining -= 1
        key[i] += 1
        tot[i] += 1
        if counts[i] < g.capacity[i]:
            heapq.heappush(heap, (key[i], tot[i], i))
    return counts


def slot_order(g: GroupFill, counts: list[int]) -> list[int]:
    """Canonical assignment order of the filled slots: the sequence of node
    indices in the order greedy filled them — i.e. all slots sorted by
    (key_at_slot, total_at_slot, node_idx). Used to materialize task→node
    deterministically (tasks sorted by id zip with this order)."""
    slots: list[tuple[int, int, int]] = []
    for i, c in enumerate(counts):
        base_k = (PENALTY_BASE if g.penalty[i] else 0) + g.svc_count[i]
        for j in range(c):
            slots.append((base_k + j, g.total_count[i] + j, i))
    slots.sort()
    return [i for _, _, i in slots]


def waterfill_reference(g: GroupFill) -> list[int]:
    """Pure-Python closed-form water-fill — the same math as the TPU kernel,
    kept host-side for differential testing of the kernel itself.

    Level L = the primary-key value of the first *unfilled* slot layer.
    c_n(L) = min(cap_n, max(0, L - k_n)); pick the largest L with
    S(L) = Σ c_n(L) <= T, fill those, then distribute the remaining
    T - S(L) among boundary slots (primary == L) ordered by
    (secondary, node_idx).
    """
    n = len(g.eligible)
    cap = [g.capacity[i] if g.eligible[i] else 0 for i in range(n)]
    k = [(PENALTY_BASE if g.penalty[i] else 0) + g.svc_count[i] for i in range(n)]
    T = g.n_tasks
    total_cap = sum(cap)
    if total_cap == 0 or T == 0:
        return [0] * n
    T = min(T, total_cap)

    def filled(L: int) -> int:
        return sum(min(cap[i], max(0, L - k[i])) for i in range(n))

    lo, hi = 0, max(k) + T + 1  # filled(hi) >= T always
    # largest L with filled(L) <= T
    while lo < hi:
        mid = (lo + hi + 1) // 2
        if filled(mid) <= T:
            lo = mid
        else:
            hi = mid - 1
    L = lo
    counts = [min(cap[i], max(0, L - k[i])) for i in range(n)]
    rem = T - sum(counts)
    if rem > 0:
        boundary = [
            (g.total_count[i] + counts[i], i)
            for i in range(n)
            if cap[i] > counts[i] and k[i] <= L and counts[i] == L - k[i]
        ]
        boundary.sort()
        for _, i in boundary[:rem]:
            counts[i] += 1
    return counts
