"""Canonical spread-placement semantics (the parity oracle).

The reference's spread strategy (manager/scheduler/scheduler.go:694-921)
round-robins a task group over nodes ordered by the `nodeLess` comparator —
recent-failure penalty, then per-service active count, then total active
count (scheduler.go:708-735) — re-filtering each node after every assignment.
Go map iteration makes the reference nondeterministic across runs; per
SURVEY.md §7 we instead define a *canonical deterministic ordering* — ties
break by node index — and implement it twice:

  * here: greedy heap fill (the oracle, and the default small-tick path);
  * ops/placement.py: a closed-form water-fill kernel on TPU that provably
    emits identical placements (greedy with uniform (+1,+1) increments equals
    taking the globally smallest slots in sorted order).

A node's *capacity* within one group fill folds in the dynamic filters the
reference re-checks mid-fill (scheduler.go:910): resource depletion,
max-replicas, and host-port exclusivity.
"""
from __future__ import annotations

import heapq
from dataclasses import dataclass

PENALTY_BASE = 1 << 20  # lexicographic linearization of (penalty, svc_count)


@dataclass
class GroupFill:
    """One (service, spec_version) task group's placement problem against a
    fixed node table. All arrays are parallel over nodes."""

    n_tasks: int
    eligible: list[bool]     # static mask: ready/constraint/platform/plugin
    capacity: list[int]      # per-node cap: resources // need, max-replicas, ports
    penalty: list[bool]      # >=5 recent failures for this service version
    svc_count: list[int]     # active tasks of this service on the node
    total_count: list[int]   # total active tasks on the node


def greedy_fill(g: GroupFill) -> list[int]:
    """Assign g.n_tasks over nodes; returns per-node counts.

    Greedy: repeatedly give one task to the smallest node by
    (penalty*B + svc_count, total_count, node_idx); each assignment increments
    both svc_count and total_count and consumes one unit of capacity.
    """
    n = len(g.eligible)
    counts = [0] * n
    heap: list[tuple[int, int, int]] = []
    key = [0] * n
    tot = list(g.total_count)
    for i in range(n):
        if g.eligible[i] and g.capacity[i] > 0:
            key[i] = (PENALTY_BASE if g.penalty[i] else 0) + g.svc_count[i]
            heapq.heappush(heap, (key[i], tot[i], i))
    remaining = g.n_tasks
    while remaining > 0 and heap:
        k, t, i = heapq.heappop(heap)
        counts[i] += 1
        remaining -= 1
        key[i] += 1
        tot[i] += 1
        if counts[i] < g.capacity[i]:
            heapq.heappush(heap, (key[i], tot[i], i))
    return counts


def slot_order(g: GroupFill, counts: list[int]) -> list[int]:
    """Canonical assignment order of the filled slots: the sequence of node
    indices in the order greedy filled them — i.e. all slots sorted by
    (key_at_slot, total_at_slot, node_idx). Used to materialize task→node
    deterministically (tasks sorted by id zip with this order)."""
    slots: list[tuple[int, int, int]] = []
    for i, c in enumerate(counts):
        base_k = (PENALTY_BASE if g.penalty[i] else 0) + g.svc_count[i]
        for j in range(c):
            slots.append((base_k + j, g.total_count[i] + j, i))
    slots.sort()
    return [i for _, _, i in slots]


def tree_fill(g: GroupFill, level_ranks: list[list[int]]) -> list[int]:
    """Canonical spread-preference fill (the oracle for the hierarchical
    kernel).

    Re-derivation of the reference's preference tree walk
    (manager/scheduler/nodeset.go:50-124 builds a tree bucketing nodes by
    each preference's label value; scheduler.go:772-822 splits a task group
    so per-branch service totals equalize). The reference's split is
    Go-map-order dependent; our canonical semantics (documented, applied
    identically on CPU and TPU):

      at each level, branches are filled by the SAME water principle as
      nodes — pour the level's quota so per-branch totals
      (existing service tasks + newly assigned) equalize, capped by branch
      capacity, ties broken by branch rank — then recurse per branch; the
      leaf level is the flat canonical fill over nodes.

    `level_ranks[l][i]` is node i's branch id at level l; branch ids are
    contiguous ranks of the value-path PREFIX (so equal rank at level l
    implies equal rank at every level above). Branch totals count the
    service tasks of ALL of a branch's nodes — even scheduling-ineligible
    ones (nodeset.go:88-104).
    """
    if not level_ranks:
        return greedy_fill(g)
    n = len(g.eligible)
    branch_svc = g.svc_count

    def fill(level: int, node_idx: list[int], quota: int) -> list[tuple[int, int]]:
        """Returns [(node, count)] with sum(count) <= quota."""
        if level == len(level_ranks):
            sub = GroupFill(
                n_tasks=quota,
                eligible=[g.eligible[i] for i in node_idx],
                capacity=[g.capacity[i] for i in node_idx],
                penalty=[g.penalty[i] for i in node_idx],
                svc_count=[g.svc_count[i] for i in node_idx],
                total_count=[g.total_count[i] for i in node_idx],
            )
            counts = greedy_fill(sub)
            return [(node_idx[j], c) for j, c in enumerate(counts) if c]

        ranks = level_ranks[level]
        branches: dict[int, list[int]] = {}
        for i in node_idx:
            branches.setdefault(ranks[i], []).append(i)
        border = sorted(branches)
        # branch aggregates: existing totals over ALL branch nodes;
        # capacity over eligible nodes only
        k = {b: sum(branch_svc[i] for i in branches[b]) for b in border}
        cap = {b: sum(g.capacity[i] for i in branches[b]
                      if g.eligible[i] and g.capacity[i] > 0)
               for b in border}
        # pour `quota` over branches: greedy by (current total, rank)
        give = _pour(quota, [k[b] for b in border], [cap[b] for b in border])
        out: list[tuple[int, int]] = []
        for rank_pos, b in enumerate(border):
            q = give[rank_pos]
            if q > 0:
                out.extend(fill(level + 1, branches[b], q))
        return out

    pairs = fill(0, list(range(n)), g.n_tasks)
    counts = [0] * n
    for i, c in pairs:
        counts[i] += c
    return counts


def _pour(quota: int, totals: list[int], caps: list[int]) -> list[int]:
    """Equalize: repeatedly give one unit to the smallest (total, index)
    entry with remaining cap. Greedy form — the branch-level analogue of
    greedy_fill, provably equal to the closed-form water level."""
    m = len(totals)
    give = [0] * m
    heap = [(totals[j], j) for j in range(m) if caps[j] > 0]
    heapq.heapify(heap)
    left = quota
    while left > 0 and heap:
        t, j = heapq.heappop(heap)
        give[j] += 1
        left -= 1
        if give[j] < caps[j]:
            heapq.heappush(heap, (t + 1, j))
    return give


def pour_waterfill(quota: int, totals: list[int], caps: list[int]) -> list[int]:
    """Closed-form `_pour` (differential test target for the kernel's
    segmented level fill): counts = min(cap, max(0, L - k)) at the largest
    L with sum <= quota, remainder to boundary entries by index order."""
    m = len(totals)
    if m == 0:
        return []
    quota = min(quota, sum(caps))
    if quota <= 0:
        return [0] * m

    def filled(L):
        return sum(min(caps[j], max(0, L - totals[j])) for j in range(m))

    lo, hi = 0, max(totals) + quota + 1
    while lo < hi:
        mid = (lo + hi + 1) // 2
        if filled(mid) <= quota:
            lo = mid
        else:
            hi = mid - 1
    L = lo
    give = [min(caps[j], max(0, L - totals[j])) for j in range(m)]
    rem = quota - sum(give)
    for j in range(m):
        if rem <= 0:
            break
        if caps[j] > give[j] and totals[j] <= L and give[j] == L - totals[j]:
            give[j] += 1
            rem -= 1
    return give


def binpack_fill(g: GroupFill) -> list[int]:
    """Binpack strategy oracle: prefer the FULLEST feasible node.

    Canonical order (documented, applied identically on CPU and TPU):
    (penalty, -svc_count, -total_count, node_idx) — the spread comparator
    with the count legs inverted, penalty still dominant, node index the
    final tie-break. Each assignment increments svc/total, so an assigned
    node's key strictly IMPROVES (-svc decreases) — greedy therefore
    drains each node to capacity before moving on, i.e. sequential
    consumption in initial-key order (`binpack_reference` is the closed
    form the kernel mirrors). Spread preferences are ignored: binpack is
    a pure consolidation strategy (flat fill).
    """
    n = len(g.eligible)
    counts = [0] * n
    heap: list[tuple[int, int, int]] = []
    key = [0] * n
    tot = list(g.total_count)
    for i in range(n):
        if g.eligible[i] and g.capacity[i] > 0:
            key[i] = (PENALTY_BASE if g.penalty[i] else 0) - g.svc_count[i]
            heapq.heappush(heap, (key[i], -tot[i], i))
    remaining = g.n_tasks
    while remaining > 0 and heap:
        k, t, i = heapq.heappop(heap)
        counts[i] += 1
        remaining -= 1
        key[i] -= 1
        tot[i] += 1
        if counts[i] < g.capacity[i]:
            heapq.heappush(heap, (key[i], -tot[i], i))
    return counts


def binpack_reference(g: GroupFill) -> list[int]:
    """Closed-form binpack (the kernel's math, host-side): sort nodes by
    the INITIAL key (penalty, -svc_count, -total_count, node_idx) and
    consume capacities sequentially. Equal to `binpack_fill` because an
    assignment only improves the assigned node's key — the heap never
    switches nodes before capacity exhausts."""
    n = len(g.eligible)
    cap = [g.capacity[i] if g.eligible[i] and g.capacity[i] > 0 else 0
           for i in range(n)]
    order = sorted(range(n), key=lambda i: (
        1 if g.penalty[i] else 0, -g.svc_count[i], -g.total_count[i], i))
    left = min(g.n_tasks, sum(cap))
    counts = [0] * n
    for i in order:
        if left <= 0:
            break
        take = min(cap[i], left)
        counts[i] = take
        left -= take
    return counts


def binpack_slot_order(g: GroupFill, counts: list[int]) -> list[int]:
    """Canonical assignment order of a binpack fill: nodes in initial-key
    order, each node's slots consecutive (sequential consumption)."""
    order = sorted(range(len(g.eligible)), key=lambda i: (
        1 if g.penalty[i] else 0, -g.svc_count[i], -g.total_count[i], i))
    out: list[int] = []
    for i in order:
        out.extend([i] * counts[i])
    return out


def topology_fill(g: GroupFill, topo_rank: list[int],
                  level_ranks: list[list[int]] | None = None) -> list[int]:
    """Topology-aware spread oracle: balance the group's replicas across a
    node-label topology axis (zone/rack), then spread within each branch.

    This is NOT a new fill algorithm — it is `tree_fill` with the topology
    axis as the OUTERMOST level, exactly how the encoder implements the
    strategy (the configured (kind, label) pair is prepended to every
    group's spread-descriptor list, so the existing prefix-rank tree and
    the `_tree_water_fill` kernel handle it unchanged). `topo_rank[i]` is
    node i's branch id on the topology axis; `level_ranks` are the group's
    own spread levels, already NESTED under the topology level (prefix
    ranks — the encoder guarantees one parent per child segment)."""
    return tree_fill(g, [topo_rank] + list(level_ranks or []))


def waterfill_reference(g: GroupFill) -> list[int]:
    """Pure-Python closed-form water-fill — the same math as the TPU kernel,
    kept host-side for differential testing of the kernel itself.

    Level L = the primary-key value of the first *unfilled* slot layer.
    c_n(L) = min(cap_n, max(0, L - k_n)); pick the largest L with
    S(L) = Σ c_n(L) <= T, fill those, then distribute the remaining
    T - S(L) among boundary slots (primary == L) ordered by
    (secondary, node_idx).
    """
    n = len(g.eligible)
    cap = [g.capacity[i] if g.eligible[i] else 0 for i in range(n)]
    k = [(PENALTY_BASE if g.penalty[i] else 0) + g.svc_count[i] for i in range(n)]
    T = g.n_tasks
    total_cap = sum(cap)
    if total_cap == 0 or T == 0:
        return [0] * n
    T = min(T, total_cap)

    def filled(L: int) -> int:
        return sum(min(cap[i], max(0, L - k[i])) for i in range(n))

    lo, hi = 0, max(k) + T + 1  # filled(hi) >= T always
    # largest L with filled(L) <= T
    while lo < hi:
        mid = (lo + hi + 1) // 2
        if filled(mid) <= T:
            lo = mid
        else:
            hi = mid - 1
    L = lo
    counts = [min(cap[i], max(0, L - k[i])) for i in range(n)]
    rem = T - sum(counts)
    if rem > 0:
        boundary = [
            (g.total_count[i] + counts[i], i)
            for i in range(n)
            if cap[i] > counts[i] and k[i] <= L and counts[i] == L - k[i]
        ]
        boundary.sort()
        for _, i in boundary[:rem]:
            counts[i] += 1
    return counts
