"""Placement-constraint engine.

Behavioral re-derivation of the reference's constraint package
(manager/constraint/constraint.go). Grammar: `key == value` / `key != value`
with `==`/`!=` the only operators; keys are matched case-insensitively
(the reference key regex carries `(?i)`, constraint.go:23); values compare
case-insensitively; label *names* are case-sensitive. A missing attribute
behaves as the empty string, so `== x` fails and `!= x` succeeds.

The same predicate is what `swarmkit_tpu.scheduler.encode` compiles to
(key_id, op, value_id) triples for the batched TPU mask kernel.
"""
from __future__ import annotations

import ipaddress
import re
from dataclasses import dataclass

EQ = 0
NOTEQ = 1

NODE_LABEL_PREFIX = "node.labels."
ENGINE_LABEL_PREFIX = "engine.labels."

# reference: constraint.go:22-30 — alphanumeric key with (?i), glob-capable
# value grammar (globbing is permitted by the grammar but not implemented by
# the evaluator, matching constraint.go:70's behavior).
_KEY_RE = re.compile(r"^(?i:[a-z_][a-z0-9\-_.]+)$")
_VALUE_RE = re.compile(r"^(?i:[a-z0-9:\-_\s\.\*\(\)\?\+\[\]\\\^\$\|\/]+)$")


class InvalidConstraint(ValueError):
    pass


@dataclass(frozen=True)
class Constraint:
    key: str
    operator: int  # EQ | NOTEQ
    exp: str

    def match(self, *candidates: str) -> bool:
        """Case-insensitive full-string match (constraint.go:84-104)."""
        hit = any(self.exp.lower() == c.lower() for c in candidates)
        return hit if self.operator == EQ else not hit


def parse(expressions: list[str]) -> list[Constraint]:
    """reference: constraint.go:40-81."""
    out: list[Constraint] = []
    for expr in expressions:
        if "==" in expr:
            op = EQ
            lhs, _, rhs = expr.partition("==")
        elif "!=" in expr:
            op = NOTEQ
            lhs, _, rhs = expr.partition("!=")
        else:
            raise InvalidConstraint(f"invalid expression: {expr!r}")
        key, value = lhs.strip(), rhs.strip()
        if not key or not _KEY_RE.match(key):
            raise InvalidConstraint(f"invalid key {key!r} in {expr!r}")
        value = value.strip("\"'")
        if not value or not _VALUE_RE.match(value):
            raise InvalidConstraint(f"invalid value {value!r} in {expr!r}")
        out.append(Constraint(key=key, operator=op, exp=value))
    return out


def node_attribute(node, key: str) -> tuple[str | None, list[str]]:
    """Resolve a constraint key against a node. Returns (kind, candidates)
    where kind is None for predefined keys, 'ip' for the IP special case.
    Unknown keys return ('unknown', []) which always fails to match."""
    lk = key.lower()
    desc = getattr(node, "description", None)
    if lk == "node.id":
        return None, [node.id]
    if lk == "node.hostname":
        return None, [desc.hostname if desc else ""]
    if lk == "node.ip":
        return "ip", [node.status.addr or ""]
    if lk == "node.role":
        from ..api.types import NodeRole
        return None, [NodeRole(node.role).name]
    if lk == "node.platform.os":
        return None, [(desc.platform.os if desc and desc.platform else "")]
    if lk == "node.platform.arch":
        return None, [(desc.platform.architecture if desc and desc.platform else "")]
    if lk.startswith(NODE_LABEL_PREFIX) and len(key) > len(NODE_LABEL_PREFIX):
        label = key[len(NODE_LABEL_PREFIX):]  # label name case-sensitive
        labels = node.spec.annotations.labels or {}
        return None, [labels.get(label, "")]
    if lk.startswith(ENGINE_LABEL_PREFIX) and len(key) > len(ENGINE_LABEL_PREFIX):
        label = key[len(ENGINE_LABEL_PREFIX):]
        labels = (desc.engine_labels if desc else None) or {}
        return None, [labels.get(label, "")]
    return "unknown", []


def _match_ip(constraint: Constraint, addr: str) -> bool:
    """IP / CIDR matching (constraint.go:127-146)."""
    try:
        node_ip = ipaddress.ip_address(addr)
    except ValueError:
        node_ip = None
    try:
        ip = ipaddress.ip_address(constraint.exp)
        eq = node_ip is not None and ip == node_ip
        return eq if constraint.operator == EQ else not eq
    except ValueError:
        pass
    try:
        # strict=False masks host bits, matching net.ParseCIDR: '10.0.0.5/24'
        # is the 10.0.0.0/24 subnet
        subnet = ipaddress.ip_network(constraint.exp, strict=False)
        within = node_ip is not None and node_ip in subnet
        return within if constraint.operator == EQ else not within
    except ValueError:
        return False  # malformed address/network rejects the node


def node_matches(constraints: list[Constraint], node) -> bool:
    """reference: constraint.go:107-207."""
    for c in constraints:
        kind, candidates = node_attribute(node, c.key)
        if kind == "unknown":
            return False
        if kind == "ip":
            if not _match_ip(c, candidates[0]):
                return False
            continue
        if not c.match(*candidates):
            return False
    return True
