"""Per-node scheduling bookkeeping.

Behavioral re-derivation of the reference's NodeInfo
(manager/scheduler/nodeinfo.go): running task maps, active counts used by the
spread comparator, available-resource accounting, host-port usage, and the
recent-failure ring that downweights flaky nodes
(manager/scheduler/scheduler.go:16-24 — ≥5 failures within 5 minutes).

These same quantities are exactly the per-node columns of the dense arrays the
TPU backend consumes (`swarmkit_tpu.scheduler.encode`).
"""
from __future__ import annotations

import itertools
import time
from collections import Counter
from dataclasses import dataclass, field

from ..api.objects import Node, Task
from ..api.specs import Resources
from ..api.types import TaskState

MAX_FAILURES = 5
FAILURE_WINDOW = 5 * 60.0  # seconds

_NODEINFO_SEQ = itertools.count()  # creation stamps for encoder fingerprints


def task_reservations(spec) -> Resources:
    return spec.resources.reservations


@dataclass
class NodeInfo:
    node: Node
    tasks: dict[str, Task] = field(default_factory=dict)
    active_tasks_count: int = 0
    # Counter, not plain dict: the wave-bulk commit (batch.apply_placements)
    # folds a node's per-service placements with one C-speed
    # Counter.update over the segment's service names
    active_tasks_count_by_service: Counter = field(default_factory=Counter)
    available_resources: Resources = field(default_factory=Resources)
    used_host_ports: set[tuple[str, int]] = field(default_factory=set)
    # task id -> {kind: (named ids granted, discrete count granted)}
    generic_assignments: dict[str, dict[str, tuple[frozenset, int]]] = field(
        default_factory=dict)
    # (service_id, spec_version_index) -> failure timestamps
    recent_failures: dict[tuple[str, int], list[float]] = field(default_factory=dict)
    last_cleanup: float = field(default_factory=time.monotonic)
    # fingerprint for the incremental encoder: (created_seq, mutations)
    # changes whenever this info's scheduling-relevant state may have changed
    created_seq: int = field(default_factory=lambda: next(_NODEINFO_SEQ))
    mutations: int = 0

    @property
    def fingerprint(self) -> tuple[int, int]:
        return (self.created_seq, self.mutations)

    @classmethod
    def new(cls, node: Node, tasks: dict[str, Task], available: Resources) -> "NodeInfo":
        info = cls(node=node, available_resources=available.copy())
        for t in tasks.values():
            info.add_task(t)
        return info

    # ------------------------------------------------------------- tasks
    def remove_task(self, t: Task) -> bool:
        old = self.tasks.pop(t.id, None)
        if old is None:
            return False
        self.mutations += 1
        if old.desired_state <= TaskState.COMPLETE:
            self.active_tasks_count -= 1
            self._bump_service(old.service_id, -1)
        for port in self._host_ports(old):
            self.used_host_ports.discard(port)
        res = task_reservations(old.spec)
        self.available_resources.memory_bytes += res.memory_bytes
        self.available_resources.nano_cpus += res.nano_cpus
        for kind, (named, count) in self.generic_assignments.pop(t.id, {}).items():
            if named:
                self.available_resources.named_generic.setdefault(
                    kind, set()).update(named)
            if count:
                self.available_resources.generic[kind] = (
                    self.available_resources.generic.get(kind, 0) + count)
        return True

    def add_task(self, t: Task) -> bool:
        old = self.tasks.get(t.id)
        if old is not None:
            # Only the active-count flip matters on re-add (nodeinfo.go:112-126).
            if (t.desired_state <= TaskState.COMPLETE
                    < old.desired_state):
                self.tasks[t.id] = t
                self.active_tasks_count += 1
                self._bump_service(t.service_id, +1)
                self.mutations += 1
                return True
            if (old.desired_state <= TaskState.COMPLETE
                    < t.desired_state):
                self.tasks[t.id] = t
                self.active_tasks_count -= 1
                self._bump_service(t.service_id, -1)
                self.mutations += 1
                return True
            return False

        self.mutations += 1
        self.tasks[t.id] = t
        res = task_reservations(t.spec)
        self.available_resources.memory_bytes -= res.memory_bytes
        self.available_resources.nano_cpus -= res.nano_cpus
        assigned = self._claim_generic(res)
        if assigned:
            # empty claims are not stored: remove_task/assigned_generic
            # default to {}, and the wave-bulk path (batch.apply_wave)
            # must land bit-identical state without per-task dict churn
            self.generic_assignments[t.id] = assigned
        for port in self._host_ports(t):
            self.used_host_ports.add(port)
        if t.desired_state <= TaskState.COMPLETE:
            self.active_tasks_count += 1
            self._bump_service(t.service_id, +1)
        return True

    def assigned_generic(self, task_id: str) -> dict[str, tuple[frozenset, int]]:
        """What a placed task was granted: kind -> (named ids, discrete count).
        Never written onto the (store-owned) Task object here — the commit
        path copies it onto the task it writes."""
        return self.generic_assignments.get(task_id, {})

    def _claim_generic(self, res: Resources) -> dict[str, tuple[frozenset, int]]:
        assigned: dict[str, tuple[frozenset, int]] = {}
        for kind, qty in res.generic.items():
            named_pool = self.available_resources.named_generic.get(kind)
            taken: set[str] = set()
            if named_pool:
                # deterministic: grant lowest ids first
                for nid in sorted(named_pool)[:qty]:
                    named_pool.discard(nid)
                    taken.add(nid)
            rest = qty - len(taken)
            if rest > 0:
                self.available_resources.generic[kind] = (
                    self.available_resources.generic.get(kind, 0) - rest)
            if taken or rest:
                assigned[kind] = (frozenset(taken), rest)
        return assigned

    def _bump_service(self, service_id: str, delta: int) -> None:
        self.active_tasks_count_by_service[service_id] = (
            self.active_tasks_count_by_service.get(service_id, 0) + delta)

    @staticmethod
    def _host_ports(t: Task) -> list[tuple[str, int]]:
        endpoint = t.endpoint
        if endpoint is None:
            return []
        return [
            (p.protocol, p.published_port)
            for p in endpoint.ports
            if p.publish_mode == "host" and p.published_port != 0
        ]

    # ---------------------------------------------------------- failures
    def task_failed(self, service_key: tuple[str, int], now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        self.mutations += 1
        self._maybe_cleanup(now)
        window = self.recent_failures.setdefault(service_key, [])
        if len(window) >= MAX_FAILURES:
            # hard cap: prune expired entries, then drop the oldest so the
            # ring never exceeds MAX_FAILURES (reference nodeinfo.go:163-221)
            window[:] = [ts for ts in window if now - ts <= FAILURE_WINDOW]
            if len(window) >= MAX_FAILURES:
                del window[:len(window) - MAX_FAILURES + 1]
        window.append(now)

    def count_recent_failures(self, service_key: tuple[str, int],
                              now: float | None = None) -> int:
        now = time.monotonic() if now is None else now
        window = self.recent_failures.get(service_key, [])
        return sum(1 for ts in window if now - ts <= FAILURE_WINDOW)

    def penalized(self, service_key: tuple[str, int], now: float | None = None) -> bool:
        """True when the spread comparator downweights this node
        (scheduler.go:708-735: ≥ MAX_FAILURES recent failures)."""
        return self.count_recent_failures(service_key, now) >= MAX_FAILURES

    def _maybe_cleanup(self, now: float) -> None:
        if now - self.last_cleanup < FAILURE_WINDOW:
            return
        for key in list(self.recent_failures):
            kept = [ts for ts in self.recent_failures[key] if now - ts <= FAILURE_WINDOW]
            if kept:
                self.recent_failures[key] = kept
            else:
                del self.recent_failures[key]
        self.last_cleanup = now
