"""The scheduler control loop.

Behavioral re-derivation of manager/scheduler/scheduler.go: an event loop over
store watches that moves PENDING tasks to ASSIGNED. Differences from the
reference are architectural, per SURVEY.md §7:

  * ticks are *batched*: all dirty groups are encoded into dense arrays and
    placed by one backend call — the greedy CPU engine for small ticks, the
    JAX water-fill kernel above `JAX_THRESHOLD` task×node products
    (backend="auto"), instead of per-task Go heap walks;
  * placement is canonically deterministic (spread.py) rather than
    Go-map-iteration dependent.

Matching reference behaviors: 50 ms commit debounce with 1 s cap
(scheduler.go:149-155), preassigned (global-service) tasks validated against
the filter pipeline without spread scoring (:398-426), in-transaction
re-validation of node state when committing decisions (:533-604), failed
decisions returned to the unassigned pool, and pipeline explanations written
to task status on failure (:923-968).
"""
from __future__ import annotations

import logging
import threading
import time
from collections import defaultdict, deque

import numpy as np

from ..api.objects import (
    EventCommit,
    EventCreate,
    EventDelete,
    EventUpdate,
    Node,
    Task,
)
from ..api.types import NodeStatusState, TaskState
from ..store import by
from ..store.memory import (
    ASSIGN_NODE_NOT_READY,
    ASSIGN_OK,
    MAX_CHANGES_PER_TRANSACTION,
    MemoryStore,
)
from ..store.watch import ChannelClosed
from ..utils import failpoints, lifecycle, trace
from .batch import apply_placements, cpu_schedule_encoded, materialize_orders
from .encode import IncrementalEncoder, TaskGroup
from .filters import Pipeline
from .nodeinfo import NodeInfo

log = logging.getLogger("swarmkit_tpu.scheduler")

COMMIT_DEBOUNCE = 0.05   # reference: 50ms
MAX_LATENCY = 1.0        # reference: 1s
# task×node products above which the TPU kernel wins. Two regimes:
# blocking ticks pay the full counts round-trip (~0.1s fixed through the
# dev tunnel), so the bar is high; pipelined ticks hide the pull under
# the commit/debounce window and their bar is the HOST-side floor only —
# encode + dispatch, measured 1.5-3 ms/tick after the round-4 group-table
# device cache (was ~6 ms), crossing CPU fill at ~100-250k products on
# the dev link and far lower on PCIe (BASELINE.md operator guidance).
JAX_THRESHOLD = 200_000
PIPELINED_JAX_THRESHOLD = 100_000
# raft-backed batched write-back: sub-transactions (≤ MAX_CHANGES each)
# pipelined through propose_async share the group-commit plane's WAL
# fsync + replication flush (store.batch pipeline_depth semantics)
WRITEBACK_PIPELINE_DEPTH = 16
# cold-start policy (backend="auto"): with NO device-resident state yet,
# a jax tick pays a full upload plus a BLOCKING counts round trip
# (~0.1 s fixed through a tunneled link) while the CPU fill at small
# node counts costs less than that RTT — the fill is node-bound, so N
# is the predictor. First wave goes to the CPU oracle below this node
# count; the device state warms on the next wave's dispatch instead.
COLD_CPU_NODES = 8_192


class Scheduler:
    def __init__(self, store: MemoryStore, backend: str = "auto",
                 jax_threshold: int | None = None, pipeline: bool = False,
                 mesh=None, async_commit: bool = False,
                 columnar_writeback: bool = True,
                 strategy: str = "spread", topology: str | None = None):
        """backend: "auto" picks per tick by task×node product against
        `jax_threshold` (default JAX_THRESHOLD); "cpu"/"jax" pin the path;
        "mesh" pins the jax path AND shards the device-resident node state
        over every visible device's `nodes` mesh axis (parallel/mesh.py
        layout — the production multi-chip mode). `mesh` narrows it: an
        int takes the first n devices, a jax.sharding.Mesh is used as-is.
        The right threshold is deployment-specific — a PCIe-attached or
        on-host accelerator amortizes ~100× sooner than the dev tunnel
        (BASELINE.md, operator guidance) — so swarmd exposes both knobs
        (--scheduler-backend / --jax-threshold, SURVEY §7).

        pipeline=True enables sustained-load tick pipelining on the jax
        path (ops/pipeline.py reorder): a tick dispatches its fill and
        returns; the NEXT tick pulls the counts — which rode the link in
        the background through the debounce window — commits them, and
        dispatches again, with the commit overlapping the new transfer.
        Placement latency gains one debounce period; steady throughput
        stops paying the blocking device pull. Commit conflicts (tasks
        raced/deleted, nodes gone) abandon the optimistic fold: the
        resident carry invalidates and fingerprint deltas re-encode the
        touched rows — the same self-healing the serial path uses.

        async_commit=True (pipelined jax path only) moves the commit's
        heavy half — slot materialization, the add_task walk, the store
        transaction, the fingerprint restamp — onto one background
        CommitWorker (ops/commit.py), overlapping it with the next
        wave's device dispatch and D2H pull. Every reader of scheduler
        host state (the event handler, the serial tick path, stop)
        takes a worker barrier first; a worker exception re-raises into
        the next tick, whose existing failure handler owns the heal.

        strategy selects the scoring engine for EVERY group (ISSUE 19):
        "spread" (default water-fill), "binpack" (fullest feasible node
        first, flat — spread preferences ignored), or "topology"
        (spread with `topology` — a node.labels.*/engine.labels.*
        descriptor — prepended as the outermost balance axis of every
        group). Both new strategies keep the kernel↔CPU-oracle
        bit-parity bar (scheduler/spread.py binpack_reference /
        topology_fill are the oracles)."""
        self.store = store
        self.backend = backend
        self.mesh = mesh
        # wave write-back through the columnar store plane (ISSUE 11):
        # one store.assign_wave per wave — vectorized in-tx validation
        # against the column mirror, shallow patches instead of tree
        # copies. Auto-off when the store runs without the mirror
        # (SWARMKIT_TPU_NO_COLUMNAR) — the object path is the fallback.
        self.columnar_writeback = bool(
            columnar_writeback and getattr(store, "columnar", None)
            is not None)
        self.jax_threshold = (
            (PIPELINED_JAX_THRESHOLD if pipeline else JAX_THRESHOLD)
            if jax_threshold is None else jax_threshold)
        self.pipeline = pipeline
        if async_commit and pipeline:
            from ..ops.commit import CommitWorker

            self._commit_worker = CommitWorker(name="sched-commit")
        else:
            if async_commit:
                # the commit plane only exists on the pipelined path —
                # dropping the flag silently would let an operator
                # believe async commit engaged when it never could
                log.warning("scheduler: async_commit requires "
                            "pipeline=True (--scheduler-pipeline); "
                            "running synchronous commits")
            self._commit_worker = None
        # set by the worker when an async commit came back unclean:
        # (problem, counts) awaiting the main-thread heal at the next
        # barrier (force_numeric_reencode + resident invalidate +
        # discard of any dispatch primed on the lying fold)
        self._worker_unclean = None
        # conflicted decisions in the LAST commit (in-tx re-validation
        # rejected a placement: node no longer READY / volume choose
        # failed). Conflicts rely on "node/task events retrigger the
        # tick" — but a wave committed BEHIND the async plane may
        # conflict on an event the run loop consumed while the wave was
        # in flight, so the completing tick must retry the pool itself
        # (see _tick_pipelined's gate bypass)
        self._last_commit_conflicts = 0
        # task-id sets of waves whose heavy commit may still ride the
        # plane (appended at submit, removed by the job's tail). On the
        # overlap path the next wave's prime excludes them: their
        # unassigned-pool pops happen on the worker thread, so without
        # the exclusion a still-uncommitted task could be re-grouped
        # into a new wave (double placement). Cleared at every barrier.
        self._pending_commit_ids: deque = deque()
        # observability: completed waves whose heavy commit was submitted
        # BEFORE the next prime (the encode/commit overlap path)
        self.overlapped_commits = 0
        # (problem, PendingCounts, frozenset of in-flight task ids)
        self._inflight = None
        self.node_infos: dict[str, NodeInfo] = {}
        self.unassigned: dict[str, Task] = {}
        self.preassigned: dict[str, Task] = {}
        self.pending_spec_version: dict[str, int] = {}
        from ..csi.volumes import VolumeSet
        self.volume_set = VolumeSet()
        # persistent dictionary encoder: node rows and vocabs survive across
        # ticks; only fingerprint-dirty nodes re-encode (verdict #6).
        # tracked=True (round 6): the scheduler feeds the dirty set
        # explicitly (every NodeInfo mutation site below marks), so a
        # steady tick's encode skips the O(N) fingerprint scan entirely
        # and nodes_clean degrades to a flag check — the zero-scan fast
        # path AND the encode/commit overlap's gate.
        self.encoder = IncrementalEncoder(tracked=True, strategy=strategy,
                                          topology=topology)
        # device-resident node tables (ops.resident): created on first jax
        # tick; deltas ride the encoder's dirty-row bookkeeping
        self._resident = None
        # cold-start policy bookkeeping: True after the one CPU wave a
        # cold period gets at small N; reset whenever a jax tick runs
        self._cold_cpu_done = False
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.ticks = 0

    # ------------------------------------------------------------- lifecycle
    def start(self):
        self._thread = threading.Thread(target=self.run, daemon=True,
                                        name="scheduler")
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        if self._commit_worker is not None:
            self._commit_worker.close()

    # ------------------------------------------------------ async commit plane
    def _drain_commit_plane(self, swallow: bool = False):
        """Barrier on the async heavy commit before any read/mutation of
        scheduler host state (node_infos, encoder fingerprints, the
        unassigned pool, volume_set), then run the pending unclean heal.
        swallow=True (event-handler path): a worker exception must not
        crash the run loop here — the worker stays poisoned and the next
        tick's barrier re-raises it into the guarded tick path."""
        w = self._commit_worker
        if w is None:
            return
        try:
            w.barrier()
        except Exception:
            if not swallow:
                raise
        else:
            # every submitted heavy retired cleanly: nothing can still
            # pop the unassigned pool, so the prime-time exclusion sets
            # are stale (poisoned-and-dropped jobs never ran their
            # removal tail — without this clear their tasks would stay
            # excluded forever)
            self._pending_commit_ids.clear()
        if self._worker_unclean is not None:
            self._heal_unclean()

    def _heal_unclean(self):
        """Main-thread half of the async unclean-commit heal (same
        semantics as the sync path's inline heal): poison the placed-on
        rows so the next encode re-derives them from the NodeInfo
        objects, resync the device, and discard any dispatch primed on
        the bad fold."""
        problem, counts = self._worker_unclean
        self._worker_unclean = None
        self.encoder.force_numeric_reencode(
            np.flatnonzero(counts.sum(axis=0)))
        if self._resident is not None:
            self._resident.invalidate()
        if self._inflight is not None:
            _p2, h2, _ids2 = self._inflight
            self._inflight = None
            try:
                h2.get()
            except Exception:
                # the dispatch is being DISCARDED and the resident carry
                # was just invalidated — a device/tunnel error pulling a
                # wave we won't use must not escape (this heal also runs
                # on the event-drain path, which has no retry handler)
                log.warning("discarding in-flight wave: counts pull "
                            "failed", exc_info=True)

    def _submit_heavy(self, problem, counts, ids: frozenset):
        """Enqueue one wave's heavy commit on the plane, bracketed by the
        prime-time exclusion bookkeeping: `ids` stays in
        `_pending_commit_ids` until the job's tail runs (worker thread;
        deque append/remove are GIL-atomic), so an overlapped prime can
        never re-group a task whose pool pop is still in flight. Dropped
        (poisoned) jobs skip the tail; the barrier paths clear the
        leftovers. The job joins the submitting tick's trace
        (trace.wrap: identity when disarmed)."""
        self._pending_commit_ids.append(ids)

        def job():
            try:
                self._commit_heavy(problem, counts)
            finally:
                try:
                    self._pending_commit_ids.remove(ids)
                except ValueError:
                    pass    # a barrier path already cleared it

        self._commit_worker.submit(trace.wrap("tick.commit_heavy", job))

    def _commit_heavy(self, problem, counts):
        """The commit's heavy half, run on the CommitWorker: slot
        materialization, store write-back with in-tx re-validation, the
        wave-bulk add_task walk, and the fingerprint restamp. An unclean
        outcome is recorded for the next barrier's main-thread heal.

        Failpoints bracket every stage boundary (`commit.materialize`,
        `commit.writeback` before the store transaction + walk — the
        walk itself has `commit.walk` in batch.apply_placements — and
        `commit.restamp`): a crash at ANY of them must poison the plane
        and heal at the next barrier, not just the boundaries production
        incidents happen to hit."""
        try:
            failpoints.fp("commit.materialize")
            with trace.span("tick.commit.materialize"):
                orders = materialize_orders(problem, counts)
            failpoints.fp("commit.writeback")
            with trace.span("tick.commit.writeback"):
                clean = self._apply_decisions(problem, orders, counts,
                                              deferred_fold=True)
        except BaseException:
            # a CRASH in the heavy half is an unclean commit too: the
            # optimistic fold already ran on the tick thread, but the
            # add_task walk (the thing that bumps mutation counters) may
            # not have — without recording the wave, the barrier heal
            # would invalidate the device yet leave the encoder's folded
            # rows as phantom reservations no fingerprint ever clears
            # (found by the seeded chaos harness, CHAOS_SEED=0)
            self._worker_unclean = (problem, counts)
            raise
        if clean:
            failpoints.fp("commit.restamp")
            self.encoder.restamp_counts(problem, counts)
        else:
            self._worker_unclean = (problem, counts)

    # ------------------------------------------------------------------ init
    def _setup(self):
        """Snapshot + subscribe (reference setupTasksList, scheduler.go:68-125)."""

        def snap(tx):
            return tx.find_tasks(), tx.find_nodes(), tx.find_volumes()

        # Unbounded subscription: the scheduler is a trusted in-process
        # consumer and must never be shed as a slow subscriber — a closed
        # channel would silently stop all scheduling.
        (tasks, nodes, volumes), ch = self.store.view_and_watch(snap, limit=None)
        for v in volumes:
            self.volume_set.add_or_update_volume(v)
        tasks_by_node: dict[str, dict[str, Task]] = defaultdict(dict)
        for t in tasks:
            if t.volumes and t.desired_state <= TaskState.RUNNING:
                self.volume_set.reserve_task(t)
            if t.status.state < TaskState.PENDING or t.status.state > TaskState.RUNNING:
                continue
            # desired_state == COMPLETE covers job-mode tasks; anything past
            # that (SHUTDOWN/REMOVE/...) is being torn down and must not be
            # scheduled (reference scheduler.go:96-99)
            if t.desired_state > TaskState.COMPLETE:
                continue
            if t.status.state == TaskState.PENDING and not t.node_id:
                self.unassigned[t.id] = t
            elif t.status.state == TaskState.PENDING and t.node_id:
                self.preassigned[t.id] = t
            if t.node_id and t.status.state >= TaskState.ASSIGNED:
                tasks_by_node[t.node_id][t.id] = t
        for n in nodes:
            self._add_or_update_node(n, tasks_by_node.get(n.id, {}))
        return ch

    # ----------------------------------------------------------------- nodes
    def _add_or_update_node(self, node: Node, tasks: dict[str, Task] | None = None):
        existing = self.node_infos.get(node.id)
        if tasks is None:
            tasks = existing.tasks if existing else {}
        desc = node.description
        total = desc.resources.copy() if desc else None
        from ..api.specs import Resources
        avail = total if total is not None else Resources()
        info = NodeInfo.new(node, dict(tasks), avail)
        if existing:
            info.recent_failures = existing.recent_failures
        self.node_infos[node.id] = info
        # tracked-encoder dirty feed: a replaced object re-encodes its
        # row's string columns; a NEW node changes the row set
        if existing is not None:
            self.encoder.mark_replaced(info)
        else:
            self.encoder.mark_node_set_changed()

    def _remove_node(self, node_id: str):
        if self.node_infos.pop(node_id, None) is not None:
            self.encoder.mark_node_set_changed()

    # ---------------------------------------------------------------- events
    def _handle(self, ev) -> bool:
        """Returns True when the event makes a tick necessary."""
        # event handling mutates node_infos / volume_set / the pools —
        # the async heavy commit must be fully retired first
        self._drain_commit_plane(swallow=True)
        if isinstance(ev, (EventCreate, EventUpdate)) and isinstance(ev.obj, Task):
            t = ev.obj
            if (t.status.state == TaskState.PENDING
                    and t.desired_state <= TaskState.COMPLETE):
                if t.node_id:
                    self.preassigned[t.id] = t
                else:
                    self.unassigned[t.id] = t
                return True
            # track running tasks on nodes for accurate counts
            if t.node_id and t.node_id in self.node_infos:
                info = self.node_infos[t.node_id]
                if t.status.state > TaskState.RUNNING:
                    # only an *observed* terminal state releases resources;
                    # a desired-state change alone still has the container
                    # running (reference scheduler.go:294 deletes on observed
                    # state, desired crossings only flip active counts via
                    # add_task, nodeinfo.go:111-119)
                    if info.remove_task(t):
                        self.encoder.mark_numeric(info)
                        if t.volumes:
                            self.volume_set.release_task(t)
                        if t.status.state == TaskState.FAILED:
                            key = (t.service_id,
                                   t.spec_version.index if t.spec_version else 0)
                            info.task_failed(key)
                        return True
                else:
                    if info.add_task(t):
                        self.encoder.mark_numeric(info)
            if (t.status.state > TaskState.PENDING
                    or t.desired_state > TaskState.COMPLETE):
                self.unassigned.pop(t.id, None)
                self.preassigned.pop(t.id, None)
            return False
        if isinstance(ev, EventDelete) and isinstance(ev.obj, Task):
            t = ev.obj
            self.unassigned.pop(t.id, None)
            self.preassigned.pop(t.id, None)
            if t.volumes:
                self.volume_set.release_task(t)
            if t.node_id and t.node_id in self.node_infos:
                info = self.node_infos[t.node_id]
                if info.remove_task(t):
                    self.encoder.mark_numeric(info)
            return True
        if isinstance(ev, (EventCreate, EventUpdate)) and isinstance(ev.obj, Node):
            self._add_or_update_node(ev.obj)
            return True
        if isinstance(ev, EventDelete) and isinstance(ev.obj, Node):
            self._remove_node(ev.obj.id)
            return True
        from ..api.objects import Volume as _Volume

        if isinstance(ev, (EventCreate, EventUpdate)) and isinstance(ev.obj, _Volume):
            self.volume_set.add_or_update_volume(ev.obj)
            return True
        if isinstance(ev, EventDelete) and isinstance(ev.obj, _Volume):
            self.volume_set.remove_volume(ev.obj.id)
            return True
        return False

    # ------------------------------------------------------------------- run
    def run(self):
        ch = self._setup()
        if self.unassigned or self.preassigned:
            self.tick()
        # a pipelined initial tick leaves a wave in flight: stay dirty so
        # the completing tick fires after the debounce
        dirty_since: float | None = (
            time.monotonic() if self._inflight is not None else None)
        try:
            while not self._stop.is_set():
                timeout = 0.2
                if dirty_since is not None:
                    timeout = COMMIT_DEBOUNCE
                try:
                    ev = ch.get(timeout=timeout)
                except TimeoutError:
                    ev = None
                except ChannelClosed:
                    return
                now = time.monotonic()
                if ev is not None:
                    needs = self._handle(ev)
                    if isinstance(ev, EventCommit):
                        needs = bool(self.unassigned or self.preassigned)
                    if needs and dirty_since is None:
                        dirty_since = now
                    # drain cheaply before ticking
                    continue_draining = True
                    while continue_draining:
                        try:
                            nxt = ch.try_get()
                        except ChannelClosed:
                            return
                        if nxt is None:
                            continue_draining = False
                        else:
                            if self._handle(nxt) and dirty_since is None:
                                dirty_since = now
                if dirty_since is not None and (
                        ev is None or now - dirty_since >= MAX_LATENCY):
                    # debounce elapsed with no new event, or max latency hit
                    try:
                        self.tick()
                        # an in-flight pipelined wave must complete even if
                        # no further event arrives: stay dirty so the next
                        # debounce fires the completing tick
                        dirty_since = (time.monotonic()
                                       if self._inflight is not None
                                       else None)
                    except Exception as exc:
                        self._inflight = None
                        if self._resident is not None:
                            # the device carry may have folded a tick the
                            # host never applied: resync from host state
                            self._resident.invalidate()
                        if self._commit_worker is not None:
                            # a worker exception propagated into this
                            # tick; the invalidate above plus the event-
                            # plane's ASSIGNED echoes heal the partial
                            # commit — un-poison the plane for the retry
                            worker_died = self._commit_worker.failed
                            # overlap can put TWO heavies on the plane: a
                            # crash in the older one makes the worker
                            # DROP the queued younger one un-run (its
                            # fold is then backed by nothing, and its
                            # finally tail never removed its ids). The
                            # recorded-unclean heal only covers the wave
                            # the worker crashed ON — any leftover
                            # exclusion entry at a died-worker heal means
                            # a dropped heavy, which needs the blanket
                            # poison (a crashed job removes its own ids
                            # in its finally).
                            dropped_heavy = (worker_died
                                             and bool(
                                                 self._pending_commit_ids))
                            self._commit_worker.reset()
                            # poisoned-and-dropped jobs never ran their
                            # exclusion-removal tail; the pool they
                            # guarded is being re-attempted wholesale
                            self._pending_commit_ids.clear()
                            if self._worker_unclean is not None:
                                self._heal_unclean()
                                if dropped_heavy:
                                    self.encoder.poison_all_numeric()
                            elif worker_died:
                                # the worker died before recording which
                                # wave it carried (crash pre-job): any
                                # row may hold an unbacked optimistic
                                # fold — poison them all (chaos-harness
                                # regression). Gated on an ACTUAL worker
                                # failure: a transient propose error must
                                # not tax the next tick with a full
                                # numeric re-encode
                                self.encoder.poison_all_numeric()
                        from ..utils.leadership import leadership_lost

                        if leadership_lost(exc):
                            log.info("scheduler: leadership lost; stopping")
                            return
                        # a propose can fail transiently (quorum loss); the
                        # unassigned pool is preserved and the max-latency
                        # path retries even with no new events — the loop
                        # must survive
                        log.exception("scheduler: tick failed; will retry")
                        dirty_since = time.monotonic()
        finally:
            try:
                if self._inflight is not None:
                    self.flush_pipeline()
            except Exception:
                self._inflight = None
                if self._resident is not None:
                    self._resident.invalidate()
            self.store.queue.stop_watch(ch)

    # ------------------------------------------------------------------ tick
    def tick(self):
        self.ticks += 1
        # trace-plane root: stage spans (encode/dispatch/device_sync/
        # barrier/commit) nest under it implicitly; the NOOP singleton
        # when disarmed — no allocation on the hot path
        with trace.span("sched.tick", n=self.ticks):
            if self._inflight is not None:
                self._tick_pipelined()
                return
            # the serial path reads and mutates host state end to end:
            # retire any heavy commit still riding the async plane first
            # (worker exceptions re-raise here, into the guarded tick)
            self._drain_commit_plane()
            if self.preassigned:
                self._process_preassigned()
            self._schedule_backlog()

    def _schedule_backlog(self):
        """One scheduling pass over the unassigned pool (the serial tick
        body). In pipeline mode a jax-shaped wave dispatches and returns
        with the wave in flight; anything else commits synchronously."""
        if not self.unassigned:
            return
        groups = self._group_unassigned()
        if not groups:
            return
        with trace.span("tick.encode", groups=len(groups)):
            problem = self.encoder.encode(list(self.node_infos.values()),
                                          groups,
                                          volume_set=self.volume_set)
        # the scan component (sort + fingerprint compare; ~0 on the
        # tracked zero-scan path) files as its own stage for the
        # tick_stage_seconds histogram (armed only; one truthiness test
        # disarmed)
        trace.rec("tick.dirty_scan", self.encoder.last_scan_s)
        use_jax = self._use_jax(problem)
        if use_jax and self.backend == "auto" \
                and len(problem.node_ids) <= COLD_CPU_NODES \
                and not self._cold_cpu_done \
                and (self._resident is None
                     or self._resident.needs_full_upload(problem)):
            # cold-start policy: no usable device state — the first wave
            # is cheaper on the CPU oracle than behind a blocking cold
            # upload + counts RTT; the next wave warms the device (the
            # one-shot flag stops the CPU tick's own invalidate() from
            # re-triggering this forever)
            use_jax = False
            self._cold_cpu_done = True
        if use_jax:
            if self._resident is None:
                from ..ops.resident import ResidentPlacement

                self._resident = ResidentPlacement(
                    self.encoder, mesh=self._make_mesh())
            self._cold_cpu_done = False      # device state is warming
            if self.pipeline:
                # dispatch only: the counts D2H rides the link through the
                # debounce window; the next tick completes the wave
                with trace.span("tick.dispatch"):
                    h = self._resident.schedule_async(problem)
                ids = frozenset(t.id for g in groups for t in g.tasks)
                self._inflight = (problem, h, ids)
                return
            # blocking schedule: the counts pull inside is the one real
            # device sync of this tick (tunnel rule: one span per burst)
            with trace.span("tick.device_sync"):
                counts = self._resident.schedule(problem)
        else:
            with trace.span("tick.cpu_fill"):
                counts = cpu_schedule_encoded(problem)
            if self._resident is not None:
                # the device copy missed this tick's fold: resync on the
                # next jax tick
                self._resident.invalidate()
        with trace.span("tick.commit"):
            orders = materialize_orders(problem, counts)
            self._apply_decisions(problem, orders, counts)

    def _make_mesh(self):
        """Resolve the configured mesh (backend="mesh" / mesh=) to a
        jax.sharding.Mesh, or None for single-device."""
        mesh = self.mesh
        if mesh is None and self.backend != "mesh":
            return None
        if mesh is None or isinstance(mesh, int):
            import jax

            from ..parallel.mesh import make_mesh

            n = mesh if mesh is not None else len(jax.devices())
            # the resident state's node buckets are powers of two, so the
            # sharded axis must be one too: round down (a 6-device host
            # runs a 4-device mesh rather than crashing on upload)
            chosen = 1 << (max(n, 1).bit_length() - 1)
            if chosen != n:
                log.info("scheduler: mesh backend using %d of %d visible "
                         "devices (node axis must be a power of two)",
                         chosen, n)
            else:
                log.info("scheduler: mesh backend over %d devices", chosen)
            mesh = make_mesh(chosen)
        return mesh

    def _use_jax(self, problem) -> bool:
        total_tasks = int(problem.n_tasks.sum())
        return (self.backend in ("jax", "mesh")
                or (self.backend == "auto"
                    and total_tasks * max(len(problem.node_ids), 1)
                    >= self.jax_threshold))

    def _tick_pipelined(self, allow_retry: bool = True):
        """Complete the in-flight wave and keep the pipeline primed: pull
        counts, fold (optimistically), dispatch the NEXT wave, then commit
        the completed one under the new wave's transfer (ops/pipeline.py
        order). An unclean commit abandons both the fold and any stale
        next dispatch — fingerprint deltas re-encode the touched rows.

        allow_retry=False (flush/stop path): a conflicted or discarded
        wave is NOT re-attempted, so the drain terminates instead of
        dispatching fresh waves forever."""
        problem, h, prev_ids = self._inflight
        self._inflight = None
        worker = self._commit_worker
        overlap = False
        if worker is not None:
            # encode/commit overlap gate (round 6) — O(1): with a
            # TRACKED-clean encoder, no preassigned work, no volumes (the
            # in-tx volume choose mutates the VolumeSet this tick's
            # encode would read) and a healthy plane, nothing below reads
            # state the riding heavy commit writes — the barrier is
            # skipped and the previous wave's walk/write-back overlaps
            # this tick's fold + zero-scan encode + dispatch. An unclean
            # outcome recorded mid-overlap is caught at the NEXT
            # non-overlap barrier, which discards the wave primed on the
            # lying fold — the pre-existing one-wave-late heal semantics.
            overlap = (self.encoder.tracked and not self.preassigned
                       and self._worker_unclean is None
                       and not worker.failed
                       and not self.volume_set.volumes
                       and self.encoder.nodes_clean(
                           self.node_infos.values()))
            # async plane: pull FIRST — the blocking transfer wait
            # releases the GIL, which is when the previous wave's heavy
            # commit runs — then (overlap off) barrier before any
            # host-state read.
            with trace.span("tick.device_sync"):
                counts = h.get()
            if overlap and (worker.failed
                            or self._worker_unclean is not None):
                overlap = False     # plane turned unhealthy mid-pull
            if not overlap:
                with trace.span("tick.barrier"):
                    worker.barrier()    # worker exceptions re-raise here
                self._pending_commit_ids.clear()
                if self._worker_unclean is not None:
                    # the PREVIOUS wave's commit was unclean, and THIS
                    # wave was primed on its lying fold: heal (poison +
                    # resident resync) and discard this wave un-folded —
                    # its tasks are still in the unassigned pool, so
                    # attempt them fresh against the healed state (no
                    # pool-changed gate: a discarded wave was never
                    # attempted, so going idle here would wedge it)
                    self._heal_unclean()
                    if self.preassigned:
                        self._process_preassigned()
                    if allow_retry and self.unassigned:
                        self._schedule_backlog()
                    return
                if self.preassigned:
                    self._process_preassigned()
            # overlap path: the gate proved no preassigned work and no
            # recorded unclean wave; a record landing in the remaining
            # window is healed at the next non-overlap barrier (which
            # discards the wave primed below) — never concurrently with
            # a still-riding heavy.
        else:
            if self.preassigned:
                # preassigned (global-service) tasks never touch the
                # encoded problem; under sustained pipelined load this
                # is their only slot (the serial path's call is short-
                # circuited). Their add_task bumps flip nodes_clean,
                # which correctly forces the touched rows to re-encode
                # before the next dispatch.
                self._process_preassigned()
            with trace.span("tick.device_sync"):
                counts = h.get()
        with trace.span("tick.fold"):
            folded = self.encoder.fold_counts(problem, counts)
            if folded:
                self._resident.after_apply(problem, counts)
            else:
                self._resident.invalidate()

        if worker is not None and folded and overlap:
            # overlap: the heavy half is submitted BEFORE the prime, so
            # the zero-scan encode below runs concurrently with the
            # walk/write-back (the pool race is closed by the exclusion
            # set _submit_heavy maintains)
            self.overlapped_commits += 1
            try:
                self._submit_heavy(problem, counts, prev_ids)
            except BaseException:
                # the riding heavy failed inside the overlap window
                # (post-gate): submit refused THIS wave, whose fold
                # already ran and whose add_task walk will never run —
                # poison its placed-on rows so the run-loop heal
                # re-derives them (the recorded-unclean heal only
                # covers the wave the worker crashed on)
                self.encoder.force_numeric_reencode(
                    np.flatnonzero(counts.sum(axis=0)))
                raise

        # next wave: everything unassigned that is NOT still uncommitted
        # in the wave being completed (no double placement) NOR in a wave
        # whose heavy commit may still be riding the plane
        if (folded and self.pipeline
                and self.encoder.nodes_clean(self.node_infos.values())):
            exclude = prev_ids
            pending = tuple(self._pending_commit_ids)
            if pending:
                exclude = frozenset().union(prev_ids, *pending)
            next_groups = self._group_unassigned(exclude=exclude)
            # CPU-shaped waves skip the prime entirely (the encode would
            # be discarded and redone by the fallthrough below)
            total_next = sum(len(g.tasks) for g in next_groups)
            if next_groups and (
                    self.backend in ("jax", "mesh")
                    or total_next * max(len(self.node_infos), 1)
                    >= self.jax_threshold):
                with trace.span("tick.encode", groups=len(next_groups)):
                    p_next = self.encoder.encode(
                        list(self.node_infos.values()), next_groups,
                        volume_set=self.volume_set)
                trace.rec("tick.dirty_scan", self.encoder.last_scan_s)
                if self._use_jax(p_next):
                    with trace.span("tick.dispatch"):
                        h_next = self._resident.schedule_async(p_next)
                    ids = frozenset(
                        t.id for g in next_groups for t in g.tasks)
                    self._inflight = (p_next, h_next, ids)

        if worker is not None and folded:
            # heavy half rides the commit plane: materialization, store
            # write-back, the add_task walk, the restamp — retired by
            # the next barrier; an unclean outcome heals there too.
            # Barriered order: enqueued only now, after this tick's
            # encode/dispatch stopped reading host state (the overlap
            # path submitted before the prime instead).
            if not overlap:
                self._submit_heavy(problem, counts, prev_ids)
            if self._inflight is None and self.unassigned:
                # nothing primed: the backlog must be attempted NOW
                # (wedge avoidance, same as the sync path below) — and
                # that reads the pool the worker is mutating, so retire
                # the commit first (rare when load is sustained; the
                # primed case above keeps the overlap)
                self._drain_commit_plane()
                if allow_retry and (
                        frozenset(self.unassigned) != prev_ids
                        or self._last_commit_conflicts):
                    # conflict bypass of the pool-changed gate: the
                    # commit ran BEHIND the plane, so the store write
                    # that conflicted it may already have been consumed
                    # by the event loop mid-flight — with no event left
                    # to retrigger, an identical pool would wedge. One
                    # immediate retry runs against node_infos that
                    # already include that write; a repeat conflict
                    # implies a FRESH store divergence whose event is
                    # still queued to wake the loop.
                    self._schedule_backlog()
            return
        if worker is not None and overlap:
            # the overlap path skipped the top barrier and the fold
            # failed (node set moved under us — unreachable while the
            # tracked gate pins it, but defensive): an inline commit
            # below must never run beside a riding heavy
            self._drain_commit_plane()
        with trace.span("tick.commit"):
            orders = materialize_orders(problem, counts)
            clean = self._apply_decisions(problem, orders, counts,
                                          deferred_fold=True)
        if clean:
            self.encoder.restamp_counts(problem, counts)
        else:
            # the optimistic fold lied: poison every placed-on row so the
            # next encode re-derives it from the NodeInfo objects (a node
            # whose placements ALL dropped never bumped its mutation
            # counter — without this its phantom reservations persist),
            # resync the device, and discard any dispatch built on the
            # bad fold
            self.encoder.force_numeric_reencode(
                np.flatnonzero(counts.sum(axis=0)))
            self._resident.invalidate()
            if self._inflight is not None:
                _p2, h2, _ids2 = self._inflight
                self._inflight = None
                h2.get()
        if (self._inflight is None and self.unassigned and allow_retry
                and (frozenset(self.unassigned) != prev_ids
                     or self._last_commit_conflicts)):
            # nothing primed (dirty nodes, CPU-shaped wave, unclean heal,
            # or the backlog arrived after the prime check): schedule it
            # NOW — leaving it for a future event would wedge a backlog
            # that generates no further events (chaos-test regression).
            # The pool-changed gate stops the degenerate loop: a pool
            # identical to the wave just attempted is unplaceable-as-is
            # (explanations written by _apply_decisions) and must go IDLE
            # until an event, exactly like the serial path — otherwise
            # flush_pipeline() never terminates and the run loop burns a
            # device round trip per debounce forever.
            self._schedule_backlog()

    def flush_pipeline(self):
        """Complete any in-flight wave now (stop/leadership-loss path);
        in async mode also retire the last heavy commit."""
        while self._inflight is not None:
            self._tick_pipelined(allow_retry=False)
        self._drain_commit_plane()

    def _group_unassigned(self, exclude: frozenset | None = None,
                          ) -> list[TaskGroup]:
        grouped: dict[tuple[str, int], list[Task]] = defaultdict(list)
        # list() is one C-level op (GIL-atomic): on the overlap path a
        # riding heavy commit pops committed tasks from this dict
        # concurrently — a plain .values() iteration would raise
        # "dict changed size". A popped task still in the snapshot is in
        # the exclusion set by construction (_pending_commit_ids).
        for t in list(self.unassigned.values()):
            if exclude is not None and t.id in exclude:
                continue
            sv = t.spec_version.index if t.spec_version else 0
            grouped[(t.service_id or t.id, sv)].append(t)
        out = []
        for k, ts in grouped.items():
            ts = sorted(ts, key=lambda t: t.id)
            # ids built here, while the sort has the task objects hot —
            # the wave-commit walk keys on them (TaskGroup.ids contract)
            out.append(TaskGroup(service_id=k[0], spec_version=k[1],
                                 tasks=ts, ids=[t.id for t in ts]))
        return out

    # -------------------------------------------------------------- commits
    def _batched_writes(self, items: list, write_one) -> None:
        """ONE grouped store update for `items` (round 6): `write_one(tx,
        item)` runs for every item inside a single update transaction —
        one lock hold, one table swap, one event batch — instead of one
        Batch closure + one sub-transaction per 200 items. Raft-backed
        stores keep the reference's per-entry bound: items chunk at
        MAX_CHANGES_PER_TRANSACTION and the sub-transactions pipeline
        through the group-commit plane (disjoint by construction — a
        task id appears at most once per wave write-back)."""
        if not items:
            return
        if self.store.proposer is not None:
            step = MAX_CHANGES_PER_TRANSACTION
            depth = WRITEBACK_PIPELINE_DEPTH
        else:
            step = len(items)
            depth = None
        chunks = [items[i:i + step] for i in range(0, len(items), step)]

        def batch_cb(batch):
            for chunk in chunks:
                def run_chunk(tx, chunk=chunk):
                    for item in chunk:
                        write_one(tx, item)

                batch.update_many(run_chunk, len(chunk))

        self.store.batch(batch_cb, pipeline_depth=depth)

    def _apply_decisions(self, problem, orders, counts=None,
                         deferred_fold=False) -> bool:
        """store.Batch with in-tx re-validation (scheduler.go:490-643).

        `orders` is materialize_orders output: per group (aligned with
        problem.groups) the canonical slot order of node indices; the
        group's id-sorted tasks zip with it, tasks past the end are
        unplaced.

        deferred_fold=True (pipelined path): the caller already folded the
        encoder optimistically and owns the restamp/invalidate decision —
        the return value says whether the commit was clean (exactly one
        add_task per decided placement)."""
        groups = problem.groups
        # gi -> [(committed task, node index)] for successful assignments
        applied_by_group: dict[int, list[tuple[Task, int]]] = {}
        # tasks no longer schedulable (deleted, dead, raced to assigned
        # elsewhere) — evicted from the unassigned pool after the batch;
        # conflicted decisions are NOT dropped and retry next tick
        drop: list[str] = []
        unplaced: list[tuple[Task, TaskGroup]] = []
        conflicts = [0]

        node_ids = problem.node_ids
        from ..csi.volumes import task_csi_mounts

        # flat decision list in (group, slot) order — the store write-back
        # runs it as ONE grouped transaction (round 6; _batched_writes)
        # instead of one closure + one 200-change sub-transaction slice
        # per task, keeping the exact per-task in-tx re-validation
        decisions: list[tuple] = []
        for gi, group in enumerate(groups):
            order = orders[gi]
            n_placed = len(order)
            for ti, task in enumerate(group.tasks):
                ni = int(order[ti]) if ti < n_placed else -1
                decisions.append(
                    (task, node_ids[ni] if ni >= 0 else None, ni, group, gi))

        # columnar bulk path (ISSUE 11): placed decisions without CSI
        # volume choice commit as ONE store.assign_wave — vectorized
        # in-tx re-validation against the columnar mirror, one shallow
        # patch per task instead of two tree copies, same events. CSI
        # tasks keep the object path (choose_task_volumes is a per-task
        # in-tx decision); unplaced rows keep it too (explanations).
        fast: list[tuple] = []
        slow: list[tuple] = []
        if self.columnar_writeback:
            for d in decisions:
                if d[1] is not None and not task_csi_mounts(d[0]):
                    fast.append(d)
                else:
                    slow.append(d)
        else:
            slow = decisions
        if fast:
            codes, committed = self.store.assign_wave(
                [(task.id, node_id) for task, node_id, *_ in fast],
                pipeline_depth=WRITEBACK_PIPELINE_DEPTH)
            for (task, node_id, ni, group, gi), code, cur in zip(
                    fast, codes, committed):
                if code == ASSIGN_OK:
                    applied_by_group.setdefault(gi, []).append((cur, ni))
                elif code == ASSIGN_NODE_NOT_READY:
                    conflicts[0] += 1
                else:           # missing / dead / raced: evict from pool
                    drop.append(task.id)

        def write_decision(tx, item):
            task, node_id, ni, group, gi = item
            cur = tx.get_task(task.id)
            if cur is None or cur.desired_state > TaskState.COMPLETE:
                drop.append(task.id)
                return
            if cur.status.state != TaskState.PENDING or cur.node_id:
                drop.append(task.id)
                return
            if node_id is None:
                # explanation is written in a second pass, after node
                # bookkeeping reflects this tick's sibling placements —
                # else 'insufficient resources' reads as 'all filters
                # passed'
                unplaced.append((cur, group))
                return
            node = tx.get_node(node_id)
            if node is None or node.status.state != NodeStatusState.READY:
                conflicts[0] += 1
                return  # conflicted: retried (see below)
            cur = cur.copy()
            # CSI volumes chosen at commit time, with the reservation
            # re-check the reference does in-tx (scheduler.go:533-604
            # volume availability)
            if task_csi_mounts(cur):
                chosen = self.volume_set.choose_task_volumes(cur, node)
                if chosen is None:
                    conflicts[0] += 1
                    return  # conflicted: retried (see below)
                cur.volumes = chosen
            cur.node_id = node_id
            cur.status.state = TaskState.ASSIGNED
            cur.status.message = "scheduler assigned task to node"
            cur.status.timestamp = time.time()
            tx.update(cur)
            applied_by_group.setdefault(gi, []).append((cur, ni))

        if slow:
            self._batched_writes(slow, write_decision)
        if applied_by_group and lifecycle.enabled():
            # lifecycle plane: ONE batched ASSIGNED record covering every
            # task this wave placed — never per task inside the commit
            # walk (the plane's batching contract; id assembly is gated
            # so the disarmed path allocates nothing)
            lifecycle.record_batch(
                TaskState.ASSIGNED,
                [t.id for placed in applied_by_group.values()
                 for t, _ in placed])
        # conflicted decisions stay in the pool; the serial path relies
        # on the causing store write's still-queued event to retrigger,
        # but a pipelined wave may conflict on an event consumed while
        # it was in flight — record the count so the completing tick can
        # retry the pool itself (async mode reads this post-barrier)
        self._last_commit_conflicts = conflicts[0]

        with_generic: list[tuple[str, str]] = []
        # wave-level NodeInfo bookkeeping (batch.apply_placements): the
        # per-task add_task loop was the commit's hot spot — typical big
        # waves degenerate to ~1 task per (group, node) cell, so the bulk
        # path segments per node across the whole wave. Groups with
        # generic reservations or host ports keep the full per-task path
        # inside apply_placements.
        placed_groups = []
        for gi, placed in applied_by_group.items():
            group = groups[gi]
            for task, _ni in placed:
                self.unassigned.pop(task.id, None)
            if group.tasks[0].spec.resources.reservations.generic:
                with_generic.extend(
                    (task.id, node_ids[ni]) for task, ni in placed)
            committed = [t for t, _ in placed]
            placed_groups.append(
                (group.tasks[0], committed,
                 np.fromiter((ni for _, ni in placed), np.int64,
                             len(placed)),
                 # ids built here while the committed copies are hot from
                 # the store transaction (TaskGroup.ids contract)
                 [t.id for t in committed]))
        if placed_groups:
            # row-order NodeInfo list for the walk: reuse the problem's
            # encode-time snapshot when it is still current (tracked
            # encoders bump infos_seq on any row-object swap — replaced
            # node, set change — so the O(1) stamp check is sound; the
            # barrier discipline keeps marks out of the commit window).
            # Stale or untracked: rebuild from the live map, where a
            # removed node correctly yields None (skipped, uncounted —
            # the unclean heal covers it).
            infos = problem.row_infos
            if (infos is None or not self.encoder.tracked
                    or problem.infos_seq != self.encoder.infos_seq):
                infos = [self.node_infos.get(nid) for nid in node_ids]
            n_added = apply_placements(infos, placed_groups)
        else:
            n_added = 0
        # fold our own placements back into the encoder's cached rows
        # (vectorized) iff every decided placement landed as exactly one
        # add_task; otherwise let the fingerprint delta re-encode the
        # touched rows next tick (conflicts/drops are rare)
        clean = counts is not None and n_added == int(counts.sum())
        if deferred_fold:
            pass    # pipelined caller folded pre-commit and owns the rest
        elif clean:
            folded = self.encoder.apply_counts(problem, counts)
            if self._resident is not None:
                if folded:
                    self._resident.after_apply(problem, counts)
                else:
                    self._resident.invalidate()
        elif counts is not None:
            if self._resident is not None:
                # fingerprint deltas will re-encode the touched rows next
                # tick, but the device carry already folded THIS tick's
                # full counts: resync from host
                self._resident.invalidate()
            if self.encoder.tracked:
                # the zero-scan path never reads those fingerprints: the
                # placed-on rows must also land in the mark feed, or the
                # partial add_task walk stays invisible to the next encode
                for r in np.flatnonzero(counts.sum(axis=0)).tolist():
                    info = self.node_infos.get(node_ids[r])
                    if info is not None:
                        self.encoder.mark_numeric(info)
        if with_generic:
            # persist which named/discrete generic resources were granted
            # (reference nodeinfo.go:132-137 stamps AssignedGenericResources
            # on the task before commit; we claim post-commit and follow up)
            def write_generic(tx, item):
                task_id, node_id = item
                cur = tx.get_task(task_id)
                info = self.node_infos.get(node_id)
                if cur is None or info is None:
                    return
                cur = cur.copy()
                cur.assigned_generic_resources = {
                    kind: (sorted(named), count)
                    for kind, (named, count)
                    in info.assigned_generic(task_id).items()
                }
                tx.update(cur)

            self._batched_writes(with_generic, write_generic)
        for task_id in drop:
            self.unassigned.pop(task_id, None)

        if unplaced:
            # second pass: explanations against bookkeeping that now includes
            # this tick's placements, written only on change so identical
            # failures don't retrigger the commit debounce forever
            # explanations computed BEFORE the grouped transaction: the
            # filter-pipeline walk is O(nodes) per group and must not run
            # under the store's update lock
            explain_cache: dict[tuple[str, int], str] = {}
            for _task, group in unplaced:
                if group.key not in explain_cache:
                    explain_cache[group.key] = self._explain(group)

            def write_explanation(tx, item):
                task, group = item
                explanation = explain_cache[group.key]
                cur = tx.get_task(task.id)
                if cur is None or cur.status.state != TaskState.PENDING:
                    return
                if cur.status.err == explanation:
                    return
                cur = cur.copy()
                cur.status.message = "scheduler: no suitable node"
                cur.status.err = explanation
                cur.status.timestamp = time.time()
                tx.update(cur)

            self._batched_writes(unplaced, write_explanation)
        # everything else (no-suitable-node, conflicted commits) stays in
        # self.unassigned; node/task events retrigger the tick
        return clean

    def _explain(self, group: TaskGroup) -> str:
        pipeline = Pipeline(self.volume_set)
        pipeline.set_task(group.tasks[0])
        for info in self.node_infos.values():
            pipeline.process(info)
        return pipeline.explain() or "no nodes available"

    # --------------------------------------------------------- preassigned
    def _process_preassigned(self):
        """Global-service tasks arrive with node_id set; validate fit only
        (reference processPreassignedTasks/taskFitNode, scheduler.go:398-426)."""
        tasks = list(self.preassigned.values())
        decided: list[tuple[Task, bool]] = []
        pipeline = Pipeline(self.volume_set)
        for t in tasks:
            info = self.node_infos.get(t.node_id)
            if info is None:
                continue  # wait for node
            pipeline.set_task(t)
            decided.append((t, pipeline.process(info)))

        # lifecycle plane: collect ids INSIDE the tx, only for writes
        # that actually landed (same discipline as the wave path's
        # applied_by_group — a task deleted mid-decision must not file a
        # phantom ASSIGNED that then reads as "stuck" forever)
        applied: list[str] | None = [] if lifecycle.enabled() else None

        def write_preassigned(tx, item):
            task, fits = item
            cur = tx.get_task(task.id)
            if cur is None or cur.status.state != TaskState.PENDING:
                return
            if fits:
                cur = cur.copy()
                cur.status.timestamp = time.time()
                cur.status.state = TaskState.ASSIGNED
                cur.status.message = (
                    "scheduler confirmed task can run on preassigned node")
                tx.update(cur)
                if applied is not None:
                    applied.append(cur.id)
            else:
                # keep PENDING and retry later — transient pressure
                # (resources, ports) may clear (reference
                # scheduler.go:654-661 only records Status.Err)
                err = "preassigned node does not satisfy filters"
                if cur.status.err != err:
                    cur = cur.copy()
                    cur.status.timestamp = time.time()
                    cur.status.err = err
                    tx.update(cur)

        self._batched_writes(decided, write_preassigned)
        if applied:
            lifecycle.record_batch(TaskState.ASSIGNED, applied)
        for task, fits in decided:
            if fits:
                self.preassigned.pop(task.id, None)
                info = self.node_infos.get(task.node_id)
                if info and info.add_task(task):
                    self.encoder.mark_numeric(info)
            # non-fitting tasks stay in self.preassigned for retry
