"""Brokered task-log streaming (reference: manager/logbroker/, SURVEY.md §2.7)."""
from .broker import (
    LogBroker,
    LogContext,
    LogMessage,
    LogSelector,
    SubscriptionMessage,
    make_log_message,
)

__all__ = [
    "LogBroker",
    "LogContext",
    "LogMessage",
    "LogSelector",
    "SubscriptionMessage",
    "make_log_message",
]
