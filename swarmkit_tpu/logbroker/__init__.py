"""Brokered task-log streaming (reference: manager/logbroker/, SURVEY.md §2.7).

Two planes (ISSUE 20): the scalar `LogBroker` is the single-plane
oracle; `ShardedLogBroker` (logbroker/sharded.py) is the production
bounded-lag fan-out. `make_log_broker` picks the sharded plane unless
SWARMKIT_TPU_NO_SHARDED_LOGS=1.
"""
from .broker import (
    LogBroker,
    LogContext,
    LogMessage,
    LogSelector,
    LogShedRecord,
    SubscriptionComplete,
    SubscriptionMessage,
    make_log_message,
)
from .sharded import (
    ShardedLogBroker,
    ShedChannel,
    default_logbroker_shards,
    make_log_broker,
)

__all__ = [
    "LogBroker",
    "LogContext",
    "LogMessage",
    "LogSelector",
    "LogShedRecord",
    "ShardedLogBroker",
    "ShedChannel",
    "SubscriptionComplete",
    "SubscriptionMessage",
    "default_logbroker_shards",
    "make_log_broker",
    "make_log_message",
]
