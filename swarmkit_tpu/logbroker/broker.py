"""LogBroker: brokered task-log streaming.

Re-derivation of manager/logbroker/broker.go:104-440: a client
`subscribe_logs` names targets by service/task/node selector; the broker
fans a subscription out to the agents that run matching tasks
(`listen_subscriptions` — the agent-facing LogBroker.ListenSubscriptions
stream); agents pump task logs back via `publish_logs`, and the broker
routes them into the client's stream. Subscriptions follow task movement:
new tasks for a followed service pull newly-involved nodes into the
subscription (broker.go subscription.Run watchers).
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field

from ..analysis.lockgraph import make_lock
from ..api.objects import EventCreate, EventUpdate, Task
from ..store import by
from ..store.watch import Channel, ChannelClosed
from ..utils.clock import REAL_CLOCK
from ..utils.identity import new_id


@dataclass
class LogSelector:
    """api/logbroker.proto LogSelector."""

    service_ids: list[str] = field(default_factory=list)
    node_ids: list[str] = field(default_factory=list)
    task_ids: list[str] = field(default_factory=list)

    def empty(self) -> bool:
        return not (self.service_ids or self.node_ids or self.task_ids)


@dataclass
class LogContext:
    service_id: str = ""
    node_id: str = ""
    task_id: str = ""


@dataclass
class LogMessage:
    """api/logbroker.proto LogMessage: context + timestamped stream data."""

    context: LogContext
    timestamp: float
    stream: str  # "stdout" | "stderr"
    data: bytes


@dataclass
class SubscriptionMessage:
    """api/logbroker.proto SubscriptionMessage sent to agents."""

    id: str
    selector: LogSelector
    follow: bool = True
    close: bool = False


@dataclass
class LogShedRecord:
    """In-stream marker for a counted, resumable loss window (ISSUE 20):
    a bounded client channel that overflowed dropped `count` messages —
    publish-sequence numbers `first_seq..last_seq` of THIS subscription —
    and the stream resumes right after the marker. Clients that need the
    window can re-subscribe non-follow to backfill; the accounting
    invariant is exact: delivered + shed == published per subscriber."""

    count: int = 0
    first_seq: int = 0
    last_seq: int = 0


@dataclass
class SubscriptionComplete:
    """Terminal record of a log stream (broker.go SubscribeLogs's
    `completed` publish): offered once every publisher finished, carrying
    the aggregated warning text — unreachable nodes, disconnects,
    never-scheduled tasks — after which the client channel closes."""

    error: str = ""


class _Subscription:
    def __init__(self, sub_id: str, selector: LogSelector, follow: bool,
                 limit: int | None = None):
        self.id = sub_id
        self.selector = selector
        self.follow = follow
        self.client = Channel(matcher=None, limit=limit)
        self.nodes: set[str] = set()  # nodes the subscription was sent to
        self.known_tasks: set[str] = set()  # tasks seen when last dispatched
        self.done = False
        # completion accounting (subscription.go wg/Done — non-follow only):
        # a node is pending from first dispatch until its publisher closes
        self.pending_nodes: set[str] = set()
        self.done_nodes: set[str] = set()
        self.errors: list[str] = []
        self.pending_tasks: set[str] = set()  # matched but never scheduled

    def err_text(self) -> str:
        """subscription.go Err(): aggregate warning, '' when clean."""
        msgs = list(self.errors)
        msgs += [f"task {t} has not been scheduled"
                 for t in sorted(self.pending_tasks)]
        if not msgs:
            return ""
        return ("warning: incomplete log stream. some logs could not be "
                "retrieved for the following reasons: " + ", ".join(msgs))


class LogBroker:
    # broken-stream sweep cadence in _run (clock-relative, so a FakeClock
    # drives sweeps deterministically)
    SWEEP_INTERVAL = 0.5

    def __init__(self, store, clock=None):
        self.store = store
        self.clock = clock or REAL_CLOCK
        self._lock = make_lock('logbroker.broker.lock')
        self._subs: dict[str, _Subscription] = {}
        # node_id -> channel of SubscriptionMessage (agent listeners)
        self._listeners: dict[str, Channel] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        self._stop = threading.Event()  # restartable across leadership cycles
        self._thread = threading.Thread(target=self._run, name="logbroker", daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        with self._lock:
            for sub in self._subs.values():
                sub.client.close()
            for ch in self._listeners.values():
                ch.close()
            self._subs.clear()
            self._listeners.clear()

    # -- client side (Logs.SubscribeLogs, logbroker.proto:103-125) ---------

    def subscribe_logs(self, selector: LogSelector, follow: bool = True,
                       limit: int | None = None) -> tuple[str, Channel]:
        """Returns (subscription_id, channel of LogMessage). A non-follow
        stream ends with a SubscriptionComplete record once every
        publisher closed (broker.go SubscribeLogs:255-283). `limit`
        bounds the client channel (None keeps the historical unbounded
        oracle behavior; the sharded plane defaults to bounded+shed).
        -1 selects the plane's default bound — unbounded here."""
        if selector.empty():
            raise ValueError("empty log selector")
        if limit == -1:
            limit = None
        sub = _Subscription(new_id(), selector, follow, limit=limit)
        with self._lock:
            self._subs[sub.id] = sub
        self._dispatch_to_nodes(sub)
        if not follow:
            with self._lock:
                self._maybe_complete(sub)
        return sub.id, sub.client

    def unsubscribe(self, sub_id: str):
        with self._lock:
            sub = self._subs.pop(sub_id, None)
        if sub is None:
            return
        sub.done = True
        sub.client.close()
        close_msg = SubscriptionMessage(id=sub.id, selector=sub.selector, close=True)
        with self._lock:
            offers = [ch for node_id in sub.nodes
                      if (ch := self._listeners.get(node_id)) is not None]
        # offer outside the broker lock (the dispatcher's offer-outside-
        # lock rule, ISSUE 20): a listener channel's own cond is the only
        # lock the close fan-out may hold
        for ch in offers:
            ch._offer(close_msg)

    # -- agent side (LogBroker.ListenSubscriptions / PublishLogs) ----------

    def listen_subscriptions(self, node_id: str) -> Channel:
        """An agent's stream of subscription open/close messages
        (broker.go:223-307). Re-listening replaces the previous stream."""
        ch = Channel(matcher=None, limit=None)
        with self._lock:
            old = self._listeners.get(node_id)
            self._listeners[node_id] = ch
            subs = [s for s in self._subs.values() if node_id in s.nodes and not s.done]
        if old is not None:
            old.close()
        # replay active subscriptions relevant to this node — one batched
        # offer, outside any broker-lock hold (offer-outside-lock rule)
        replay = [SubscriptionMessage(id=s.id, selector=s.selector,
                                      follow=s.follow) for s in subs]
        if replay:
            ch._offer_many(replay)
        return ch

    def stop_listening(self, node_id: str):
        """Explicit node disconnect (broker.go nodeDisconnected): pending
        completion accounting must not wait on a node that left."""
        with self._lock:
            ch = self._listeners.pop(node_id, None)
            for sub in list(self._subs.values()):
                if node_id in sub.pending_nodes:
                    self._mark_done(
                        sub, node_id,
                        f"node {node_id} disconnected unexpectedly")
        if ch is not None:
            ch.close()

    def publish_logs(self, sub_id: str, messages: list[LogMessage],
                     node_id: str = "", close: bool = False,
                     error: str = ""):
        """Agent publishes task log data upstream (broker.go PublishLogs).
        `close=True` is the publisher's EOF for this node — with an
        optional error when the pump failed — which feeds the non-follow
        completion accounting (broker.go:379-440 markDone)."""
        with self._lock:
            sub = self._subs.get(sub_id)
        if sub is None or sub.done:
            return
        # batched offer OUTSIDE the broker lock: one matcher pass, one
        # cond acquisition, one notify for the whole batch — messages are
        # never offered one-at-a-time under the broker lock (ISSUE 20)
        if messages:
            sub.client._offer_many(list(messages))
        if close:
            with self._lock:
                if self._subs.get(sub_id) is sub and not sub.done:
                    self._mark_done(sub, node_id, error)

    def _mark_done(self, sub: _Subscription, node_id: str, error: str = ""):
        """Lock held. subscription.go Done: record the publisher's end;
        complete the subscription when the last pending node finishes.
        A node already done is a duplicate close (sweep-then-replay race)
        and is ignored entirely, error included."""
        if node_id and node_id in sub.done_nodes:
            return
        if error:
            sub.errors.append(error)
        if node_id:
            sub.done_nodes.add(node_id)
            sub.pending_nodes.discard(node_id)
        if not sub.follow:
            self._maybe_complete(sub)

    def _maybe_complete(self, sub: _Subscription):
        """Lock held. Non-follow only: once no publisher is pending, emit
        the terminal record and end the client stream."""
        if sub.follow or sub.done or sub.pending_nodes:
            return
        sub.done = True
        self._subs.pop(sub.id, None)
        sub.client._offer(SubscriptionComplete(error=sub.err_text()))
        sub.client.close()

    # -- internals ---------------------------------------------------------

    def _match_tasks(self, tx, selector: LogSelector) -> list[Task]:
        out: dict[str, Task] = {}
        for tid in selector.task_ids:
            t = tx.get_task(tid)
            if t is not None:
                out[t.id] = t
        for sid in selector.service_ids:
            for t in tx.find_tasks(by.ByServiceID(sid)):
                out[t.id] = t
        for nid in selector.node_ids:
            for t in tx.find_tasks(by.ByNodeID(nid)):
                out[t.id] = t
        return list(out.values())

    def _dispatch_to_nodes(self, sub: _Subscription, force_nodes: set[str] = frozenset()):
        """Send the subscription to every node that gained a matching task —
        whether the node is new to the subscription or already receiving it
        (broker.go subscription.Run re-runs the match on task events).
        Re-offers are idempotent: agents dedupe pumped logs per task, not
        per subscription id, so `force_nodes` (nodes with fresh task events)
        are always re-notified to close the offer-before-task-start race."""
        tasks = self.store.view(lambda tx: self._match_tasks(tx, sub.selector))
        msg = SubscriptionMessage(id=sub.id, selector=sub.selector, follow=sub.follow)
        with self._lock:
            notify: set[str] = set(force_nodes)
            for t in tasks:
                if not t.node_id:
                    continue
                if t.node_id not in sub.nodes or t.id not in sub.known_tasks:
                    notify.add(t.node_id)
            sub.nodes |= notify
            sub.known_tasks = {t.id for t in tasks if t.node_id}
            # completion accounting (registerSubscription:128-143): a node
            # without a live listener can never publish — record the error
            # and mark it done immediately instead of waiting forever
            sub.pending_tasks = {t.id for t in tasks if not t.node_id}
            offers = []
            for n in notify:
                ch = self._listeners.get(n)
                alive = ch is not None and not ch.closed
                if alive:
                    offers.append(ch)
                    if not sub.follow and n not in sub.done_nodes:
                        sub.pending_nodes.add(n)
                elif not sub.follow and n not in sub.done_nodes:
                    # record only — completing here would race nodes later
                    # in the iteration out of their pending registration
                    # (subscribe_logs runs _maybe_complete after dispatch)
                    sub.errors.append(f"node {n} is not available")
                    sub.done_nodes.add(n)
        for ch in offers:
            ch._offer(msg)

    def _sweep(self):
        """Detect broken streams by their closed channels (the RPC server
        closes a stream's channel on disconnect):

        * a dead agent listener marks its pending subscriptions done with
          a disconnect error (broker.go nodeDisconnected:285-293);
        * a gone log client unsubscribes, telling its publishers to stop.
        """
        with self._lock:
            dead_nodes = [n for n, ch in self._listeners.items()
                          if ch.closed]
            for n in dead_nodes:
                del self._listeners[n]
                for sub in list(self._subs.values()):
                    if n in sub.pending_nodes:
                        self._mark_done(
                            sub, n, f"node {n} disconnected unexpectedly")
            gone_clients = [s.id for s in self._subs.values()
                            if s.client.closed and not s.done]
        for sid in gone_clients:
            self.unsubscribe(sid)

    def _run(self):
        """Follow-mode maintenance: tasks appearing on new nodes extend the
        subscription to those nodes (broker.go subscription task watcher).
        Also sweeps for broken client/agent streams."""
        queue = self.store.watch_queue()
        ch = queue.watch()
        last_sweep = self.clock.monotonic()
        try:
            while not self._stop.is_set():
                if self.clock.monotonic() - last_sweep > self.SWEEP_INTERVAL:
                    last_sweep = self.clock.monotonic()
                    self._sweep()
                try:
                    ev = ch.get(timeout=0.2)
                except TimeoutError:
                    self._sweep()
                    last_sweep = self.clock.monotonic()
                    continue
                except ChannelClosed:
                    queue.stop_watch(ch)
                    ch = queue.watch()
                    with self._lock:
                        subs = [s for s in self._subs.values() if s.follow and not s.done]
                    for s in subs:
                        self._dispatch_to_nodes(s)
                    continue
                if isinstance(ev, (EventCreate, EventUpdate)) and isinstance(ev.obj, Task):
                    t = ev.obj
                    with self._lock:
                        subs = [s for s in self._subs.values() if s.follow and not s.done]
                    for s in subs:
                        sel = s.selector
                        matches = (
                            t.id in sel.task_ids
                            or t.service_id in sel.service_ids
                            or t.node_id in sel.node_ids
                        )
                        force = {t.node_id} if (matches and t.node_id) else set()
                        self._dispatch_to_nodes(s, force_nodes=force)
        finally:
            queue.stop_watch(ch)


def make_log_message(task: Task, stream: str, data: bytes,
                     clock=None) -> LogMessage:
    """Timestamps ride the injectable clock seam (utils/clock) so tests
    pin them under FakeClock; callers without one get wall time."""
    return LogMessage(
        context=LogContext(
            service_id=task.service_id, node_id=task.node_id, task_id=task.id
        ),
        timestamp=(clock or REAL_CLOCK).time(),
        stream=stream,
        data=data,
    )
