"""Sharded log fan-out plane (ISSUE 20): the production LogBroker.

Rebuild of the scalar broker on the PR 13 dispatcher pattern:

* **Shards.** Agent listeners partition by ``stable_shard(node_id, P)``
  (the dispatcher's crc32 hash) into shards with leaf locks on the
  ``logbroker.shard<i>.lock`` naming scheme — the lockgraph hazard
  detector keys on the ``logbroker.shard`` prefix, like the
  dispatcher's. Pinned order: ``logbroker.lock`` (the global
  subscription registry) → shard lock, never the reverse.
* **Pumps.** Each shard owns a pump thread that serves the listener
  fan-out (subscription open/close offers) and sweeps its own
  listeners, so 100k agents never serialize on one broker loop.
  Offers always happen OUTSIDE broker locks; an unstarted broker
  drains jobs inline so driven tests stay synchronous.
* **Bounded channels + shed.** Client and listener channels are
  bounded (the ``Channel(limit=None)`` queued-wire-copy OOM shape
  ISSUE 16 fixed). A slow log client does not close and does not
  stall publishers: the overflow is SHED — counted per subscriber,
  announced in-stream by a resumable :class:`LogShedRecord` window —
  and the stream resumes as soon as the consumer drains. Invariant:
  ``delivered + shed == published`` per subscriber, exactly.
* **Batched publish.** ``publish_logs`` is one lock-free registry read
  plus ONE burst into the client channel's own cond (``offer_batch``)
  — zero broker/shard lock holds on the publish hot path, messages
  never offered one-at-a-time under any broker lock.
* **Telemetry.** ``swarm_logbroker_*`` families (per-shard published /
  delivered / shed counters + delivery-lag histogram) are built
  through the utils/metrics factories and populate ONLY while the
  telemetry plane is armed — the disarmed publish path pays one
  module-global truthiness test and allocates nothing. The always-on
  accounting lives on the channels (plain ints under their cond) and
  is aggregated on demand by :meth:`ShardedLogBroker.metrics_snapshot`
  for /metrics, /debug/cluster and the PR 15 rollup.

``SWARMKIT_TPU_NO_SHARDED_LOGS=1`` reverts to the single-plane broker
(`broker.LogBroker`), which stays the wire-parity oracle.
"""
from __future__ import annotations

import logging
import os
import threading
from collections import deque

from ..analysis.lockgraph import make_lock
from ..dispatcher.heartbeat import stable_shard
from ..store.watch import Channel
from ..utils import telemetry
from ..utils.metrics import CounterDict, counter_family, histogram_family
from ..utils.identity import new_id
from .broker import (
    LogBroker,
    LogSelector,
    LogShedRecord,
    SubscriptionComplete,
    SubscriptionMessage,
    _Subscription,
)

log = logging.getLogger("swarmkit_tpu.logbroker")

CLIENT_CHANNEL_LIMIT = 4096     # default bound on a log client's stream
LISTENER_CHANNEL_LIMIT = 1024   # bound on an agent's subscription stream

# armed-only families (utils/metrics factories → they ride
# registry_snapshot into the PR 15 rollup as swarm_cluster_* lifts)
_PUBLISHED = counter_family(
    "swarm_logbroker_published_total",
    "log messages published into the broker, by publisher shard",
    ("shard",))
_DELIVERED = counter_family(
    "swarm_logbroker_delivered_total",
    "log messages delivered into client channels, by publisher shard",
    ("shard",))
_SHED = counter_family(
    "swarm_logbroker_shed_total",
    "log messages shed at bounded client channels, by publisher shard",
    ("shard",))
_LAG = histogram_family(
    "swarm_logbroker_lag_seconds",
    "publish-to-delivery lag of the last message in each publish batch",
    ("shard",))


def default_logbroker_shards() -> int:
    """Shard count for the log fan-out plane: the dispatcher's shape
    (min(4, cores)), overridable via SWARMKIT_TPU_LOGBROKER_SHARDS."""
    env = os.environ.get("SWARMKIT_TPU_LOGBROKER_SHARDS", "")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            log.warning("ignoring bad SWARMKIT_TPU_LOGBROKER_SHARDS=%r", env)
    return max(1, min(4, os.cpu_count() or 1))


class ShedChannel(Channel):
    """Bounded client stream with shed-don't-stall overflow.

    The base Channel CLOSES a slow subscriber at its limit (the store
    watch-queue contract). A log client instead loses a counted window:
    overflowing messages are dropped, the loss is announced in-stream by
    one LogShedRecord (emitted the moment a slot frees — on the next
    offer or the next consumer pop), and delivery resumes. Cursors are
    delivery-gated: ``delivered``/``shed`` advance only by what actually
    entered or missed the deque, and ``published`` is the per-subscriber
    sequence the shed window's first/last_seq refer to."""

    def __init__(self, limit: int | None = CLIENT_CHANNEL_LIMIT):
        super().__init__(None, limit)
        # accounting, all under the inherited cond: exact per-subscriber
        # invariant published == delivered + shed
        self.published = 0
        self.delivered = 0
        self.shed = 0
        self.shed_windows = 0
        self._pending_shed = 0     # current un-announced window
        self._window_first = 0
        self._window_last = 0

    # -- internals (cond held) --------------------------------------------

    def _emit_marker_locked(self, force: bool = False):
        """Announce a pending shed window once a slot is free (`force`
        skips the room check — control records ride past the data bound
        and their marker must precede them regardless). Everything
        queued predates the window, so appending at the tail keeps the
        marker at the exact stream position of the loss."""
        if self._pending_shed and (
                force or self._limit is None
                or len(self._events) < self._limit):
            self._events.append(LogShedRecord(
                count=self._pending_shed,
                first_seq=self._window_first,
                last_seq=self._window_last))
            self._pending_shed = 0

    # -- publisher side ----------------------------------------------------

    def offer_batch(self, msgs: list) -> tuple[int, int]:
        """ONE cond hold and one notify for the whole batch; never blocks
        and never closes the stream. Returns (delivered, shed)."""
        with self._cond:
            n = len(msgs)
            first = self.published + 1
            self.published += n
            if self._closed:
                # still window-tracked: a consumer draining the closed
                # stream's tail sees one marker covering the loss, so
                # marker counts stay exactly equal to `shed`
                if not self._pending_shed:
                    self.shed_windows += 1
                    self._window_first = first
                self._pending_shed += n
                self._window_last = self.published
                self.shed += n
                return 0, n
            self._emit_marker_locked()
            if self._limit is None:
                take = n
            else:
                take = max(0, min(self._limit - len(self._events), n))
            if take:
                self._events.extend(msgs[:take])
                self.delivered += take
                self._cond.notify_all()
            dropped = n - take
            if dropped:
                if not self._pending_shed:
                    self.shed_windows += 1
                    self._window_first = first + take
                self._pending_shed += dropped
                self._window_last = self.published
                self.shed += dropped
            return take, dropped

    def offer_control(self, record) -> bool:
        """Control records (SubscriptionComplete) bypass the data limit —
        they are one-shot and must not be shed — but still follow any
        pending shed marker so the loss window is announced first."""
        with self._cond:
            if self._closed:
                return False
            self._emit_marker_locked(force=True)
            self._events.append(record)
            self._cond.notify_all()
            return True

    # -- consumer side (marker emission on drain) -------------------------

    def get(self, timeout: float | None = None):
        with self._cond:
            if not self._cond.wait_for(
                    lambda: self._events or self._closed, timeout):
                raise TimeoutError("no event within timeout")
            if self._events:
                ev = self._events.popleft()
                self._emit_marker_locked()
                return ev
            self._raise_closed()

    def try_get(self):
        with self._cond:
            if self._events:
                ev = self._events.popleft()
                self._emit_marker_locked()
                return ev
            if self._closed:
                self._raise_closed()
            return None

    def drain(self) -> list:
        with self._cond:
            out = list(self._events)
            self._events.clear()
            self._emit_marker_locked()
            if self._events:        # the freshly-emitted marker
                out.extend(self._events)
                self._events.clear()
            return out


class _ShardedSubscription(_Subscription):
    def __init__(self, sub_id: str, selector: LogSelector, follow: bool,
                 limit: int | None):
        super().__init__(sub_id, selector, follow)
        self.client = ShedChannel(limit)


class _Shard:
    """One slice of the listener plane: its agents, its pump inbox."""

    __slots__ = ("index", "lock", "listeners", "pending", "wake")

    def __init__(self, index: int):
        self.index = index
        # leaf lock on the hazard-keyed naming scheme (lockgraph's
        # DEFAULT_HAZARD_PREFIXES includes "logbroker.shard")
        self.lock = make_lock(f"logbroker.shard{index}.lock")
        self.listeners: dict[str, Channel] = {}
        # lock-free pump inbox (deque appends are GIL-atomic, the
        # dispatcher event-pump shape); jobs: (msg, [(node_id, ch), ...])
        self.pending: deque = deque()
        self.wake = threading.Event()


class ShardedLogBroker(LogBroker):
    """Sharded, bounded, telemetry-instrumented LogBroker (see module
    docstring). Drop-in for the scalar broker's full surface."""

    def __init__(self, store, shards: int | None = None, clock=None,
                 client_limit: int | None = CLIENT_CHANNEL_LIMIT,
                 listener_limit: int | None = LISTENER_CHANNEL_LIMIT):
        super().__init__(store, clock=clock)
        # the inherited lock is the GLOBAL subscription-registry lock;
        # rename it on the graph so the pinned order reads
        # logbroker.lock → logbroker.shard<i>.lock
        self._lock = make_lock("logbroker.lock")
        self.shards = max(1, int(shards if shards is not None
                                 else default_logbroker_shards()))
        self.client_limit = client_limit
        self.listener_limit = listener_limit
        self._shards = [_Shard(i) for i in range(self.shards)]
        self._pumps: list[threading.Thread] = []
        self._running = False
        # structural counters (never touched on the publish hot path) +
        # totals folded in from retired subscriptions
        self._bag = CounterDict({
            "subscriptions_opened": 0,
            "subscriptions_completed": 0,
            "listener_disconnects": 0,
            "dispatch_offers": 0,
            "pump_jobs": 0,
            "published": 0,
            "delivered": 0,
            "shed": 0,
            "shed_windows": 0,
        })
        for i in range(self.shards):
            self._bag[f"pump_depth_shard{i}"] = 0

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        self._stop = threading.Event()  # restartable across leaderships
        self._running = True
        self._pumps = []
        for sh in self._shards:
            t = threading.Thread(target=self._pump_loop, args=(sh,),
                                 name=f"logbroker-pump-{sh.index}",
                                 daemon=True)
            t.start()
            self._pumps.append(t)
        self._thread = threading.Thread(target=self._run, name="logbroker",
                                        daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        self._running = False
        for sh in self._shards:
            sh.wake.set()
        for t in self._pumps:
            t.join(timeout=5)
        self._pumps = []
        if self._thread:
            self._thread.join(timeout=5)
        # drain leftover pump jobs inline so close fan-outs aren't lost
        for sh in self._shards:
            self._drain_shard(sh)
        with self._lock:
            subs = list(self._subs.values())
            self._subs.clear()
        for sub in subs:
            self._retire(sub)
            sub.client.close()
        for sh in self._shards:
            with sh.lock:
                listeners = list(sh.listeners.values())
                sh.listeners.clear()
            for ch in listeners:
                ch.close()

    # -- client side -------------------------------------------------------

    def subscribe_logs(self, selector: LogSelector, follow: bool = True,
                       limit: int | None = -1) -> tuple[str, Channel]:
        """`limit=-1` takes the broker's default client bound; None means
        unbounded (the oracle shape — parity tests use it)."""
        if selector.empty():
            raise ValueError("empty log selector")
        if limit == -1:
            limit = self.client_limit
        sub = _ShardedSubscription(new_id(), selector, follow, limit)
        with self._lock:
            self._subs[sub.id] = sub
        self._bag.inc("subscriptions_opened")
        self._dispatch_to_nodes(sub)
        if not follow:
            with self._lock:
                self._maybe_complete(sub)
        return sub.id, sub.client

    def unsubscribe(self, sub_id: str):
        with self._lock:
            sub = self._subs.pop(sub_id, None)
        if sub is None:
            return
        sub.done = True
        self._retire(sub)
        sub.client.close()
        close_msg = SubscriptionMessage(id=sub.id, selector=sub.selector,
                                        close=True)
        offers = []
        for n in sub.nodes:
            sh = self._shards[stable_shard(n, self.shards)]
            with sh.lock:
                ch = sh.listeners.get(n)
            if ch is not None:
                offers.append((n, ch))
        self._submit_offers(offers, close_msg)

    # -- agent side --------------------------------------------------------

    def listen_subscriptions(self, node_id: str) -> Channel:
        ch = Channel(matcher=None, limit=self.listener_limit)
        sh = self._shards[stable_shard(node_id, self.shards)]
        with sh.lock:
            old = sh.listeners.get(node_id)
            sh.listeners[node_id] = ch
        with self._lock:
            subs = [s for s in self._subs.values()
                    if node_id in s.nodes and not s.done]
        if old is not None:
            old.close()
        # batched replay outside every broker lock
        replay = [SubscriptionMessage(id=s.id, selector=s.selector,
                                      follow=s.follow) for s in subs]
        if replay:
            ch._offer_many(replay)
        return ch

    def stop_listening(self, node_id: str):
        sh = self._shards[stable_shard(node_id, self.shards)]
        with sh.lock:
            ch = sh.listeners.pop(node_id, None)
        with self._lock:
            for sub in list(self._subs.values()):
                if node_id in sub.pending_nodes:
                    self._mark_done(
                        sub, node_id,
                        f"node {node_id} disconnected unexpectedly")
        if ch is not None:
            ch.close()

    def publish_logs(self, sub_id: str, messages, node_id: str = "",
                     close: bool = False, error: str = ""):
        """The publish HOT PATH: a lock-free registry read (GIL-atomic
        dict get — no broker or shard lock is ever held here) and ONE
        offer burst into the client channel's own cond. Disarmed
        telemetry costs exactly one truthiness test; the armed recorder
        is the only allocation site."""
        sub = self._subs.get(sub_id)
        if sub is None or sub.done:
            return
        if messages:
            delivered, shed = sub.client.offer_batch(list(messages))
            if telemetry.enabled():
                self._record_publish(messages, delivered, shed)
        if close:
            with self._lock:
                if self._subs.get(sub_id) is sub and not sub.done:
                    self._mark_done(sub, node_id, error)

    def _record_publish(self, messages, delivered: int, shed: int):
        """Armed-only (telemetry.enabled() guarded at every call site):
        fold the batch into the swarm_logbroker_* families, attributed
        to the publishing node's shard."""
        nid = messages[0].context.node_id if messages else ""
        lbl = (str(stable_shard(nid, self.shards)),)
        _PUBLISHED.inc(lbl, len(messages))
        if delivered:
            _DELIVERED.inc(lbl, delivered)
        if shed:
            _SHED.inc(lbl, shed)
        _LAG.observe(lbl, max(0.0,
                              self.clock.time() - messages[-1].timestamp))

    # -- completion plane (global lock held by callers) --------------------

    def _maybe_complete(self, sub: _Subscription):
        if sub.follow or sub.done or sub.pending_nodes:
            return
        sub.done = True
        self._subs.pop(sub.id, None)
        self._retire(sub)
        # control record bypasses the data bound (and never sheds); it
        # still trails any pending loss marker
        sub.client.offer_control(SubscriptionComplete(error=sub.err_text()))
        sub.client.close()
        self._bag.inc("subscriptions_completed")

    def _retire(self, sub: _Subscription):
        """Fold a finished subscription's channel accounting into the
        broker totals so metrics survive the subscription."""
        ch = sub.client
        if getattr(sub, "_retired", False) or not isinstance(ch, ShedChannel):
            return
        sub._retired = True
        with ch._cond:
            pub, dlv, shd, win = (ch.published, ch.delivered, ch.shed,
                                  ch.shed_windows)
        self._bag.inc("published", pub)
        self._bag.inc("delivered", dlv)
        self._bag.inc("shed", shd)
        self._bag.inc("shed_windows", win)

    # -- dispatch fan-out (shard pumps) ------------------------------------

    def _dispatch_to_nodes(self, sub: _Subscription,
                           force_nodes: set[str] = frozenset()):
        """Same match + accounting as the oracle (synchronous, under the
        global lock), but the listener offers ride the owning shards'
        pumps — the fan-out never runs under the registry lock."""
        tasks = self.store.view(
            lambda tx: self._match_tasks(tx, sub.selector))
        msg = SubscriptionMessage(id=sub.id, selector=sub.selector,
                                  follow=sub.follow)
        offers = []
        with self._lock:
            notify: set[str] = set(force_nodes)
            for t in tasks:
                if not t.node_id:
                    continue
                if t.node_id not in sub.nodes \
                        or t.id not in sub.known_tasks:
                    notify.add(t.node_id)
            sub.nodes |= notify
            sub.known_tasks = {t.id for t in tasks if t.node_id}
            sub.pending_tasks = {t.id for t in tasks if not t.node_id}
            for n in notify:
                # pinned order: logbroker.lock → logbroker.shard<i>.lock
                sh = self._shards[stable_shard(n, self.shards)]
                with sh.lock:
                    ch = sh.listeners.get(n)
                alive = ch is not None and not ch.closed
                if alive:
                    offers.append((n, ch))
                    if not sub.follow and n not in sub.done_nodes:
                        sub.pending_nodes.add(n)
                elif not sub.follow and n not in sub.done_nodes:
                    sub.errors.append(f"node {n} is not available")
                    sub.done_nodes.add(n)
        self._submit_offers(offers, msg)

    def _submit_offers(self, offers, msg):
        """Route (node, channel) offers to the owning shards' pumps; an
        unstarted/stopped broker serves them inline (driven tests)."""
        if not offers:
            return
        if not self._running:
            self._do_offers(offers, msg)
            return
        by_shard: dict[int, list] = {}
        for n, ch in offers:
            by_shard.setdefault(stable_shard(n, self.shards),
                                []).append((n, ch))
        for idx, items in by_shard.items():
            sh = self._shards[idx]
            sh.pending.append((msg, items))   # lock-free inbox append
            sh.wake.set()

    def _do_offers(self, items, msg):
        """Offer OUTSIDE every broker lock. A refused offer (closed or
        overflowed listener channel) is a dead agent stream: account the
        disconnect like the sweep would — the agent's re-listen replay
        heals the subscription (dup closes are ignored by _mark_done)."""
        for n, ch in items:
            if ch._offer(msg):
                self._bag.inc("dispatch_offers")
            else:
                self._note_listener_dead(n, ch)

    def _note_listener_dead(self, node_id: str, ch: Channel):
        sh = self._shards[stable_shard(node_id, self.shards)]
        with sh.lock:
            if sh.listeners.get(node_id) is ch:
                del sh.listeners[node_id]
        with self._lock:
            for sub in list(self._subs.values()):
                if node_id in sub.pending_nodes:
                    self._mark_done(
                        sub, node_id,
                        f"node {node_id} disconnected unexpectedly")
        self._bag.inc("listener_disconnects")
        ch.close()

    # -- pumps + sweeps ----------------------------------------------------

    def _pump_loop(self, sh: _Shard):
        while not self._stop.is_set():
            self.clock.wait(sh.wake, timeout=self.SWEEP_INTERVAL)
            sh.wake.clear()
            self._drain_shard(sh)
            self._sweep_shard(sh)

    def _drain_shard(self, sh: _Shard):
        """FIFO drain of the shard's inbox; offers run outside all broker
        locks (the channels' own conds are leaves)."""
        n = 0
        while sh.pending:
            try:
                msg, items = sh.pending.popleft()
            except IndexError:
                break
            self._do_offers(items, msg)
            n += 1
        if n:
            self._bag.inc("pump_jobs", n)
        self._bag[f"pump_depth_shard{sh.index}"] = len(sh.pending)

    def _sweep_shard(self, sh: _Shard):
        """A shard sweeps ITS listeners; dead ones feed the same
        disconnect accounting as stop_listening. Collected under the
        shard lock, accounted after it is released (never nest shard →
        global)."""
        with sh.lock:
            dead = [(n, ch) for n, ch in sh.listeners.items() if ch.closed]
        for n, ch in dead:
            self._note_listener_dead(n, ch)

    def _sweep(self):
        """The watcher thread's sweep: gone log CLIENTS unsubscribe
        (listener sweeps live on the shard pumps)."""
        with self._lock:
            gone = [s.id for s in self._subs.values()
                    if s.client.closed and not s.done]
        for sid in gone:
            self.unsubscribe(sid)

    # -- observability -----------------------------------------------------

    def metrics_snapshot(self) -> dict:
        """Always-on counter surface for /metrics, /debug/cluster and the
        telemetry rollup's `logbroker` block: retired totals + live
        subscription accounting + plane gauges. Never touched by the
        publish hot path."""
        out = {k: v for k, v in self._bag.items()}
        with self._lock:
            live = list(self._subs.values())
            pending = len(live)
        for sub in live:
            ch = sub.client
            if not isinstance(ch, ShedChannel):
                continue
            with ch._cond:
                out["published"] += ch.published
                out["delivered"] += ch.delivered
                out["shed"] += ch.shed
                out["shed_windows"] += ch.shed_windows
        out["pending_subscriptions"] = pending
        out["listeners"] = sum(len(sh.listeners) for sh in self._shards)
        return out


def make_log_broker(store, shards: int | None = None, clock=None):
    """The production constructor: the sharded plane unless the kill
    switch (SWARMKIT_TPU_NO_SHARDED_LOGS=1) selects the single-plane
    oracle."""
    if os.environ.get("SWARMKIT_TPU_NO_SHARDED_LOGS", ""):
        return LogBroker(store, clock=clock)
    return ShardedLogBroker(store, shards=shards, clock=clock)
