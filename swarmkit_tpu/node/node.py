"""Node bootstrap: joint worker/manager runtime.

Re-derivation of node/node.go:286-533: a Node loads or obtains its TLS
identity (local state dir, or CSR against the cluster CA using a join
token), always runs an agent, runs an embedded manager while its role is
manager, renews its certificate, and persists identity across restarts so a
restarted node comes back as itself.

In-process topology: `join` is a handle to an existing Manager (the
reference dials a remote address; the wire layer rides the same seams).
The role watcher mirrors node.go's role-change flow (agent session node
updates → manager start/stop): it observes the node's desired role and
flips the embedded manager.
"""
from __future__ import annotations

import json
import os
import threading

from ..analysis.lockgraph import make_lock
from ..agent.agent import Agent
from ..api.types import IssuanceState, NodeRole, NodeStatusState
from ..ca import (
    KeyReadWriter,
    RootCA,
    SecurityConfig,
    TLSRenewer,
    create_csr,
)
from ..ca.auth import Caller
from ..manager.manager import Manager
from ..remotes import ConnectionBroker, Remotes
from ..utils.identity import new_id

STATE_FILE = "state.json"
CERT_FILE = "cert.pem"
CA_FILE = "ca.pem"
KEY_FILE = "key.json"


class NodeError(Exception):
    pass


class Node:
    """node/node.go Node: security bootstrap + agent + optional manager."""

    def __init__(
        self,
        state_dir: str,
        executor,
        join: Manager | None = None,
        join_token: str | None = None,
        org: str = "swarmkit-tpu",
        kek: bytes | None = None,
        heartbeat_period: float = 5.0,
        role_check_interval: float = 0.2,
        fips: bool = False,
    ):
        self.state_dir = state_dir
        self.executor = executor
        self.fips = fips
        self.join = join
        self.join_token = join_token
        self.org = org
        self.kek = kek
        self.heartbeat_period = heartbeat_period
        self.role_check_interval = role_check_interval

        self.security: SecurityConfig | None = None
        self.agent: Agent | None = None
        self.manager: Manager | None = None
        self.renewer: TLSRenewer | None = None
        self.broker = ConnectionBroker(Remotes())
        self._stop = threading.Event()
        self._role_thread: threading.Thread | None = None
        self._manager_lock = make_lock('node.node.manager_lock')

    # -- identity persistence (node.go:1202-1286 state.json + cert dir) ----

    def _paths(self):
        return (
            os.path.join(self.state_dir, STATE_FILE),
            os.path.join(self.state_dir, CERT_FILE),
            os.path.join(self.state_dir, CA_FILE),
            os.path.join(self.state_dir, KEY_FILE),
        )

    def _save_identity(self):
        state_path, cert_path, ca_path, key_path = self._paths()
        os.makedirs(self.state_dir, exist_ok=True)
        key_pem, cert_pem = self.security.key_and_cert()
        KeyReadWriter(key_path, self.kek).write(key_pem)
        with open(cert_path, "wb") as f:
            f.write(cert_pem)
        with open(ca_path, "wb") as f:
            f.write(self.security.root_ca.cert_pem)
        with open(state_path, "w") as f:
            json.dump({"node_id": self.security.node_id()}, f)

    def _load_identity(self) -> SecurityConfig | None:
        """node.go loadSecurityConfig:799-910 — reuse local certs if present."""
        state_path, cert_path, ca_path, key_path = self._paths()
        if not (os.path.exists(cert_path) and os.path.exists(key_path)):
            return None
        with open(ca_path, "rb") as f:
            root = RootCA(f.read())
        with open(cert_path, "rb") as f:
            cert_pem = f.read()
        key_pem, _headers = KeyReadWriter(key_path, self.kek).read()
        return SecurityConfig(root, key_pem, cert_pem)

    # -- bootstrap ---------------------------------------------------------

    def _obtain_identity(self) -> SecurityConfig:
        loaded = self._load_identity()
        if loaded is not None:
            return loaded
        if self.join is None:
            # first node: create the cluster (manager, self-signed root)
            return SecurityConfig.bootstrap_manager(org=self.org)
        if not self.join_token:
            raise NodeError("joining an existing cluster requires a join token")
        # CSR flow against the remote CA (ca/certificates.go
        # RequestAndSaveNewCertificates → NodeCA.IssueNodeCertificate)
        node_id = new_id()
        key_pem, csr_pem = create_csr(node_id, NodeRole.WORKER, self.org)
        ca = self.join.ca_server
        node_id = ca.issue_node_certificate(csr_pem, token=self.join_token, node_id=node_id)
        cert = ca.node_certificate_status(node_id, timeout=30)
        if cert is None or cert.status_state != IssuanceState.ISSUED:
            raise NodeError(
                f"certificate issuance failed: {getattr(cert, 'status_err', 'timeout')}"
            )
        root = RootCA(ca.get_root_ca_certificate())  # trust anchor only
        return SecurityConfig(root, key_pem, cert.certificate_pem)

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        self.security = self._obtain_identity()
        self._save_identity()

        if self.join is None and self.manager is None:
            self._start_manager_bootstrap()

        target = self.manager if self.join is None else self.join
        self.broker.remotes.add(target)
        if self.manager is not None:
            self.broker.set_local_peer(self.manager)

        self.agent = Agent(
            self.security.node_id(),
            target.dispatcher,
            self.executor,
            log_broker=target.log_broker,
            fips=self.fips,
        )
        self.agent.start()

        self.renewer = TLSRenewer(self.security, target.ca_server)
        self.renewer.start()

        if self.join is not None:
            self._role_thread = threading.Thread(
                target=self._watch_role, name="role-watcher", daemon=True
            )
            self._role_thread.start()

    def stop(self):
        self._stop.set()
        if self.renewer is not None:
            self.renewer.stop()
        if self.agent is not None:
            self.agent.stop()
        with self._manager_lock:
            mgr, self.manager = self.manager, None
        if mgr is not None:
            mgr.stop()
        if self._role_thread is not None:
            self._role_thread.join(timeout=5)

    @property
    def node_id(self) -> str:
        return self.security.node_id() if self.security else ""

    @property
    def role(self) -> int:
        return self.security.role() if self.security else NodeRole.WORKER

    # -- embedded manager --------------------------------------------------

    def _start_manager_bootstrap(self):
        """First-manager path (node.go runManager:983 on a fresh cluster):
        embedded manager using this node's root, self registered READY."""
        mgr = Manager(
            security=self.security,
            org=self.org,
            heartbeat_period=self.heartbeat_period,
        )
        mgr.start()
        # register ourselves in the cluster state
        from ..api.objects import ManagerStatus, Node as NodeObj, NodeCertificate
        from ..api.specs import NodeSpec

        node_id = self.security.node_id()

        def txn(tx):
            if tx.get_node(node_id) is None:
                n = NodeObj(
                    id=node_id,
                    spec=NodeSpec(desired_role=NodeRole.MANAGER),
                    role=NodeRole.MANAGER,
                )
                n.status.state = NodeStatusState.READY
                n.manager_status = ManagerStatus(leader=True)
                n.certificate = NodeCertificate(
                    role=NodeRole.MANAGER,
                    status_state=IssuanceState.ISSUED,
                    certificate_pem=self.security.key_and_cert()[1],
                    cn=node_id,
                )
                tx.create(n)

        mgr.store.update(txn)
        with self._manager_lock:
            self.manager = mgr

    def _watch_role(self):
        """Poll the cluster's view of this node's desired role and start or
        stop the embedded manager (node.go superviseManager:1099-1194; the
        reference receives role changes via its agent session — the store
        poll is the in-process analogue of that notification path)."""
        node_id = self.security.node_id()
        while not self._stop.wait(timeout=self.role_check_interval):
            try:
                obj = self.join.store.view(lambda tx: tx.get_node(node_id))
            except Exception:
                continue
            if obj is None:
                continue
            desired = obj.spec.desired_role
            with self._manager_lock:
                has_manager = self.manager is not None
            if desired == NodeRole.MANAGER and not has_manager:
                # request a manager cert, then run the manager when issued
                try:
                    if self.renewer is not None:
                        self.renewer.renew_once()
                except Exception:
                    continue
                if self.security.role() == NodeRole.MANAGER:
                    # joined managers share the leader's replicated state
                    # through raft; the in-process embedded manager rides the
                    # same store object (the wire/raft deployment gives each
                    # its own replica — node/README parity note)
                    mgr = Manager(
                        store=self.join.store,
                        security=self.security,
                        cluster_id=self.join.cluster_id,
                        org=self.org,
                        heartbeat_period=self.heartbeat_period,
                    )
                    # not the leader: components stay down until elected
                    with self._manager_lock:
                        self.manager = mgr
                    self.broker.set_local_peer(mgr)
            elif desired == NodeRole.WORKER and has_manager:
                with self._manager_lock:
                    mgr, self.manager = self.manager, None
                self.broker.set_local_peer(None)
                if mgr is not None and mgr is not self.join:
                    mgr.stop()
