"""Node bootstrap (reference: node/, SURVEY.md §2.9)."""
from .node import Node, NodeError

__all__ = ["Node", "NodeError"]
