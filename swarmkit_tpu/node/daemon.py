"""SwarmNode: the networked daemon assembly — one OS process per node.

This is the process the reference calls swarmd (swarmd/cmd/swarmd/main.go +
node/node.go): bootstrap a TLS identity (local state dir, or a digest-pinned
CSR against a remote manager using a join token), then run the role's stack
over real TCP:

  manager:  RPC server (all planes on one mTLS listener, manager.go:441-641)
            + raft node on the network transport (joins the quorum via the
            RaftMembership.Join RPC, raft.go:926) + replicated store +
            Manager component lifecycle + an agent (managers run workloads
            too, node/node.go runAgent:576) + cert renewal.
  worker:   agent with a RemoteDispatcher session against the managers +
            cert renewal; periodically refreshes the manager list
            (the Session message manager-list plane).

State dir layout (node/node.go:1202-1286 + manager/deks.go):
    state.json   node id, raft id, advertise addr
    cert.pem / ca.pem / key.json     TLS identity (KEK-sealable)
    raft/        encrypted WAL + snapshots (DEK in key.json headers)
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import random
import ssl
import tempfile
import threading
import time

from ..analysis.lockgraph import make_lock
from ..agent.agent import Agent
from ..api.types import IssuanceState, NodeRole, NodeStatusState
from ..ca import (
    KeyReadWriter,
    RootCA,
    SecurityConfig,
    TLSRenewer,
    create_csr,
    parse_join_token,
)
from ..manager.manager import Manager
from ..raft.node import SNAPSHOT_RESEND_TICKS, Peer, RaftNode
from ..raft.proposer import RaftProposer
from ..raft.storage import RaftStorage, new_dek
from ..raft.transport import NetworkTransport
from ..rpc.client import RPCClient
from ..rpc.server import RPCServer, ServiceRegistry
from ..rpc.services import (
    LeaderConns,
    RemoteCA,
    RemoteDispatcher,
    RemoteLogBroker,
    build_manager_registry,
)
from ..rpc.wire import connect_tls, parse_addr
from ..store.memory import MemoryStore
from ..utils.identity import new_id

log = logging.getLogger("swarmkit_tpu.daemon")

STATE_FILE = "state.json"
CERT_FILE = "cert.pem"
CA_FILE = "ca.pem"
KEY_FILE = "key.json"
DEK_HEADER = "raft-dek"

JOIN_RETRY = 0.5
JOIN_TIMEOUT = 30.0
ANNOUNCE_RETRY = 0.5


class NodeError(Exception):
    pass


class TrustPinMismatch(NodeError):
    """The fetched root CA does not match the join token's digest pin —
    never retried (it is an attack or a wrong token, not a flake)."""


def fetch_root_cert(addr: str, expected_digest: str,
                    timeout: float = 10.0) -> bytes:
    """Download the cluster root CA over an *unauthenticated* TLS connection
    and verify it against the digest pinned in the join token — the trust
    bootstrap of ca/certificates.go GetRemoteCA (connection is untrusted;
    the token's sha256 pin is the root of trust)."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.check_hostname = False
    ctx.verify_mode = ssl.CERT_NONE  # trust comes from the digest pin below
    sock = connect_tls(addr, ctx, timeout=timeout)
    try:
        from ..rpc.wire import REQ, RESP, recv_frame, send_frame

        lock = make_lock('node.daemon.lock')
        send_frame(sock, lock,
                   [REQ, 1, "ca.get_root_ca_certificate", ((), {})])
        ftype, _sid, head, payload = recv_frame(sock)
        if ftype != RESP:
            raise NodeError(f"root CA fetch failed: {head}: {payload}")
    finally:
        sock.close()
    cert_pem = payload
    if hashlib.sha256(cert_pem).hexdigest() == expected_digest:
        return cert_pem
    # Mid-rotation the server publishes a multi-anchor bundle (old root,
    # new root, cross-signed intermediate); a token minted before the
    # rotation pins one member. The pin only extends to OTHER members that
    # the pinned anchor actually vouches for: a member is accepted iff it is
    # directly issued by an accepted member, or an accepted member issued a
    # cross-signature for its exact (subject, public key). Anything else in
    # the bundle (e.g. an attacker-appended root on the join path) rejects
    # the whole download.
    try:
        from cryptography import x509 as _x509
        from cryptography.hazmat.primitives.serialization import (
            Encoding, PublicFormat)

        blocks = [b"-----BEGIN CERTIFICATE-----" + part
                  for part in cert_pem.split(b"-----BEGIN CERTIFICATE-----")
                  if part.strip()]
        certs = [_x509.load_pem_x509_certificates(b)[0] for b in blocks]

        def spki(c):
            return c.public_key().public_bytes(
                Encoding.DER, PublicFormat.SubjectPublicKeyInfo)

        def issued_by(child, parent) -> bool:
            try:
                child.verify_directly_issued_by(parent)
                return True
            except Exception:
                return False

        accepted = {i for i, b in enumerate(blocks)
                    if hashlib.sha256(b).hexdigest() == expected_digest}
        changed = bool(accepted)
        while changed:
            changed = False
            for i, c in enumerate(certs):
                if i in accepted:
                    continue
                for j in accepted:
                    if issued_by(c, certs[j]):
                        accepted.add(i)
                        changed = True
                        break
                    # cross-signature vouching: an accepted anchor issued a
                    # cert for this exact subject+key elsewhere in the bundle
                    if any(issued_by(certs[k], certs[j])
                           and certs[k].subject == c.subject
                           and spki(certs[k]) == spki(c)
                           for k in range(len(certs)) if k != i):
                        accepted.add(i)
                        changed = True
                        break
        if accepted and len(accepted) == len(certs):
            return cert_pem
    except TrustPinMismatch:
        raise
    except Exception:
        pass
    raise TrustPinMismatch(
        "remote root CA bundle does not match the join token pin "
        f"{expected_digest[:16]}… (or contains unvouched anchors) — "
        "refusing to join")


class _Ticker(threading.Thread):
    """Drives the raft logical clock in real time (the reference's
    clock.NewClock ticker, raft.go:540 tick arm)."""

    def __init__(self, raft: RaftNode, interval: float, clock=None,
                 catch_up_cap: int = 9):
        super().__init__(daemon=True, name=f"raft-tick-{raft.id}")
        self.raft = raft
        self.interval = interval
        from ..utils.clock import REAL_CLOCK

        self.clock = clock or REAL_CLOCK
        # a starved thread fires the ticks wall time owed it, so logical
        # election time tracks wall time (the round-2 flake mechanism:
        # lost ticks under load → elections missing their windows). The
        # cap stays BELOW election_tick: one burst alone can never
        # campaign past a live leader whose queued heartbeats interleave
        # in the raft inbox, and bounds the avalanche after a suspend.
        self.catch_up_cap = max(1, catch_up_cap)
        # NOT named _stop: threading.Thread.join() calls an internal
        # self._stop() method, which an Event attribute would shadow
        self._stop_ev = threading.Event()

    def run(self):
        clock = self.clock
        next_t = clock.monotonic() + self.interval
        while not clock.wait(self._stop_ev,
                             max(0.0, next_t - clock.monotonic())):
            now = clock.monotonic()
            owed = 1 + int(max(0.0, now - next_t) / self.interval)
            n = min(owed, self.catch_up_cap)
            for _ in range(n):
                self.raft.tick()
            next_t = max(next_t + owed * self.interval,
                         now + self.interval / 2)

    def stop(self):
        self._stop_ev.set()


class SwarmNode:
    """One daemon process: identity + role stack over the network."""

    def __init__(
        self,
        state_dir: str,
        executor,
        listen_addr: str = "127.0.0.1:0",
        advertise_addr: str | None = None,
        join_addr: str | None = None,
        join_token: str | None = None,
        org: str = "swarmkit-tpu",
        kek: bytes | None = None,
        heartbeat_period: float = 5.0,
        tick_interval: float = 0.1,
        election_tick: int = 10,
        manager_refresh_interval: float = 5.0,
        force_new_cluster: bool = False,
        control_socket: bool = True,
        cert_expiry: float | None = None,
        external_ca=None,
        generic_resources=None,  # {kind: count} or api Resources
        autolock: bool = False,
        fips: bool = False,
        csi_plugins=None,  # csi.plugin.PluginGetter (e.g. RemoteCSIPlugin)
        scheduler_backend: str = "auto",
        jax_threshold: int | None = None,
        scheduler_pipeline: bool = False,
        scheduler_async_commit: bool = False,
        scheduler_strategy: str = "spread",
        scheduler_topology: str | None = None,
        dispatcher_shards: int | None = None,
        clock=None,
    ):
        self.state_dir = state_dir
        self.executor = executor
        self.listen_addr = listen_addr
        self.advertise_addr = advertise_addr
        self._user_advertise = advertise_addr  # operator-pinned, if any
        self.join_addr = join_addr
        self.join_token = join_token
        self.org = org
        self.kek = kek
        self.heartbeat_period = heartbeat_period
        self.tick_interval = tick_interval
        self.election_tick = election_tick
        self.manager_refresh_interval = manager_refresh_interval
        self.force_new_cluster = force_new_cluster
        self.control_socket = control_socket
        self.control_socket_path: str | None = None
        self.cert_expiry = cert_expiry
        self.external_ca = external_ca
        self.generic_resources = generic_resources
        self.autolock = autolock
        self.fips = fips
        self.csi_plugins = csi_plugins
        self.scheduler_backend = scheduler_backend
        self.jax_threshold = jax_threshold
        self.scheduler_pipeline = scheduler_pipeline
        self.scheduler_async_commit = scheduler_async_commit
        self.scheduler_strategy = scheduler_strategy
        self.scheduler_topology = scheduler_topology
        self.dispatcher_shards = dispatcher_shards
        from ..utils.clock import REAL_CLOCK
        self.clock = clock or REAL_CLOCK
        self._identity_lock = make_lock('node.daemon.identity_lock')
        self._control_server: RPCServer | None = None

        self.security: SecurityConfig | None = None
        self.manager: Manager | None = None
        self.raft: RaftNode | None = None
        self.store: MemoryStore | None = None
        self.server: RPCServer | None = None
        self.agent: Agent | None = None
        self.renewer: TLSRenewer | None = None
        self.raft_id: int | None = None

        self._transport: NetworkTransport | None = None
        self._follower_reads = None
        self._ticker: _Ticker | None = None
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._dispatcher_shim: RemoteDispatcher | None = None
        self._manager_addrs: list[str] = []
        self._role_flip_active = False
        self._role_flip_lock = make_lock('node.daemon.role_flip_lock')
        self._last_session_msg = None
        self._root_renew_active = False
        # state.json is read-merge-written from several threads (promote
        # flips, session plane, refresh loop) — serialize the cycle or a
        # managers write could clobber a just-persisted raft_id
        self._state_lock = make_lock('node.daemon.state_lock')

    # ------------------------------------------------------------- identity

    def _paths(self):
        return (os.path.join(self.state_dir, STATE_FILE),
                os.path.join(self.state_dir, CERT_FILE),
                os.path.join(self.state_dir, CA_FILE),
                os.path.join(self.state_dir, KEY_FILE))

    def _load_state(self) -> dict:
        state_path = self._paths()[0]
        if not os.path.exists(state_path):
            return {}
        with open(state_path) as f:
            return json.load(f)

    def _save_state(self, **updates):
        with self._state_lock:
            state_path = self._paths()[0]
            os.makedirs(self.state_dir, exist_ok=True)
            state = self._load_state()
            state.update(updates)
            # unique temp + atomic rename, like the identity writes (the
            # round-3 de-flake): a restarted node briefly overlaps its
            # predecessor's draining threads on the SAME state dir, and a
            # shared fixed ".tmp" name let two writers interleave
            fd, tmp = tempfile.mkstemp(prefix=".state-",
                                       dir=self.state_dir)
            try:
                with os.fdopen(fd, "w") as f:
                    json.dump(state, f)
                os.replace(tmp, state_path)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise

    def _watch_kek_loop(self) -> None:
        """manager.go updateKEK (:743): when the replicated unlock key
        rotates (controlapi rotate_unlock_key) — or autolock is enabled
        cluster-wide — every manager re-seals its local key material under
        the new KEK so a restart unlocks with the CURRENT key."""
        store = self.store
        if store is None:
            return
        from ..api.objects import Cluster as ClusterObj
        from ..store.watch import ChannelClosed

        queue = store.watch_queue()
        ch = queue.watch()
        try:
            while not self._stop.is_set():
                try:
                    ev = ch.get(timeout=0.5)
                except TimeoutError:
                    continue
                except ChannelClosed:
                    return
                obj = getattr(ev, "obj", None)
                if not isinstance(obj, ClusterObj):
                    continue
                if not obj.spec.encryption.auto_lock_managers \
                        or not obj.unlock_keys:
                    continue
                new = obj.unlock_keys[0]
                if isinstance(new, str):
                    new = new.encode()
                if new == self.kek:
                    continue
                self.kek = new
                if self.manager is not None:
                    self.manager.autolock_key = new
                try:
                    self._save_identity()
                    log.info("re-sealed key material under rotated "
                             "unlock key")
                except Exception:
                    log.exception("KEK rotation re-seal failed")
        finally:
            queue.stop_watch(ch)

    def _persist_managers(self, addrs: list[str]) -> None:
        """persistentRemotes (node/node.go:1202-1286): remember the live
        manager list so a restarted worker reconnects without a join
        address. Written only on change."""
        if not addrs:
            return
        addrs = sorted(addrs)
        if addrs != getattr(self, "_persisted_managers", None):
            self._persisted_managers = addrs
            try:
                self._save_state(managers=addrs)
            except OSError:
                pass

    def _save_identity(self):
        # one writer at a time: cert renewal and root-rotation updates
        # both re-save the identity concurrently (the security watch fires
        # from either thread); interleaved writes corrupted key.json tmp
        # files under load (round-3 de-flake)
        with self._identity_lock:
            _state, cert_path, ca_path, key_path = self._paths()
            os.makedirs(self.state_dir, exist_ok=True)
            key_pem, cert_pem = self.security.key_and_cert()
            KeyReadWriter(key_path, self.kek).write(key_pem)
            for path, data in ((cert_path, cert_pem),
                               (ca_path, self.security.root_ca.cert_pem)):
                tmp = f"{path}.{threading.get_ident()}.tmp"
                with open(tmp, "wb") as f:
                    f.write(data)
                os.replace(tmp, path)
            self._save_state(node_id=self.security.node_id())

    def _load_identity(self) -> SecurityConfig | None:
        _state, cert_path, _ca_path, key_path = self._paths()
        if not (os.path.exists(cert_path) and os.path.exists(key_path)):
            return None
        return SecurityConfig.load_from_dir(self.state_dir, self.kek)

    def _obtain_identity(self) -> SecurityConfig:
        loaded = self._load_identity()
        if loaded is not None:
            return loaded
        if self.join_addr is None:
            # first node of a new cluster: self-signed root, manager cert
            return SecurityConfig.bootstrap_manager(org=self.org)
        if not self.join_token:
            raise NodeError("joining an existing cluster requires a join token")
        parsed = parse_join_token(self.join_token)
        seeds = [a.strip() for a in self.join_addr.split(",") if a.strip()]
        root_pem = None
        node_id = new_id()
        key_pem = csr_pem = None
        # the CSR flow must survive transient cluster states — an election
        # in flight, a follower that doesn't know the leader yet
        # (ca/certificates.go GetRemoteSignedCertificate retries w/ backoff)
        deadline = time.monotonic() + JOIN_TIMEOUT * 2
        last: Exception | None = None
        while time.monotonic() < deadline:
            # A server token/identity REJECTION is replicated state, not a
            # transient — retrying the same seed just burns the whole join
            # window before surfacing the same answer. But one manager's
            # verdict can be stale (a deposed leader whose cluster object
            # still holds pre-rotation tokens), so the rejection becomes
            # final only when no seed gave a NON-rejection response this
            # pass: unreachable seeds don't vote, any seed that answered
            # differently (issued, pending, timed out server-side) keeps
            # the retry loop alive to reach the real leader.
            rejections = responses = 0
            reject_err: Exception | None = None
            for seed in seeds:
                try:
                    if root_pem is None:
                        root_pem = fetch_root_cert(seed, parsed.root_digest)
                    if csr_pem is None:
                        key_pem, csr_pem = create_csr(
                            node_id, NodeRole.WORKER, self.org)
                    ca = RemoteCA(seed, root_cert_pem=root_pem)
                    try:
                        node_id = ca.issue_node_certificate(
                            csr_pem, token=self.join_token, node_id=node_id)
                        cert = ca.node_certificate_status(node_id, timeout=30)
                    finally:
                        ca.close()
                    if cert is not None and \
                            cert.status_state == IssuanceState.ISSUED:
                        return SecurityConfig(RootCA(root_pem), key_pem,
                                              cert.certificate_pem)
                    responses += 1
                    last = NodeError(
                        "issuance failed: "
                        f"{getattr(cert, 'status_err', 'timeout')}")
                except TrustPinMismatch:
                    raise  # never retry a trust failure
                except Exception as exc:
                    from ..ca.auth import PermissionDenied
                    from ..ca.config import InvalidToken
                    from ..rpc.wire import RPCError

                    # the wire layer maps known error names back to their
                    # real classes, so check both forms
                    rejected = isinstance(
                        exc, (InvalidToken, PermissionDenied)) or (
                        isinstance(exc, RPCError) and exc.name in (
                            "InvalidToken", "PermissionDenied"))
                    if rejected:
                        rejections += 1
                        responses += 1
                        reject_err = NodeError(f"join rejected: {exc}")
                        reject_err.__cause__ = exc
                        continue
                    if isinstance(exc, RPCError):
                        # the seed ANSWERED with a non-rejection error
                        # (e.g. NotLeaderError mid-election) — that vote
                        # keeps the retry loop alive; only connection-level
                        # failures and timeouts are non-voting
                        responses += 1
                    last = exc
            if rejections and rejections == responses:
                raise reject_err
            if reject_err is not None:
                # keep the actionable verdict visible even if a later
                # seed's transient error arrived after it
                last = reject_err
            if self._stop.wait(JOIN_RETRY):
                break
        raise NodeError(f"certificate issuance failed: {last}")

    # ------------------------------------------------------------ lifecycle

    class MandatoryFIPSError(Exception):
        """node.go ErrMandatoryFIPS: the cluster mandates FIPS but this
        node is not FIPS-enabled."""

    FIPS_MARKER = "fips-cluster"

    def _check_fips(self):
        """Mandatory-FIPS enforcement (reference node.go:59-60, 781-797 +
        integration TestMixedFIPSClusterMandatoryFIPS): a join token
        carrying the FIPS bit refuses non-FIPS joiners, and a node that
        ever joined a mandatory-FIPS cluster refuses to RESTART in
        non-FIPS mode (the marker persists in the state dir, the analogue
        of the reference's FIPS.-prefixed cluster id in the cert org).
        Non-mandatory clusters accept any mix of FIPS/non-FIPS nodes.
        Returns whether MEMBERSHIP in a mandatory cluster should be
        recorded once this start's identity is actually established —
        branding a state dir on a join that then fails would poison its
        reuse (_mark_fips_membership runs post-identity)."""
        import os as _os

        marker = _os.path.join(self.state_dir, self.FIPS_MARKER)
        token_mandates = False
        if self.join_token is not None:
            try:
                from ..ca.config import parse_join_token

                token_mandates = parse_join_token(self.join_token).fips
            except Exception:
                pass  # malformed tokens fail later with a clearer error
        mandated = token_mandates or _os.path.exists(marker)
        if mandated and not self.fips:
            raise self.MandatoryFIPSError(
                "node is not FIPS-enabled but cluster requires FIPS")
        # membership gets recorded when this start makes the cluster
        # mandatory (fresh FIPS bootstrap) or joins one; a FIPS-enabled
        # node in a NON-mandatory cluster stays unbranded
        fresh = not _os.path.exists(self._paths()[1])   # no cert on disk
        bootstrap_fips = self.fips and self.join_addr is None and fresh
        return token_mandates or bootstrap_fips

    def _mark_fips_membership(self):
        import os as _os

        marker = _os.path.join(self.state_dir, self.FIPS_MARKER)
        if not _os.path.exists(marker):
            _os.makedirs(self.state_dir, exist_ok=True)
            with open(marker, "w") as f:
                f.write("this node belongs to a mandatory-FIPS cluster\n")

    def start(self):
        fips_member = self._check_fips()
        if self.autolock and self.kek is None:
            # autolock without an operator-provided key: mint one; swarmd
            # prints it as SWARM_UNLOCK_KEY (docker's --autolock UX)
            import secrets

            self.kek = secrets.token_hex(16).encode()
        self.security = self._obtain_identity()
        if fips_member:
            # identity established: NOW the mandatory membership is real
            self._mark_fips_membership()
        self._save_identity()
        # renewed certs / rotated roots must survive a restart: persist on
        # every credential swap (ca/certificates.go
        # RequestAndSaveNewCertificates — "AndSave" is load-bearing)
        self.security.watch(lambda _sec: self._save_identity())
        if self.security.role() == NodeRole.MANAGER:
            self._start_manager()
        else:
            self._start_worker()

    def stop(self):
        self._stop.set()
        if self.renewer is not None:
            self.renewer.stop()
        if self.agent is not None:
            self.agent.stop()
        if self._dispatcher_shim is not None:
            self._dispatcher_shim.close()
        if self._control_server is not None:
            self._control_server.stop()
            self._control_server = None
        if self.manager is not None:
            self.manager.stop()
        if self._follower_reads is not None:
            self._follower_reads.stop()
            self._follower_reads = None
        if self._ticker is not None:
            self._ticker.stop()
        if self.raft is not None:
            self.raft.stop()
        if self._transport is not None:
            self._transport.stop()
        if self.server is not None:
            self.server.stop()
        for t in self._threads:
            t.join(timeout=2)

    @property
    def node_id(self) -> str:
        return self.security.node_id() if self.security else ""

    @property
    def addr(self) -> str | None:
        return self.server.addr if self.server is not None else None

    @property
    def is_leader(self) -> bool:
        return self.raft is not None and self.raft.is_leader

    # ------------------------------------------------------- manager stack

    def _dek(self) -> bytes:
        """Raft at-rest DEK, persisted in the TLS key file's headers
        (manager/deks.go keeps DEKs in PEM headers of the node key)."""
        krw = KeyReadWriter(self._paths()[3], self.kek)
        key_pem, headers = krw.read()
        dek_hex = (headers or {}).get(DEK_HEADER)
        if dek_hex:
            return dek_hex.encode()
        dek = new_dek()
        headers = dict(headers or {})
        headers[DEK_HEADER] = dek.decode()
        krw.write(key_pem, headers)
        return dek

    def _start_manager(self):
        node_id = self.security.node_id()
        persisted = self._load_state()
        prev_advertise = persisted.get("advertise")

        listen = self.listen_addr
        if self.advertise_addr is None and prev_advertise \
                and listen.endswith(":0"):
            # restart with an ephemeral listen port: rebind the previous
            # port so the quorum's recorded dial address stays valid
            host = listen.rsplit(":", 1)[0]
            prev_port = prev_advertise.rsplit(":", 1)[1]
            try_server = RPCServer(f"{host}:{prev_port}", self.security,
                                   ServiceRegistry())
            try:
                try_server.bind()
                self.server = try_server
                listen = try_server.addr
            except OSError:
                self.server = None  # port taken; fall through to a new one

        registry = ServiceRegistry()
        if self.server is not None:
            self.server.registry = registry
        else:
            self.server = RPCServer(listen, self.security, registry)
        advertise = self.advertise_addr or self.server.bind()
        # normalize a 0.0.0.0 bind into a dialable advertise address
        host, port = parse_addr(advertise)
        if host in ("0.0.0.0", "::"):
            advertise = f"127.0.0.1:{port}"
        self.advertise_addr = advertise

        storage = RaftStorage(os.path.join(self.state_dir, "raft"),
                              dek=self._dek())
        raft_id = persisted.get("raft_id")
        fresh = raft_id is None

        members: list[tuple[int, str, str]] = []
        if fresh:
            if self.join_addr is None:
                raft_id = 1
            else:
                raft_id, members = self._join_raft(node_id, advertise)
        self.raft_id = raft_id
        self._save_state(raft_id=raft_id, advertise=advertise)

        transport = NetworkTransport(self.security, local_raft_id=raft_id)
        raft = RaftNode(
            raft_id=raft_id,
            transport=transport,
            storage=storage,
            election_tick=self.election_tick,
            rng=random.Random(),
            auto_recover=False,
            # read lease (ISSUE 13): the grant must stay BELOW the
            # vote-withholding window (election_tick ticks) that makes
            # it sound; 75% leaves margin for tick-delivery jitter, and
            # the follower discounts a further skew margin on receipt
            lease_duration=self.tick_interval * self.election_tick * 0.75,
            clock=self.clock,
            # snapshot resend deadline in the daemon's own tick units
            # (ISSUE 18): the historical SNAPSHOT_RESEND_TICKS cadence,
            # clock-based so the deadline rides self.clock (FakeClock in
            # the deterministic tiers)
            snapshot_resend_seconds=(self.tick_interval
                                     * SNAPSHOT_RESEND_TICKS),
        )
        transport.set_node(raft)
        self._transport = transport
        self.raft = raft
        # a member that applies its own removal has been demoted by the
        # leader's role manager (role_manager.go removes the raft member
        # first); the removed side cannot learn it from the session plane —
        # its own dispatcher serves a store that stopped replicating
        raft.on_removed = self._on_member_removed

        proposer = RaftProposer(raft)
        self.store = MemoryStore(proposer=proposer)
        proposer.attach_store(self.store)  # replays WAL/snapshot if any

        if fresh:
            if self.join_addr is None:
                raft.bootstrap([Peer(1, node_id, advertise)])
            else:
                peers = [Peer(rid, nid, addr) for rid, nid, addr in members]
                if raft_id not in {p.raft_id for p in peers}:
                    peers.append(Peer(raft_id, node_id, advertise))
                raft.bootstrap(peers)
        elif self.force_new_cluster:
            # disaster recovery (raft.go ForceNewCluster): collapse the
            # membership to this node alone, keeping the replicated state
            raft.members = {raft_id: Peer(raft_id, node_id, advertise)}
            # keep the removed-member set: a member demoted from the OLD
            # quorum must still be answered with the removed marker (and
            # its raft id never reused) after disaster recovery
            storage.save_membership(raft.members, raft.removed_ids)
        elif prev_advertise and prev_advertise != advertise:
            # restarted on a different address than the quorum recorded:
            # re-join through any member so the leader replicates the new
            # dial address (raft_join proposes an idempotent add)
            peer_addrs = [p.addr for p in raft.members.values()
                          if p.raft_id != raft_id and p.addr
                          and not p.addr.startswith("mem://")]
            if peer_addrs:
                t = threading.Thread(
                    target=self._repair_addr_loop,
                    args=(node_id, advertise, peer_addrs),
                    daemon=True, name="raft-addr-repair")
                t.start()
                self._threads.append(t)

        self.manager = Manager(
            store=self.store,
            security=self.security,
            raft_node=raft,
            org=self.org,
            heartbeat_period=self.heartbeat_period,
            external_ca=self.external_ca,
            cert_expiry=self.cert_expiry,
            autolock_key=self.kek if self.autolock else None,
            fips=self.fips,
            csi_plugins=self.csi_plugins,
            scheduler_backend=self.scheduler_backend,
            jax_threshold=self.jax_threshold,
            scheduler_pipeline=self.scheduler_pipeline,
            scheduler_async_commit=self.scheduler_async_commit,
            scheduler_strategy=self.scheduler_strategy,
            scheduler_topology=self.scheduler_topology,
            dispatcher_shards=self.dispatcher_shards,
            clock=self.clock,
        )
        # lease-gated follower read plane (ISSUE 13): this manager can
        # serve Assignments/Tasks/watch READS from its replicated store
        # while it holds the leader's read lease; writes still forward
        from ..dispatcher.follower import FollowerReadPlane

        self._follower_reads = FollowerReadPlane(
            self.store, raft, clock=self.clock)
        self._follower_reads.start()
        build_manager_registry(self.manager, raft,
                               LeaderConns(raft, self.security),
                               registry=registry,
                               follower_reads=self._follower_reads)

        self.server.start()
        t = threading.Thread(target=self._watch_kek_loop, daemon=True,
                             name="kek-watch")
        t.start()
        self._threads.append(t)
        if self.control_socket:
            # local operator socket (xnet unix listener): swarmctl on the
            # same host needs no TLS material (swarmd/cmd/swarmd control
            # socket; filesystem perms are the boundary)
            sock_path = os.path.join(self.state_dir, "swarmd.sock")
            self._control_server = RPCServer(
                "", self.security, registry, org=self.org,
                unix_path=sock_path)
            self._control_server.start()
            self.control_socket_path = sock_path
        raft.start()
        self._ticker = _Ticker(raft, self.tick_interval, clock=self.clock,
                               catch_up_cap=max(1, self.election_tick - 1))
        self._ticker.start()
        self.manager.start()

        if fresh and self.join_addr is None:
            raft.campaign()  # single node: elect immediately, don't wait out
            self._register_self_node(leader=True)

        # every manager announces its reachable endpoint (leader-forwarded)
        t = threading.Thread(target=self._announce_loop, daemon=True,
                             name=f"announce-{node_id[:8]}")
        t.start()
        self._threads.append(t)

        # managers also run an agent against the cluster (runAgent:576);
        # its session follows the leader via the local endpoint, WIDENED
        # by the persisted manager list (node.go persistentRemotes): a
        # manager demoted while down boots with a dead local endpoint and
        # must still reach the live quorum to re-register as a worker. A
        # PROMOTED manager already has both the agent and the renewer
        # from its worker phase — just widen their seed lists.
        if self.agent is None:
            persisted = self._load_state().get("managers") or []
            seeds = [advertise] + [a for a in persisted if a != advertise]
            self._start_agent(",".join(seeds))
        else:
            self._dispatcher_shim.update_managers([advertise])
        if self.renewer is None:
            self.renewer = TLSRenewer(
                self.security,
                RemoteCA(advertise, security=self.security,
                         seeds_fn=self._live_manager_seeds))
            self.renewer.start()

    def _join_raft(self, node_id: str,
                   advertise: str) -> tuple[int, list]:
        """RaftMembership.Join against any live manager (leader-forwarded),
        retried until the quorum admits us (raft.go JoinAndStart:375)."""
        deadline = time.monotonic() + JOIN_TIMEOUT
        last: Exception | None = None
        seeds = [a.strip() for a in self.join_addr.split(",") if a.strip()]
        while time.monotonic() < deadline:
            for seed in seeds:
                try:
                    client = RPCClient(seed, security=self.security)
                except OSError as exc:
                    last = exc
                    continue
                try:
                    raft_id, members = client.call(
                        "raft.join", node_id, advertise, timeout=15.0)
                    return raft_id, members
                except Exception as exc:  # NotLeaderError, timeouts, …
                    last = exc
                finally:
                    client.close()
            if self._stop.wait(JOIN_RETRY):
                break
        raise NodeError(f"could not join the raft quorum: {last}")

    def _repair_addr_loop(self, node_id: str, advertise: str,
                          peer_addrs: list[str]):
        """Tell the quorum this member's address changed (restart on a new
        ephemeral port): raft.join with the same node_id replicates the
        repair; retried until a leader accepts it."""
        while not self._stop.is_set():
            for addr in peer_addrs:
                try:
                    client = RPCClient(addr, security=self.security)
                except OSError:
                    continue
                try:
                    client.call("raft.join", node_id, advertise, timeout=15.0)
                    return
                except Exception:
                    pass
                finally:
                    client.close()
            if self._stop.wait(JOIN_RETRY * 2):
                return

    def _register_self_node(self, leader: bool = False):
        """Create this manager's own Node object in the replicated state
        (the reference seeds it in becomeLeader / on join via the CA)."""
        from ..api.objects import ManagerStatus, Node as NodeObj, NodeCertificate
        from ..api.specs import NodeSpec

        node_id = self.security.node_id()
        cert_pem = self.security.key_and_cert()[1]

        def txn(tx):
            if tx.get_node(node_id) is None:
                n = NodeObj(
                    id=node_id,
                    spec=NodeSpec(desired_role=NodeRole.MANAGER),
                    role=NodeRole.MANAGER,
                )
                n.status.state = NodeStatusState.READY
                n.manager_status = ManagerStatus(
                    raft_id=self.raft_id or 0, addr=self.advertise_addr or "",
                    leader=leader, reachability="reachable")
                n.certificate = NodeCertificate(
                    role=NodeRole.MANAGER,
                    status_state=IssuanceState.ISSUED,
                    certificate_pem=cert_pem,
                    cn=node_id,
                )
                tx.create(n)

        self.store.update(txn)

    def _announce_loop(self):
        """Publish this manager's endpoint onto its Node object, retrying
        through leadership churn; re-announce on every leadership change so
        a recovered cluster re-learns addresses."""
        node_id = self.security.node_id()
        announced_leader = None  # raft leader id the announce landed under
        while not self._stop.is_set():
            leader = self.raft.leader_id if self.raft is not None else None
            if leader is not None and leader != announced_leader:
                try:
                    client = RPCClient(self.advertise_addr,
                                       security=self.security)
                    try:
                        client.call("cluster.announce_manager", node_id,
                                    self.advertise_addr, self.raft_id,
                                    timeout=10.0)
                        announced_leader = leader
                    finally:
                        client.close()
                except Exception:
                    pass
            done = announced_leader is not None \
                and announced_leader == leader
            if self._stop.wait(self.manager_refresh_interval if done
                               else ANNOUNCE_RETRY):
                return

    # -------------------------------------------------------- worker stack

    def _live_manager_seeds(self) -> list[str]:
        shim = self._dispatcher_shim
        return list(shim.seeds) if shim is not None else []

    def _start_worker(self):
        join_addr = self.join_addr
        if join_addr is None:
            # restart path: reconnect from the persisted manager list
            # (reference node/node.go:1202-1286 persistentRemotes — a node
            # that joined once needs no join address ever again)
            persisted = self._load_state().get("managers") or []
            if persisted:
                join_addr = ",".join(persisted)
        if join_addr is None:
            raise NodeError("a worker node needs a join address")
        self._start_agent(join_addr)
        # renewal follows the live manager list, not just the join seed
        # (the original endpoint may die long before the cert expires)
        self.renewer = TLSRenewer(
            self.security,
            RemoteCA(join_addr, security=self.security,
                     seeds_fn=self._live_manager_seeds))
        self.renewer.start()

    def _start_agent(self, addr: str):
        dispatcher = RemoteDispatcher(addr, self.security)
        self._dispatcher_shim = dispatcher
        self.agent = Agent(
            self.security.node_id(),
            dispatcher,
            self.executor,
            state_path=os.path.join(self.state_dir, "worker.json"),
            log_broker=RemoteLogBroker(addr.split(",")[0].strip(),
                                       self.security),
            generic_resources=self.generic_resources,
            fips=self.fips,
            csi_plugins=self.csi_plugins,
        )
        self.agent.on_session_message = self._on_session_message
        self.agent.start()
        # fallback for manager-list freshness when no session message has
        # arrived yet (the stream needs a live session first)
        t = threading.Thread(target=self._refresh_managers_loop,
                             args=(dispatcher,), daemon=True,
                             name="manager-refresh")
        t.start()
        self._threads.append(t)

    def _refresh_managers_loop(self, dispatcher: RemoteDispatcher):
        """Keep the agent's manager seed list fresh even when the session
        stream is down (the Session message plane is the primary source),
        and re-arm role flips: session messages are change-driven, so a
        flip attempt that failed (e.g. CA briefly unreachable) would
        otherwise never retry."""
        while not self._stop.wait(self.manager_refresh_interval):
            msg = self._last_session_msg
            if msg is not None:
                self._maybe_flip_roles(msg)
            self._ensure_rotation_renewal()
            try:
                managers = dispatcher._conn().call("cluster.managers",
                                                   timeout=5.0)
            except Exception:
                continue
            addrs = [addr for _nid, addr in managers]
            dispatcher.update_managers(addrs)
            self._persist_managers(addrs)

    # ------------------------------------------------- session message plane

    def _on_session_message(self, msg):
        """agent/agent.go handleSessionMessage:416-477: manager list feeds
        reconnect failover, network keys reach the executor, and role
        changes flip the manager stack (node/node.go superviseManager)."""
        if msg.managers:
            addrs = [a for _nid, a in msg.managers]
            self._dispatcher_shim.update_managers(addrs)
            self._persist_managers(addrs)
            self._manager_addrs = addrs
        if msg.network_keys:
            try:
                self.executor.set_network_bootstrap_keys(msg.network_keys)
            except Exception:
                pass
        self._apply_root_update(msg.root_ca_pem)
        self._last_session_msg = msg
        self._maybe_flip_roles(msg)

    def _apply_root_update(self, root_pem: bytes) -> None:
        """Adopt a changed cluster trust bundle from the session plane and
        renew this node's certificate onto the new signer (the rotation
        reconciler marks our server-side cert ROTATE; the renewal CSR picks
        the fresh cert up). node/node.go handleSessionMessage applies the
        root the same way; persistence rides the security watch."""
        if not root_pem or self.security is None \
                or root_pem == self.security.root_ca.cert_pem:
            return
        try:
            from ..ca import RootCA

            self.security.update_root_ca(RootCA(root_pem))
        except Exception:
            log.exception("session plane delivered an unusable root bundle")
            return
        self._kick_renew()

    def _kick_renew(self):
        """Single-flight background certificate renewal (used when the trust
        root changes and by the rotation straggler check). The check-then-set
        is under the role-flip lock: two concurrent renew threads would race
        their CSRs and could pair one thread's key with the other's cert."""
        if self.renewer is None:
            return
        with self._role_flip_lock:
            if self._root_renew_active:
                return
            self._root_renew_active = True

        def renew():
            try:
                deadline = time.monotonic() + JOIN_TIMEOUT
                while not self._stop.is_set() \
                        and time.monotonic() < deadline:
                    try:
                        # False = soft failure (status poll timed out —
                        # e.g. the CA skipped our CSR because a rotation
                        # bumped the epoch after we submitted it). Retry:
                        # each renew_once submits a FRESH CSR, which picks
                        # up the current epoch.
                        if self.renewer.renew_once():
                            return
                    except Exception:
                        pass
                    if self._stop.wait(JOIN_RETRY):
                        return
            finally:
                self._root_renew_active = False

        t = threading.Thread(target=renew, daemon=True, name="root-renew")
        t.start()
        self._threads.append(t)

    def _ensure_rotation_renewal(self):
        """Self-healing rotation stragglers (ca/reconciler.go force-renews
        them server-side; here the node heals itself): while the adopted
        trust is a multi-anchor rotation bundle but our leaf does not chain
        to the NEW root (the bundle's second anchor), keep kicking renewals
        — a single missed 30s window after `_apply_root_update` must not
        stall the rotation until the natural renewal window."""
        sec = self.security
        if sec is None or self._root_renew_active:
            return
        try:
            from ..ca import RootCA

            bundle = sec.root_ca.cert_pem
            parts = [b"-----BEGIN CERTIFICATE-----" + p
                     for p in bundle.split(b"-----BEGIN CERTIFICATE-----")
                     if p.strip()]
            leaf = sec.key_and_cert()[1]
            if len(parts) >= 2:
                # rotation in flight: the leaf must chain to the NEW
                # anchor (the bundle's second entry) or the rotation
                # stalls on us
                RootCA(parts[1]).verify_cert(leaf)
                return
            # single anchor: a leaf that doesn't chain to our OWN trust
            # is always broken — the lost-install window (our cert was
            # re-ISSUED at the rotation's epoch, the reconciler finished
            # and trust trimmed to the new root, but the status poll
            # raced out before we installed it). Peers still accept our
            # old leaf for the ROTATION_TRUST_GRACE window, so the
            # renewal kicked here can authenticate and heal.
            for part in parts:
                try:
                    RootCA(part).verify_cert(leaf)
                    return
                except Exception:
                    continue
            self._kick_renew()
        except Exception:
            self._kick_renew()

    def _maybe_flip_roles(self, msg):
        """Called from BOTH the session-message thread and the periodic
        refresh loop — the check-then-set of _role_flip_active is under a
        lock so only one flip thread ever runs."""
        desired = msg.desired_role
        if desired is None:
            return
        with self._role_flip_lock:
            if self._role_flip_active:
                return
            if desired == NodeRole.MANAGER and self.manager is None:
                target, name = self._promote, "promote"
            elif desired == NodeRole.WORKER and self.manager is not None \
                    and msg.node_role == NodeRole.WORKER:
                # the role manager flips node.role only AFTER the raft
                # membership removal succeeded (role_manager.go:154-214),
                # so observing role==WORKER means teardown cannot break
                # quorum. (A removed raft member never hears its own
                # removal — the leader stops replicating to it — so the
                # signal must come from the session plane.)
                target, name = self._demote, "demote"
            else:
                return
            self._role_flip_active = True
        t = threading.Thread(target=target, daemon=True, name=name)
        t.start()
        self._threads.append(t)

    def _promote(self):
        """Worker → manager: renew the certificate until it carries the
        manager role (the role manager reconciles spec.desired_role into
        the cert role), then bring up the full manager stack joining the
        existing quorum."""
        try:
            deadline = time.monotonic() + JOIN_TIMEOUT * 2
            while not self._stop.is_set() and time.monotonic() < deadline:
                if self.security.role() == NodeRole.MANAGER:
                    break
                try:
                    self.renewer.renew_once()
                except Exception:
                    pass
                if self.security.role() == NodeRole.MANAGER:
                    break
                if self._stop.wait(JOIN_RETRY):
                    return
            if self.security.role() != NodeRole.MANAGER:
                log.warning("promotion: manager certificate never issued")
                return
            addrs = list(getattr(self, "_manager_addrs", [])) \
                or list(self._dispatcher_shim.seeds)
            self.join_addr = ",".join(addrs)
            self._save_identity()
            self._start_manager()
            log.info("promoted to manager (raft id %s)", self.raft_id)
        except Exception:
            log.exception("promotion failed")
        finally:
            self._role_flip_active = False

    def _on_member_removed(self):
        """Raft applied OUR removal from the membership: the leader's role
        manager demoted this node (the removal commits before node.role
        flips — role_manager.go:154-214), so manager teardown is safe and
        cannot break quorum. This is the only demotion signal a LEADER
        being demoted ever gets — its agent sessions with itself, and its
        local store stops replicating the moment it is removed."""
        with self._role_flip_lock:
            if self._role_flip_active or self.manager is None:
                return
            self._role_flip_active = True
        t = threading.Thread(target=self._demote, daemon=True,
                             name="demote-removed")
        t.start()
        self._threads.append(t)

    def _demote(self):
        """Manager → worker: called once the role manager has already
        removed us from the raft quorum (node.role flipped WORKER); tear
        the manager stack down and continue as a pure agent."""
        try:
            if self._control_server is not None:
                self._control_server.stop()
                self._control_server = None
                self.control_socket_path = None
            if self.manager is not None:
                self.manager.stop()
                self.manager = None
            if self._follower_reads is not None:
                # the read plane dies with the manager stack: a demoted
                # node's store stops replicating, so lease-gated reads
                # from it would go stale the moment the lease lapses —
                # and a re-promotion builds a fresh plane on the new
                # store (_start_manager)
                self._follower_reads.stop()
                self._follower_reads = None
            if self._ticker is not None:
                self._ticker.stop()
                self._ticker = None
            if self.raft is not None:
                self.raft.stop()
                self.raft = None
            if self._transport is not None:
                self._transport.stop()
                self._transport = None
            if self.server is not None:
                self.server.stop()
                self.server = None
            self.store = None
            self.raft_id = None
            # the computed advertise dies with the server; a re-promotion
            # must advertise its NEW bind, not this stint's port
            self.advertise_addr = self._user_advertise
            self._save_state(raft_id=None, advertise=None)
            # wipe the raft state dir: a later re-promotion joins with a
            # fresh raft id, and replaying this stint's WAL/hard state/
            # membership under it would poison the new quorum view
            # (the reference deletes the raft data dir on demotion)
            import shutil

            shutil.rmtree(os.path.join(self.state_dir, "raft"),
                          ignore_errors=True)
            # pick up the worker certificate from the surviving managers
            deadline = time.monotonic() + JOIN_TIMEOUT * 2
            while not self._stop.is_set() and time.monotonic() < deadline:
                if self.security.role() == NodeRole.WORKER:
                    break
                try:
                    self.renewer.renew_once()
                except Exception:
                    pass
                if self.security.role() == NodeRole.WORKER \
                        or self._stop.wait(JOIN_RETRY):
                    break
            log.info("demoted to worker")
        except Exception:
            log.exception("demotion failed")
        finally:
            self._role_flip_active = False
