"""Operator observability listeners: --listen-metrics and --listen-debug.

The reference exposes Prometheus via --listen-metrics and pprof/expvar via
--listen-debug (swarmd/cmd/swarmd/main.go:5-9, 97-100, 266;
manager/manager.go:551-562 grpc_prometheus). The Python-native analogue:

  /metrics       Prometheus text (content type text/plain; version=0.0.4)
                 — object/node gauges + hot-path histograms
                 (manager/metrics.py MetricsCollector.prometheus_text)
                 + per-node component counters (WAL fsyncs, store op
                 counts, commit-worker depth/poison, heartbeat-wheel
                 entries/buckets) + the trace plane's derived stage
                 histograms when the tracer is armed
  /healthz       liveness probe
  /debug/stacks  all thread stacks (the pprof goroutine-dump analogue —
                 the same diagnostic the wedge detector emits)
  /debug/vars    expvar-style JSON snapshot (+ store op counts, failpoint
                 arm-state, trace arm-state — a leaked arm is visible
                 here without reading conftest output)
  /debug/profile?seconds=N
                 CPU profile of the live process (the pprof CPU-profile
                 analogue, VERDICT item 9): all threads sampled at
                 ~100 Hz for N seconds, reported as a pstats dump
                 sorted by cumulative time
  /debug/trace?seconds=N
                 collect spans for N seconds (arming the tracer for the
                 window if it was disarmed) and return JSON span trees
  /debug/trace/recent
                 the armed flight recorder's current contents as JSON
                 span trees (empty when disarmed)
  /debug/slo     task-lifecycle SLO snapshot from the armed lifecycle
                 recorder (utils/lifecycle.py): NEW→RUNNING percentiles
                 (exact + histogram-estimate), transition counts, and
                 the stage-attribution report; ?since= / ?window=N
                 restrict to the trailing recovery window
  /debug/tasks   ?id=<task>: that task's state-transition timeline;
                 without id, tracked tasks with their latest stage
  /debug/cluster cluster telemetry rollup (utils/telemetry.py +
                 manager/telemetry.py, leader only): merged node metric
                 snapshots, per-node freshness (stale nodes listed,
                 never averaged in), manager-local families;
                 ?window=N adds ring percentiles over the trailing
                 window; {"armed": false} when the plane is disarmed
                 or this node holds no aggregator

Bound to loopback by default; no TLS (match the reference's plaintext debug
listeners, which are operator-only surfaces).
"""
from __future__ import annotations

import json
import math
import sys
import threading
try:
    from ..analysis.lockgraph import make_lock
except ImportError:
    # file-mode load (tests/test_debug_profile.py execs this module
    # straight from its path so crypto-less environments skip the
    # package import chain) — the factory is still reachable absolutely
    from swarmkit_tpu.analysis.lockgraph import make_lock
import time
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


def dump_stacks() -> str:
    lines = []
    frames = sys._current_frames()
    for t in threading.enumerate():
        frame = frames.get(t.ident)
        lines.append(f"--- thread {t.name} (daemon={t.daemon}) ---")
        if frame is not None:
            lines.extend(traceback.format_stack(frame))
    return "\n".join(lines)


def profile_dump(seconds: float, interval: float = 0.01) -> str:
    """CPU profile of every live thread, formatted as a pstats dump.

    Go's pprof CPU profile is a SAMPLING profiler; CPython's tracing
    profilers (cProfile) attach per-thread only, so enabling one inside
    an HTTP handler would profile nothing but the handler's own sleep.
    The closest live-daemon analogue: sample `sys._current_frames()`
    across all threads at ~1/interval Hz, synthesize cProfile-shaped
    stats ((file, line, func) -> (cc, nc, tt, ct, callers), tt/ct from
    leaf/cumulative sample counts x interval), and print them through
    `pstats.Stats` sorted by cumulative — the exact report an operator
    reads out of `cProfile` runs, from a live wedged daemon.

    Caveat the header states: frames accrue samples by WALL time, not
    CPU time — unlike SIGPROF-driven pprof, a thread parked in
    Condition.wait collects samples at the same rate as a busy one, so
    idle wait stacks rank alongside hot ones (which is also what makes
    this the right tool for WEDGED daemons: the stuck stack is exactly
    what surfaces)."""
    import io
    import pstats
    from collections import Counter

    leaf: Counter = Counter()
    cum: Counter = Counter()
    me = threading.get_ident()
    samples = 0
    deadline = time.monotonic() + seconds
    while True:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue              # not the sampler itself
            stack = []
            f = frame
            while f is not None:
                code = f.f_code
                stack.append((code.co_filename, code.co_firstlineno,
                              code.co_name))
                f = f.f_back
            if stack:
                leaf[stack[0]] += 1
                for key in set(stack):   # one cum tick per frame per sample
                    cum[key] += 1
        samples += 1
        if time.monotonic() >= deadline:
            break
        # sampling profiler: the wall-clock pacing IS the sample grid —
        # not a retry loop  # lint: allow(ad-hoc-sleep)
        time.sleep(interval)

    stats = {k: (c, c, leaf.get(k, 0) * interval, c * interval, {})
             for k, c in cum.items()}

    class _Synth:                      # duck-typed pstats source
        def create_stats(self):
            self.stats = stats

    out = io.StringIO()
    out.write(f"CPU profile: {samples} wall-clock samples over "
              f"{seconds:g}s at {interval * 1000:g}ms intervals, all "
              f"threads (tt/ct are sample-count x interval WALL-time "
              f"estimates; parked wait stacks accrue like busy ones)\n")
    ps = pstats.Stats(_Synth(), stream=out)
    ps.sort_stats("cumulative").print_stats(80)
    return out.getvalue()


def _find(node, attr):
    """Resolve a component off the node or its manager (the two shapes
    DebugServer is constructed around: SwarmNode and bare test stubs)."""
    v = getattr(node, attr, None)
    if v is not None:
        return v
    return getattr(getattr(node, "manager", None), attr, None)


def component_metrics_text(node) -> str:
    """Per-node component counters that were bench-only/internal until
    ISSUE 5: raft storage fsyncs, store op counts, the commit plane's
    queue depth + poison count, and heartbeat-wheel occupancy. Every
    lookup is defensive — a worker node (no raft), a stub, or a
    pre-leadership manager simply contributes fewer families."""
    # absolute import: this module is also loaded straight from its
    # file in crypto-less environments (see the lockgraph import above),
    # where relative imports have no package context
    from swarmkit_tpu.utils.metrics import _escape_label_value

    lines: list[str] = []

    def fam(name, help_, type_, samples):
        if not samples:
            return
        lines.append(f"# HELP {name} {help_}")
        lines.append(f"# TYPE {name} {type_}")
        lines.extend(samples)

    storage = getattr(_find(node, "raft"), "storage", None)
    if storage is not None and hasattr(storage, "wal_fsyncs"):
        fam("swarm_raft_wal_fsyncs_total",
            "WAL group-append fsyncs on this node (one per ready-flush "
            "batch; amortized per commit under load)", "counter",
            [f"swarm_raft_wal_fsyncs_total {storage.wal_fsyncs}"])
        fam("swarm_raft_meta_fsyncs_total",
            "hardstate/membership/snapshot/dir fsyncs on this node",
            "counter",
            [f"swarm_raft_meta_fsyncs_total {storage.meta_fsyncs}"])
    raft = _find(node, "raft")
    if raft is not None and hasattr(raft, "snap_chunks_sent"):
        # recovery plane (ISSUE 18): exposed generically off the live
        # snap_* counter surface so a new recovery counter appears here
        # WITHOUT a hand edit (the exposition drift guard walks it)
        ints, floats = [], []
        for key in sorted(a for a in vars(raft) if a.startswith("snap_")
                          and a != "snap_stream_max_bytes"):  # config knob
            v = getattr(raft, key)
            lbl = _escape_label_value(key)
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            if isinstance(v, int):
                ints.append(f'swarm_raft_recovery_total'
                            f'{{counter="{lbl}"}} {v}')
            else:
                floats.append(f'swarm_raft_recovery_seconds'
                              f'{{stat="{lbl}"}} {v}')
        fam("swarm_raft_recovery_total",
            "raft recovery plane counters (snapshot chunks sent/resent/"
            "rejected, suffix resumes, installs)", "counter", ints)
        fam("swarm_raft_recovery_seconds",
            "raft recovery plane timings (cumulative snapshot install "
            "seconds)", "counter", floats)
    op_counts = getattr(_find(node, "store"), "op_counts", None)
    if op_counts:
        fam("swarm_store_ops_total",
            "store operations by kind (view/update transactions, "
            "per-table finds)", "counter",
            [f'swarm_store_ops_total{{op="{_escape_label_value(op)}"}} {n}'
             for op, n in sorted(op_counts.items())])
    disp = _find(node, "dispatcher")
    disp_metrics = getattr(disp, "metrics", None)
    if disp_metrics:
        # the flush-plane counter bag, exposed generically so a new key
        # appears here WITHOUT a hand edit (the exposition drift guard
        # in tests/test_metrics_exposition.py walks the live dict)
        ints, floats = [], []
        for key in sorted(disp_metrics):
            v = disp_metrics[key]
            lbl = _escape_label_value(key)
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            if isinstance(v, int):
                ints.append(f'swarm_dispatcher_plane_total'
                            f'{{counter="{lbl}"}} {v}')
            else:
                floats.append(f'swarm_dispatcher_plane'
                              f'{{stat="{lbl}"}} {v}')
        fam("swarm_dispatcher_plane_total",
            "dispatcher fan-out plane counters (flushes, flush_tx, "
            "ships, wire_copies, dirty_walks, ...)", "counter", ints)
        fam("swarm_dispatcher_plane",
            "dispatcher fan-out plane stats (last_flush_s, ...)",
            "gauge", floats)
    broker = _find(node, "log_broker")
    broker_snap = getattr(broker, "metrics_snapshot", None)
    if broker_snap is not None:
        # log fan-out plane (ISSUE 20): the broker's always-on counter
        # surface, exposed generically off the live snapshot so a new
        # key appears here WITHOUT a hand edit (the exposition drift
        # guard walks the live dict the same way)
        ints, floats = [], []
        for key, v in sorted(broker_snap().items()):
            lbl = _escape_label_value(key)
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            if isinstance(v, int):
                ints.append(f'swarm_logbroker_plane_total'
                            f'{{counter="{lbl}"}} {v}')
            else:
                floats.append(f'swarm_logbroker_plane'
                              f'{{stat="{lbl}"}} {v}')
        fam("swarm_logbroker_plane_total",
            "log fan-out plane counters (published, delivered, shed, "
            "shed_windows, pump_jobs, listener_disconnects, ...)",
            "counter", ints)
        fam("swarm_logbroker_plane",
            "log fan-out plane stats", "gauge", floats)
    wheel = getattr(disp, "_hb_wheel", None)
    if wheel is not None:
        fam("swarm_heartbeat_wheel_entries",
            "sessions armed on the dispatcher heartbeat wheel", "gauge",
            [f"swarm_heartbeat_wheel_entries {len(wheel)}"])
        fam("swarm_heartbeat_wheel_buckets",
            "live buckets on the dispatcher heartbeat wheel", "gauge",
            [f"swarm_heartbeat_wheel_buckets {wheel.bucket_count}"])
        fam("swarm_heartbeat_wheel_ticks_total",
            "heartbeat-wheel ticker fires", "counter",
            [f"swarm_heartbeat_wheel_ticks_total {wheel.ticks}"])
        fam("swarm_heartbeat_wheel_expired_total",
            "heartbeat expirations delivered by the wheel", "counter",
            [f"swarm_heartbeat_wheel_expired_total {wheel.fired}"])
    worker = None
    mgr = getattr(node, "manager", None)
    for c in (getattr(mgr, "_leader_components", None) or ()):
        w = getattr(c, "_commit_worker", None)
        if w is not None:
            worker = w
            break
    if worker is None:
        worker = getattr(getattr(node, "scheduler", None),
                         "_commit_worker", None)
    if worker is not None:
        fam("swarm_commit_worker_queue_depth",
            "async commit plane: heavy commits submitted but not yet "
            "retired", "gauge",
            [f"swarm_commit_worker_queue_depth {worker.pending}"])
        fam("swarm_commit_worker_poisoned",
            "async commit plane: 1 while the worker holds an unraised "
            "exception (heals at the next barrier)", "gauge",
            [f"swarm_commit_worker_poisoned {int(worker.failed)}"])
        fam("swarm_commit_worker_jobs_total",
            "async commit plane: heavy commits retired", "counter",
            [f"swarm_commit_worker_jobs_total {worker.jobs_total}"])
        fam("swarm_commit_worker_poison_total",
            "async commit plane: poison episodes (worker-side commit "
            "crashes)", "counter",
            [f"swarm_commit_worker_poison_total {worker.poisoned_total}"])
    return "\n".join(lines)


class DebugServer:
    """One HTTP listener serving the observability surface for a node."""

    def __init__(self, addr: str, node):
        host, _, port = addr.rpartition(":")
        self.node = node
        # serializes /debug/trace?seconds=N captures (see _trace)
        self._trace_window_lock = make_lock('node.debugserver.trace_window_lock')
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def _reply(self, body: str, ctype="text/plain; charset=utf-8",
                       code=200):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                try:
                    if self.path == "/metrics":
                        # the Prometheus text-format version the scraper
                        # content-negotiates on (satellite: exposition fix)
                        self._reply(outer._metrics_text(),
                                    ctype="text/plain; version=0.0.4; "
                                          "charset=utf-8")
                    elif self.path == "/healthz":
                        self._reply("ok\n")
                    elif self.path == "/debug/stacks":
                        self._reply(dump_stacks())
                    elif self.path == "/debug/vars":
                        self._reply(json.dumps(outer._vars(), indent=2),
                                    ctype="application/json")
                    elif self.path.startswith("/debug/trace"):
                        self._reply(json.dumps(outer._trace(self.path),
                                               indent=2),
                                    ctype="application/json")
                    elif self.path.startswith("/debug/slo"):
                        self._reply(json.dumps(outer._slo(self.path),
                                               indent=2),
                                    ctype="application/json")
                    elif self.path.startswith("/debug/cluster"):
                        self._reply(json.dumps(outer._cluster(self.path),
                                               indent=2),
                                    ctype="application/json")
                    elif self.path.startswith("/debug/tasks"):
                        self._reply(json.dumps(outer._tasks(self.path),
                                               indent=2),
                                    ctype="application/json")
                    elif self.path.startswith("/debug/profile"):
                        from urllib.parse import parse_qs, urlparse

                        q = parse_qs(urlparse(self.path).query)
                        try:
                            seconds = float(q.get("seconds", ["1"])[0])
                        except ValueError:
                            seconds = 1.0
                        # cap: the sampler blocks this handler thread
                        # (ThreadingHTTPServer — other endpoints stay
                        # responsive), not the daemon
                        self._reply(profile_dump(
                            max(0.05, min(seconds, 60.0))))
                    else:
                        self._reply("not found\n", code=404)
                except Exception as exc:  # surface, don't kill the listener
                    self._reply(f"error: {exc}\n", code=500)

        self._httpd = ThreadingHTTPServer((host or "127.0.0.1", int(port)),
                                          Handler)
        self.addr = "%s:%d" % self._httpd.server_address[:2]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="debug-http")

    def _metrics_text(self) -> str:
        node = self.node
        parts = []
        mgr = getattr(node, "manager", None)
        collector = None
        if mgr is not None:
            for c in getattr(mgr, "_leader_components", []):
                if hasattr(c, "prometheus_text"):
                    collector = c
                    break
        if collector is not None:
            parts.append(collector.prometheus_text())
        else:
            # non-leader / worker: hot-path histograms + per-RPC families
            # still exist (absolute import — file-mode load, see above)
            from swarmkit_tpu.utils.metrics import (
                all_families,
                all_histograms,
            )

            parts.extend(
                [h.prometheus_text() for h in all_histograms()]
                + [f.prometheus_text() for f in all_families()])
        comp = component_metrics_text(node)
        if comp:
            parts.append(comp)
        # cluster rollup families (ISSUE 15): the leader's aggregator
        # renders swarm_cluster_* next to the per-process families
        from swarmkit_tpu.utils import telemetry

        agg = telemetry.aggregator()
        if agg is not None and telemetry.enabled():
            try:
                parts.append(agg.prometheus_text())
            except Exception:
                pass  # a degraded rollup must not break the scrape
        return "\n".join(p for p in parts if p)

    def _cluster(self, path: str) -> dict:
        """/debug/cluster: the telemetry rollup (merged node snapshots,
        freshness, manager families); ?window=N adds nearest-rank
        percentiles over the ring's trailing window."""
        from urllib.parse import parse_qs, urlparse

        from swarmkit_tpu.utils import telemetry

        agg = telemetry.aggregator()
        if agg is None:
            return {"armed": telemetry.enabled(), "aggregator": False}
        q = parse_qs(urlparse(path).query)
        window = None
        try:
            if "window" in q:
                window = float(q["window"][0])
        except ValueError:
            window = None
        return agg.rollup(window_s=window)

    def _trace(self, path: str) -> dict:
        """/debug/trace?seconds=N and /debug/trace/recent: JSON span
        trees from the flight recorder. The windowed form arms the
        tracer for the window when it was disarmed — an operator gets a
        trace capture from a live daemon without restarting it."""
        from urllib.parse import parse_qs, urlparse

        from swarmkit_tpu.utils import trace

        parsed = urlparse(path)
        if parsed.path.rstrip("/").endswith("/recent"):
            r = trace.recorder()
            return {"armed": r is not None,
                    "spans": r.spans_started if r is not None else 0,
                    "traces": r.trees() if r is not None else []}
        q = parse_qs(parsed.query)
        try:
            seconds = float(q.get("seconds", ["1"])[0])
        except ValueError:
            seconds = 1.0
        seconds = max(0.05, min(seconds, 30.0))
        # windowed captures SERIALIZE (one lock across arm+sleep+disarm):
        # an overlapping request must not have its window truncated by
        # the first one's disarm, nor report "armed" for a recorder that
        # is about to be torn down. Blocks this handler thread only
        # (ThreadingHTTPServer); /debug/trace/recent stays lock-free.
        with self._trace_window_lock:
            r = trace.recorder()
            temporary = r is None
            if temporary:
                r = trace.arm()
            try:
                # operator-requested real-time capture window (not a
                # retry loop)  # lint: allow(ad-hoc-sleep)
                time.sleep(seconds)
                trees = r.trees(seconds=seconds + 0.05)
            finally:
                # never clobber an arm that raced in (an operator's
                # trace.arm replaces the recorder — then it is theirs)
                if temporary and trace.recorder() is r:
                    trace.disarm()
        return {"armed": not temporary, "window_s": seconds,
                "spans": r.spans_started, "traces": trees}

    def _slo(self, path: str) -> dict:
        """/debug/slo: startup percentiles (exact recorder samples AND
        the conservative /metrics-histogram estimates), transition
        counts, and the stage-attribution report. `?since=<wall-clock
        seconds>` restricts to tasks that reached RUNNING in the
        trailing window (`?window=N` is sugar for since=now-N)."""
        from urllib.parse import parse_qs, urlparse

        from swarmkit_tpu.utils import lifecycle, slo

        r = lifecycle.recorder()
        if r is None:
            return {"armed": False}
        q = parse_qs(urlparse(path).query)
        since = None
        try:
            if "since" in q:
                since = float(q["since"][0])
            elif "window" in q:
                since = time.time() - float(q["window"][0])
        except ValueError:
            since = None
        # the canonical report (shared with control.get_slo_report),
        # extended with the debug-only extras
        out = slo.report(r, since=since)
        out["batches"] = r.batches
        # what an alerting pipeline scraping /metrics would see; a rank
        # in the +Inf tail serializes as null — json.dumps would emit
        # the non-RFC token `Infinity` and break strict parsers exactly
        # on the degraded cluster an operator is inspecting
        est = slo.histogram_quantile(lifecycle.startup_histogram(), 99)
        out["startup"]["p99_s_histogram"] = (
            None if est is not None and not math.isfinite(est) else est)
        out["transitions"] = {f"{a}->{b}": n for (a, b), n
                              in sorted(r.transition_counts().items())}
        return out

    def _tasks(self, path: str) -> dict:
        """/debug/tasks?id=<task>: one task's timeline; without id, the
        tracked task ids with their latest stage (newest-inserted last,
        capped at 200)."""
        from urllib.parse import parse_qs, urlparse

        from swarmkit_tpu.utils import lifecycle

        r = lifecycle.recorder()
        if r is None:
            return {"armed": False}
        q = parse_qs(urlparse(path).query)
        task_id = q.get("id", [""])[0]
        if task_id:
            tl = r.timeline(task_id)
            return {"armed": True, "id": task_id,
                    "events": [{"stage": s, "t": t} for s, t in tl]}
        # key-list copy + 200 short per-timeline fetches — never a deep
        # copy of every timeline under the recorder lock (this endpoint
        # is polled on degraded clusters, exactly when the record sites
        # contending on that lock are busiest)
        out = {}
        for tid in r.task_ids()[-200:]:
            tl = r.timeline(tid)
            if tl:
                out[tid] = tl[-1][0]
        return {"armed": True, "tasks": len(r), "latest_stage": out}

    def _vars(self) -> dict:
        from swarmkit_tpu.utils import (
            failpoints,
            lifecycle,
            telemetry,
            trace,
        )

        node = self.node
        out = {
            "node_id": getattr(node, "node_id", None),
            "addr": getattr(node, "addr", None),
            "is_leader": bool(getattr(node, "is_leader", False)),
            "threads": len(threading.enumerate()),
            # fault/trace plane arm-state: a leaked arm (a test, an
            # operator session) is visible to operators HERE, not only
            # in conftest teardown assertions
            "failpoints_armed": failpoints.active(),
            "trace_armed": trace.active(),
            "lifecycle_armed": lifecycle.active(),
            "telemetry_armed": telemetry.active(),
        }
        store = _find(node, "store")
        if store is not None and getattr(store, "op_counts", None) \
                is not None:
            out["store_ops"] = dict(store.op_counts)
        col = getattr(store, "columnar", None)
        if col is not None:
            # columnar plane counters (ISSUE 11): scatter/materialize/
            # query volumes next to the op counts they complement
            out["store_columnar"] = {
                "tasks": len(col),
                "node_vocab": len(col.nodes),
                "service_vocab": len(col.services),
                **dict(col.stats),
            }
        raft = getattr(node, "raft", None)
        if raft is not None:
            out["raft"] = {
                "id": raft.id,
                "role": str(raft.role),
                "term": raft.term,
                "members": len(raft.members),
                "commit": raft.commit_index,
            }
        return out

    def start(self):
        self._thread.start()

    def stop(self):
        try:
            if self._thread.is_alive():
                # shutdown() handshakes with serve_forever — calling it
                # on a never-started server blocks forever on the
                # is-shut-down event that only serve_forever sets
                self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass
