"""Operator observability listeners: --listen-metrics and --listen-debug.

The reference exposes Prometheus via --listen-metrics and pprof/expvar via
--listen-debug (swarmd/cmd/swarmd/main.go:5-9, 97-100, 266;
manager/manager.go:551-562 grpc_prometheus). The Python-native analogue:

  /metrics       Prometheus text — object/node gauges + hot-path histograms
                 (manager/metrics.py MetricsCollector.prometheus_text)
  /healthz       liveness probe
  /debug/stacks  all thread stacks (the pprof goroutine-dump analogue —
                 the same diagnostic the wedge detector emits)
  /debug/vars    expvar-style JSON snapshot

Bound to loopback by default; no TLS (match the reference's plaintext debug
listeners, which are operator-only surfaces).
"""
from __future__ import annotations

import json
import sys
import threading
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


def dump_stacks() -> str:
    lines = []
    frames = sys._current_frames()
    for t in threading.enumerate():
        frame = frames.get(t.ident)
        lines.append(f"--- thread {t.name} (daemon={t.daemon}) ---")
        if frame is not None:
            lines.extend(traceback.format_stack(frame))
    return "\n".join(lines)


class DebugServer:
    """One HTTP listener serving the observability surface for a node."""

    def __init__(self, addr: str, node):
        host, _, port = addr.rpartition(":")
        self.node = node
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def _reply(self, body: str, ctype="text/plain; charset=utf-8",
                       code=200):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                try:
                    if self.path == "/metrics":
                        self._reply(outer._metrics_text())
                    elif self.path == "/healthz":
                        self._reply("ok\n")
                    elif self.path == "/debug/stacks":
                        self._reply(dump_stacks())
                    elif self.path == "/debug/vars":
                        self._reply(json.dumps(outer._vars(), indent=2),
                                    ctype="application/json")
                    else:
                        self._reply("not found\n", code=404)
                except Exception as exc:  # surface, don't kill the listener
                    self._reply(f"error: {exc}\n", code=500)

        self._httpd = ThreadingHTTPServer((host or "127.0.0.1", int(port)),
                                          Handler)
        self.addr = "%s:%d" % self._httpd.server_address[:2]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="debug-http")

    def _metrics_text(self) -> str:
        node = self.node
        mgr = getattr(node, "manager", None)
        if mgr is not None:
            for c in getattr(mgr, "_leader_components", []):
                if hasattr(c, "prometheus_text"):
                    return c.prometheus_text()
        # non-leader / worker: hot-path histograms + per-RPC families
        # still exist
        from ..utils.metrics import all_families, all_histograms

        return "\n".join(
            [h.prometheus_text() for h in all_histograms()]
            + [f.prometheus_text() for f in all_families()])

    def _vars(self) -> dict:
        node = self.node
        out = {
            "node_id": getattr(node, "node_id", None),
            "addr": getattr(node, "addr", None),
            "is_leader": bool(getattr(node, "is_leader", False)),
            "threads": len(threading.enumerate()),
        }
        raft = getattr(node, "raft", None)
        if raft is not None:
            out["raft"] = {
                "id": raft.id,
                "role": str(raft.role),
                "term": raft.term,
                "members": len(raft.members),
                "commit": raft.commit_index,
            }
        return out

    def start(self):
        self._thread.start()

    def stop(self):
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass
