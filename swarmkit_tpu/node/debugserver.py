"""Operator observability listeners: --listen-metrics and --listen-debug.

The reference exposes Prometheus via --listen-metrics and pprof/expvar via
--listen-debug (swarmd/cmd/swarmd/main.go:5-9, 97-100, 266;
manager/manager.go:551-562 grpc_prometheus). The Python-native analogue:

  /metrics       Prometheus text — object/node gauges + hot-path histograms
                 (manager/metrics.py MetricsCollector.prometheus_text)
  /healthz       liveness probe
  /debug/stacks  all thread stacks (the pprof goroutine-dump analogue —
                 the same diagnostic the wedge detector emits)
  /debug/vars    expvar-style JSON snapshot
  /debug/profile?seconds=N
                 CPU profile of the live process (the pprof CPU-profile
                 analogue, VERDICT item 9): all threads sampled at
                 ~100 Hz for N seconds, reported as a pstats dump
                 sorted by cumulative time

Bound to loopback by default; no TLS (match the reference's plaintext debug
listeners, which are operator-only surfaces).
"""
from __future__ import annotations

import json
import sys
import threading
import time
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


def dump_stacks() -> str:
    lines = []
    frames = sys._current_frames()
    for t in threading.enumerate():
        frame = frames.get(t.ident)
        lines.append(f"--- thread {t.name} (daemon={t.daemon}) ---")
        if frame is not None:
            lines.extend(traceback.format_stack(frame))
    return "\n".join(lines)


def profile_dump(seconds: float, interval: float = 0.01) -> str:
    """CPU profile of every live thread, formatted as a pstats dump.

    Go's pprof CPU profile is a SAMPLING profiler; CPython's tracing
    profilers (cProfile) attach per-thread only, so enabling one inside
    an HTTP handler would profile nothing but the handler's own sleep.
    The closest live-daemon analogue: sample `sys._current_frames()`
    across all threads at ~1/interval Hz, synthesize cProfile-shaped
    stats ((file, line, func) -> (cc, nc, tt, ct, callers), tt/ct from
    leaf/cumulative sample counts x interval), and print them through
    `pstats.Stats` sorted by cumulative — the exact report an operator
    reads out of `cProfile` runs, from a live wedged daemon.

    Caveat the header states: frames accrue samples by WALL time, not
    CPU time — unlike SIGPROF-driven pprof, a thread parked in
    Condition.wait collects samples at the same rate as a busy one, so
    idle wait stacks rank alongside hot ones (which is also what makes
    this the right tool for WEDGED daemons: the stuck stack is exactly
    what surfaces)."""
    import io
    import pstats
    from collections import Counter

    leaf: Counter = Counter()
    cum: Counter = Counter()
    me = threading.get_ident()
    samples = 0
    deadline = time.monotonic() + seconds
    while True:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue              # not the sampler itself
            stack = []
            f = frame
            while f is not None:
                code = f.f_code
                stack.append((code.co_filename, code.co_firstlineno,
                              code.co_name))
                f = f.f_back
            if stack:
                leaf[stack[0]] += 1
                for key in set(stack):   # one cum tick per frame per sample
                    cum[key] += 1
        samples += 1
        if time.monotonic() >= deadline:
            break
        time.sleep(interval)

    stats = {k: (c, c, leaf.get(k, 0) * interval, c * interval, {})
             for k, c in cum.items()}

    class _Synth:                      # duck-typed pstats source
        def create_stats(self):
            self.stats = stats

    out = io.StringIO()
    out.write(f"CPU profile: {samples} wall-clock samples over "
              f"{seconds:g}s at {interval * 1000:g}ms intervals, all "
              f"threads (tt/ct are sample-count x interval WALL-time "
              f"estimates; parked wait stacks accrue like busy ones)\n")
    ps = pstats.Stats(_Synth(), stream=out)
    ps.sort_stats("cumulative").print_stats(80)
    return out.getvalue()


class DebugServer:
    """One HTTP listener serving the observability surface for a node."""

    def __init__(self, addr: str, node):
        host, _, port = addr.rpartition(":")
        self.node = node
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def _reply(self, body: str, ctype="text/plain; charset=utf-8",
                       code=200):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                try:
                    if self.path == "/metrics":
                        self._reply(outer._metrics_text())
                    elif self.path == "/healthz":
                        self._reply("ok\n")
                    elif self.path == "/debug/stacks":
                        self._reply(dump_stacks())
                    elif self.path == "/debug/vars":
                        self._reply(json.dumps(outer._vars(), indent=2),
                                    ctype="application/json")
                    elif self.path.startswith("/debug/profile"):
                        from urllib.parse import parse_qs, urlparse

                        q = parse_qs(urlparse(self.path).query)
                        try:
                            seconds = float(q.get("seconds", ["1"])[0])
                        except ValueError:
                            seconds = 1.0
                        # cap: the sampler blocks this handler thread
                        # (ThreadingHTTPServer — other endpoints stay
                        # responsive), not the daemon
                        self._reply(profile_dump(
                            max(0.05, min(seconds, 60.0))))
                    else:
                        self._reply("not found\n", code=404)
                except Exception as exc:  # surface, don't kill the listener
                    self._reply(f"error: {exc}\n", code=500)

        self._httpd = ThreadingHTTPServer((host or "127.0.0.1", int(port)),
                                          Handler)
        self.addr = "%s:%d" % self._httpd.server_address[:2]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="debug-http")

    def _metrics_text(self) -> str:
        node = self.node
        mgr = getattr(node, "manager", None)
        if mgr is not None:
            for c in getattr(mgr, "_leader_components", []):
                if hasattr(c, "prometheus_text"):
                    return c.prometheus_text()
        # non-leader / worker: hot-path histograms + per-RPC families
        # still exist
        from ..utils.metrics import all_families, all_histograms

        return "\n".join(
            [h.prometheus_text() for h in all_histograms()]
            + [f.prometheus_text() for f in all_families()])

    def _vars(self) -> dict:
        node = self.node
        out = {
            "node_id": getattr(node, "node_id", None),
            "addr": getattr(node, "addr", None),
            "is_leader": bool(getattr(node, "is_leader", False)),
            "threads": len(threading.enumerate()),
        }
        raft = getattr(node, "raft", None)
        if raft is not None:
            out["raft"] = {
                "id": raft.id,
                "role": str(raft.role),
                "term": raft.term,
                "members": len(raft.members),
                "commit": raft.commit_index,
            }
        return out

    def start(self):
        self._thread.start()

    def stop(self):
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass
