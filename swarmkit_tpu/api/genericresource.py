"""Generic (custom) resources: parsing, validation, claim/reclaim.

Re-derivation of api/genericresource/ (SURVEY.md §2.1): operators declare
per-node custom resources as `kind=quantity` (discrete) or `kind=id1,id2`
(named); the scheduler claims them onto tasks and the dispatcher tells the
worker which named ids it got (resource_management.go Claim/Reclaim/
ConsumeNodeResources/HasEnough; parsing parse.go).
"""
from __future__ import annotations

import re

from .specs import Resources

_KIND_RE = re.compile(r"^[a-zA-Z0-9_-]+$")


class GenericResourceError(Exception):
    pass


def parse_cmd(arg: str) -> Resources:
    """Parse swarmd's --generic-node-resources value, e.g.
    "gpu=4,fpga=f1;f2,ssd=1" (parse.go ParseCmd; the reference separates
    named ids with commas inside repeated flags — we accept `;` inside one
    flag for unambiguity and `,` between kinds)."""
    res = Resources()
    if not arg.strip():
        return res
    for part in arg.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise GenericResourceError(f"invalid generic resource {part!r} (want kind=value)")
        kind, value = part.split("=", 1)
        kind, value = kind.strip(), value.strip()
        if not _KIND_RE.match(kind):
            raise GenericResourceError(f"invalid resource kind {kind!r}")
        if not value:
            raise GenericResourceError(f"empty value for resource {kind!r}")
        if value.isdigit():
            res.generic[kind] = res.generic.get(kind, 0) + int(value)
        else:
            ids = {v.strip() for v in value.split(";") if v.strip()}
            if not ids:
                raise GenericResourceError(f"empty id list for resource {kind!r}")
            dupes = res.named_generic.get(kind, set()) & ids
            if dupes:
                raise GenericResourceError(f"duplicate ids {sorted(dupes)} for {kind!r}")
            res.named_generic.setdefault(kind, set()).update(ids)
    for kind in res.generic:
        if kind in res.named_generic:
            raise GenericResourceError(
                f"resource {kind!r} is both discrete and named"
            )
    return res


def has_enough(node_avail: Resources, want: dict[str, int]) -> bool:
    """resource_management.go HasEnough: named ids count toward the kind."""
    for kind, qty in want.items():
        have = node_avail.generic.get(kind, 0) + len(
            node_avail.named_generic.get(kind, ())
        )
        if have < qty:
            return False
    return True


def claim(node_avail: Resources, want: dict[str, int]) -> dict[str, tuple[frozenset, int]]:
    """Claim resources from a node's available pool, preferring named ids
    (resource_management.go Claim). Returns kind -> (named ids, discrete
    count) actually taken; mutates node_avail. Raises if short."""
    if not has_enough(node_avail, want):
        raise GenericResourceError("insufficient generic resources")
    taken: dict[str, tuple[frozenset, int]] = {}
    for kind, qty in want.items():
        named_pool = node_avail.named_generic.get(kind, set())
        take_named = frozenset(sorted(named_pool)[:qty])
        named_pool -= take_named
        remaining = qty - len(take_named)
        if remaining:
            node_avail.generic[kind] = node_avail.generic.get(kind, 0) - remaining
        taken[kind] = (take_named, remaining)
    return taken


def reclaim(node_avail: Resources, taken: dict[str, tuple[frozenset, int]]):
    """Return claimed resources to the pool (resource_management.go Reclaim)."""
    for kind, (named, count) in taken.items():
        if named:
            node_avail.named_generic.setdefault(kind, set()).update(named)
        if count:
            node_avail.generic[kind] = node_avail.generic.get(kind, 0) + count


def consume_node_resources(node_avail: Resources, taken: dict[str, tuple[frozenset, int]]):
    """Deduct an existing task's claim from a freshly-described node pool
    (resource_management.go ConsumeNodeResources — used when rebuilding
    NodeInfo from running tasks)."""
    for kind, (named, count) in taken.items():
        if named:
            pool = node_avail.named_generic.get(kind, set())
            pool -= set(named)
        if count:
            node_avail.generic[kind] = max(
                0, node_avail.generic.get(kind, 0) - count
            )
