"""Store objects: the versioned, replicated cluster state.

Re-derivation of the reference's object protos (api/objects.proto) and the
StoreObject abstraction (api/storeobject.go:19-27): every object exposes
id/meta/copy and maps to create/update/delete events. Where the reference
generates this via protobuf plugins, we use one dataclass base.
"""
from __future__ import annotations

import copy as _copy
from dataclasses import dataclass, field
from typing import Any

from ..native import hostops as _hostops

from .specs import (
    Annotations,
    ClusterSpec,
    ConfigSpec,
    ExtensionSpec,
    NetworkSpec,
    NodeDescription,
    NodeSpec,
    SecretSpec,
    ServiceSpec,
    TaskSpec,
    VolumeSpec,
)
from .types import NodeStatusState, TaskState


@dataclass
class Version:
    """Optimistic-concurrency version: the raft index of the last write
    (reference: api/objects.proto Meta.Version; ErrSequenceConflict on mismatch)."""

    index: int = 0


@dataclass
class Meta:
    version: Version = field(default_factory=Version)
    created_at: float = 0.0
    updated_at: float = 0.0


@dataclass
class StoreObject:
    """Base for everything the store replicates (api/storeobject.go:19-27).

    NO-ALIASING CONTRACT: every StoreObject (and every spec it embeds)
    must be tree-shaped — no field may share a mutable substructure with
    another field of the same object. `copy()` uses the native tree
    copier, which forks aliased subtrees into independent copies (it has
    no deepcopy memo); a future field that deliberately aliased another
    would silently change copy semantics versus the deepcopy fallback.
    tests/test_native_hostops.py::test_tree_copy_matches_deepcopy_catalog
    pins tree_copy == copy.deepcopy over a representative object of every
    table; keep it green when adding fields."""

    id: str = ""
    meta: Meta = field(default_factory=Meta)

    # Table name, filled in by subclasses; used by the store and snapshots.
    TABLE = ""

    def copy(self):
        # the store's hottest call: 2-3 copies per write transaction.
        # The replicated object model is tree-shaped (no cycles, no
        # aliasing between fields), so the native tree copier applies;
        # unknown subtrees inside `Any` fields fall back to deepcopy
        # per-subtree, and the whole call falls back without the native
        # module (tests/test_native_hostops.py pins equivalence)
        if _hostops is not None:
            return _hostops.tree_copy(self, _copy.deepcopy)
        return _copy.deepcopy(self)

    def get_id(self) -> str:
        return self.id


@dataclass
class TaskStatus:
    """Observed state, written only by the worker path
    (reference: api/objects.proto:244-249 comment on observed vs desired)."""

    timestamp: float = 0.0
    state: TaskState = TaskState.NEW
    message: str = ""
    err: str = ""
    # container/runtime exit status
    exit_code: int | None = None
    port_status: list[Any] = field(default_factory=list)
    applied_by: str = ""  # node that reported it


@dataclass
class Task(StoreObject):
    """reference: api/objects.proto:183-276."""

    TABLE = "task"

    spec: TaskSpec = field(default_factory=TaskSpec)
    service_id: str = ""
    slot: int = 0  # replicated-mode slot; 0 for global mode
    node_id: str = ""  # set by the scheduler exactly once (task immutability)
    annotations: Annotations = field(default_factory=Annotations)
    service_annotations: Annotations = field(default_factory=Annotations)
    status: TaskStatus = field(default_factory=TaskStatus)
    desired_state: TaskState = TaskState.NEW
    spec_version: Version | None = None
    endpoint: Any = None
    log_driver: Any = None
    networks: list[Any] = field(default_factory=list)
    assigned_generic_resources: dict[str, Any] = field(default_factory=dict)
    volumes: list[str] = field(default_factory=list)  # VolumeAttachment ids
    job_iteration: Version | None = None


@dataclass
class Service(StoreObject):
    TABLE = "service"

    spec: ServiceSpec = field(default_factory=ServiceSpec)
    previous_spec: ServiceSpec | None = None
    spec_version: Version = field(default_factory=Version)
    previous_spec_version: Version | None = None
    endpoint: Any = None
    update_status: Any = None
    job_status: Any = None
    pending_delete: bool = False


@dataclass
class NodeStatus:
    state: NodeStatusState = NodeStatusState.UNKNOWN
    message: str = ""
    addr: str = ""


@dataclass
class ManagerStatus:
    raft_id: int = 0
    addr: str = ""
    leader: bool = False
    reachability: str = "unknown"  # unknown|unreachable|reachable


@dataclass
class NodeCertificate:
    """Per-node certificate record replicated in the store
    (reference: api/types.proto Certificate: role/CSR/status/certificate/CN)."""

    role: int = 0  # NodeRole.WORKER
    csr_pem: bytes = b""
    status_state: int = 0  # IssuanceState
    status_err: str = ""
    certificate_pem: bytes = b""
    cn: str = ""
    # cluster root_ca.last_forced_rotation at CSR submission: the rotation
    # reconciler finishes only when every node re-CSR'd under the current
    # epoch — i.e. the node itself fetched and swapped to the new cert, not
    # merely that the server re-signed an old CSR (premature trust-anchor
    # swap would wedge nodes still presenting old-signed leafs)
    rotation_epoch: int = 0


@dataclass
class RootCAObj:
    """Cluster root CA material held on the Cluster object
    (reference: api/types.proto RootCA: key/cert/digest/join tokens/rotation)."""

    ca_key_pem: bytes = b""
    ca_cert_pem: bytes = b""
    cert_digest: str = ""
    join_token_worker: str = ""
    join_token_manager: str = ""
    root_rotation: Any = None
    last_forced_rotation: int = 0


@dataclass
class Node(StoreObject):
    TABLE = "node"

    spec: NodeSpec = field(default_factory=NodeSpec)
    description: NodeDescription | None = None
    status: NodeStatus = field(default_factory=NodeStatus)
    manager_status: ManagerStatus | None = None
    attachments: list[Any] = field(default_factory=list)
    certificate: Any = None
    role: int = 0  # observed role (cert role); spec.desired_role is desired
    vxlan_udp_port: int = 0


@dataclass
class Cluster(StoreObject):
    TABLE = "cluster"

    spec: ClusterSpec = field(default_factory=ClusterSpec)
    root_ca: Any = None
    network_bootstrap_keys: list[Any] = field(default_factory=list)
    encryption_key_lamport_clock: int = 0
    blacklisted_certificates: dict[str, Any] = field(default_factory=dict)
    unlock_keys: list[Any] = field(default_factory=list)
    fips: bool = False
    default_address_pool: list[str] = field(default_factory=list)
    subnet_size: int = 24
    vxlan_udp_port: int = 4789


@dataclass
class Secret(StoreObject):
    TABLE = "secret"

    spec: SecretSpec = field(default_factory=SecretSpec)
    internal: bool = False


@dataclass
class Config(StoreObject):
    TABLE = "config"

    spec: ConfigSpec = field(default_factory=ConfigSpec)


@dataclass
class Network(StoreObject):
    TABLE = "network"

    spec: NetworkSpec = field(default_factory=NetworkSpec)
    driver_state: Any = None
    ipam: Any = None
    pending_delete: bool = False


@dataclass
class Volume(StoreObject):
    TABLE = "volume"

    spec: VolumeSpec = field(default_factory=VolumeSpec)
    publish_status: list[Any] = field(default_factory=list)
    volume_info: Any = None
    pending_delete: bool = False


@dataclass
class Extension(StoreObject):
    TABLE = "extension"

    annotations: Annotations = field(default_factory=Annotations)
    description: str = ""


@dataclass
class Resource(StoreObject):
    """Custom extension-kind resources (reference: api/objects.proto Resource)."""

    TABLE = "resource"

    annotations: Annotations = field(default_factory=Annotations)
    kind: str = ""
    payload: bytes = b""


ALL_TABLES: dict[str, type[StoreObject]] = {
    cls.TABLE: cls
    for cls in (Task, Service, Node, Cluster, Secret, Config, Network, Volume, Extension, Resource)
}


# ---------------------------------------------------------------------------
# Events. The reference generates EventCreate<T>/EventUpdate<T>/EventDelete<T>
# per object via the storeobject protobuf plugin; we use one generic family.
# ---------------------------------------------------------------------------


@dataclass
class StoreEvent:
    obj: StoreObject

    @property
    def table(self) -> str:
        return self.obj.TABLE


@dataclass
class EventCreate(StoreEvent):
    pass


@dataclass
class EventUpdate(StoreEvent):
    old: StoreObject | None = None


@dataclass
class EventDelete(StoreEvent):
    pass


@dataclass
class EventCommit:
    """Published after each committed transaction (manager/state/watch.go:10)."""

    version: Version = field(default_factory=Version)
