"""Core enums and value types of the object model.

TPU-native re-implementation of the reference's protobuf enum surface
(reference: api/types.proto). Values are kept numerically identical to the
reference so that state machines, ordering comparisons, and on-disk snapshots
remain comparable (api/types.proto:510-540 for TaskState).
"""
from __future__ import annotations

import enum


class TaskState(enum.IntEnum):
    """Monotonic task lifecycle (reference: api/types.proto:510-540).

    A task's observed state never decreases (agent/exec/controller.go:163-166
    panics on a backward transition in the reference); the same invariant is
    enforced in `swarmkit_tpu.agent.exec`.
    """

    NEW = 0
    PENDING = 64
    ASSIGNED = 192
    ACCEPTED = 256
    PREPARING = 320
    READY = 384
    STARTING = 448
    RUNNING = 512
    COMPLETE = 576
    SHUTDOWN = 640
    FAILED = 704
    REJECTED = 768
    REMOVE = 800
    ORPHANED = 832

    @property
    def terminal(self) -> bool:
        return self >= TaskState.COMPLETE


class NodeRole(enum.IntEnum):
    """reference: api/types.proto NodeRole."""

    WORKER = 0
    MANAGER = 1


class NodeMembership(enum.IntEnum):
    PENDING = 0
    ACCEPTED = 1


class NodeAvailability(enum.IntEnum):
    ACTIVE = 0
    PAUSE = 1
    DRAIN = 2


class NodeStatusState(enum.IntEnum):
    """reference: api/types.proto NodeStatus.State."""

    UNKNOWN = 0
    DOWN = 1
    READY = 2
    DISCONNECTED = 3


class ServiceMode(enum.Enum):
    REPLICATED = "replicated"
    GLOBAL = "global"
    REPLICATED_JOB = "replicated_job"
    GLOBAL_JOB = "global_job"


class RestartCondition(enum.Enum):
    """reference: api/types.proto RestartPolicy.RestartCondition."""

    NONE = "none"
    ON_FAILURE = "on_failure"
    ANY = "any"


class UpdateFailureAction(enum.Enum):
    PAUSE = "pause"
    CONTINUE = "continue"
    ROLLBACK = "rollback"


class UpdateOrder(enum.Enum):
    STOP_FIRST = "stop_first"
    START_FIRST = "start_first"


class UpdateStatusState(enum.Enum):
    UNKNOWN = "unknown"
    UPDATING = "updating"
    PAUSED = "paused"
    COMPLETED = "completed"
    ROLLBACK_STARTED = "rollback_started"
    ROLLBACK_PAUSED = "rollback_paused"
    ROLLBACK_COMPLETED = "rollback_completed"


class IssuanceState(enum.IntEnum):
    """Certificate issuance lifecycle (reference: api/ca.proto IssuanceStatus.State)."""

    UNKNOWN = 0
    RENEW = 1  # manager forces the node to re-CSR
    PENDING = 2
    ISSUED = 3
    FAILED = 4
    ROTATE = 5  # cert valid but must be re-issued under a new root


# Platform normalization applied by the platform filter
# (reference: manager/scheduler/filter.go:254-320).
ARCH_ALIASES = {
    "x86_64": "amd64",
    "aarch64": "arm64",
}


def normalize_arch(arch: str) -> str:
    return ARCH_ALIASES.get(arch.lower(), arch.lower())
