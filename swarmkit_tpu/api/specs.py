"""Spec types: the *desired* half of every object.

Re-derivation of the reference's spec protos (api/specs.proto, 581 lines).
Specs are plain frozen-ish dataclasses; objects embed a spec plus observed
runtime state. Deep-copy semantics mirror the generated deepcopy plugin
(protobuf/plugin/deepcopy in the reference).
"""
from __future__ import annotations

import copy
import dataclasses
from dataclasses import dataclass, field
from typing import Any

from ..native import hostops as _hostops

from .types import (
    NodeAvailability,
    NodeRole,
    RestartCondition,
    ServiceMode,
    UpdateFailureAction,
    UpdateOrder,
)


@dataclass
class Annotations:
    name: str = ""
    labels: dict[str, str] = field(default_factory=dict)
    # custom indexes (reference api/types.proto Annotations.indices,
    # IndexEntry key/val): application-defined secondary keys that watch
    # selectors and custom find-by queries match on
    indices: dict[str, str] = field(default_factory=dict)


@dataclass
class Platform:
    """reference: api/types.proto Platform (os/architecture)."""

    architecture: str = ""
    os: str = ""


@dataclass
class Resources:
    """Scalar + generic resources (reference: api/types.proto Resources).

    nano_cpus follows the reference's NanoCPUs convention (1e9 == one core).
    `generic` maps resource-kind -> quantity for discrete generic resources
    (api/genericresource in the reference); named generic resources carry a
    set of string ids per kind.
    """

    nano_cpus: int = 0
    memory_bytes: int = 0
    generic: dict[str, int] = field(default_factory=dict)
    named_generic: dict[str, set[str]] = field(default_factory=dict)

    def copy(self) -> "Resources":
        return Resources(
            nano_cpus=self.nano_cpus,
            memory_bytes=self.memory_bytes,
            generic=dict(self.generic),
            named_generic={k: set(v) for k, v in self.named_generic.items()},
        )


@dataclass
class ResourceRequirements:
    reservations: Resources = field(default_factory=Resources)
    limits: Resources = field(default_factory=Resources)


@dataclass
class PlacementPreference:
    """Spread-over-label preference (reference: api/specs.proto Placement)."""

    spread_descriptor: str = ""  # e.g. "node.labels.datacenter"


@dataclass
class Placement:
    """reference: api/specs.proto Placement."""

    constraints: list[str] = field(default_factory=list)
    preferences: list[PlacementPreference] = field(default_factory=list)
    platforms: list[Platform] = field(default_factory=list)
    max_replicas: int = 0  # 0 == unlimited (MaxReplicasFilter)


@dataclass
class RestartPolicy:
    """reference: api/types.proto RestartPolicy; defaults api/defaults/service.go."""

    condition: RestartCondition = RestartCondition.ANY
    delay: float = 5.0  # seconds (reference default 5s)
    max_attempts: int = 0  # 0 == unlimited
    window: float = 0.0  # seconds; 0 == unbounded window


@dataclass
class UpdateConfig:
    """Rolling-update knobs (reference: api/types.proto UpdateConfig)."""

    parallelism: int = 1
    delay: float = 0.0
    failure_action: UpdateFailureAction = UpdateFailureAction.PAUSE
    monitor: float = 5.0
    max_failure_ratio: float = 0.0
    order: UpdateOrder = UpdateOrder.STOP_FIRST


@dataclass
class SecretReference:
    secret_id: str = ""
    secret_name: str = ""
    target: str = ""  # filename in the task sandbox


@dataclass
class ConfigReference:
    config_id: str = ""
    config_name: str = ""
    target: str = ""


@dataclass
class VolumeMount:
    source: str = ""  # volume name, or "group:<name>" for cluster volumes
    target: str = ""
    readonly: bool = False
    type: str = "volume"  # "bind" | "volume" | "tmpfs" | "csi"


@dataclass
class PortConfig:
    """reference: api/types.proto PortConfig (host-port publishing)."""

    name: str = ""
    protocol: str = "tcp"
    target_port: int = 0
    published_port: int = 0  # 0 == dynamically assigned
    publish_mode: str = "ingress"  # "ingress" | "host"


@dataclass
class EndpointSpec:
    mode: str = "vip"  # "vip" | "dnsrr"
    ports: list[PortConfig] = field(default_factory=list)


@dataclass
class NetworkAttachmentConfig:
    target: str = ""  # network id or name
    aliases: list[str] = field(default_factory=list)
    addresses: list[str] = field(default_factory=list)


@dataclass
class ContainerSpec:
    """The default runtime spec (reference: api/specs.proto ContainerSpec).

    The executor interprets it; the fake executor in tests only sleeps/exits.
    """

    image: str = ""
    command: list[str] = field(default_factory=list)
    args: list[str] = field(default_factory=list)
    env: list[str] = field(default_factory=list)
    labels: dict[str, str] = field(default_factory=dict)
    dir: str = ""
    user: str = ""
    secrets: list[SecretReference] = field(default_factory=list)
    configs: list[ConfigReference] = field(default_factory=list)
    mounts: list[VolumeMount] = field(default_factory=list)
    stop_grace_period: float = 10.0
    pull_options: dict[str, str] = field(default_factory=dict)
    hosts: list[str] = field(default_factory=list)


@dataclass
class NetworkAttachmentSpec:
    """Attachment-task runtime: bind an existing engine container to a
    cluster network (reference: api/specs.proto NetworkAttachmentSpec)."""

    container_id: str = ""


@dataclass
class TaskSpec:
    """reference: api/specs.proto TaskSpec (oneof runtime:
    container | attachment)."""

    runtime: ContainerSpec | None = None
    attachment: NetworkAttachmentSpec | None = None
    resources: ResourceRequirements = field(default_factory=ResourceRequirements)
    restart: RestartPolicy = field(default_factory=RestartPolicy)
    placement: Placement = field(default_factory=Placement)
    networks: list[NetworkAttachmentConfig] = field(default_factory=list)
    log_driver: dict[str, Any] | None = None
    force_update: int = 0  # bumping forces a task refresh (spec-equal but dirty)


@dataclass
class JobSpec:
    max_concurrent: int = 0
    total_completions: int = 0


@dataclass
class ServiceSpec:
    """reference: api/specs.proto ServiceSpec."""

    annotations: Annotations = field(default_factory=Annotations)
    task: TaskSpec = field(default_factory=TaskSpec)
    mode: ServiceMode = ServiceMode.REPLICATED
    replicas: int = 1  # replicated mode
    job: JobSpec = field(default_factory=JobSpec)
    update: UpdateConfig = field(default_factory=UpdateConfig)
    rollback: UpdateConfig | None = None
    endpoint: EndpointSpec = field(default_factory=EndpointSpec)
    networks: list[NetworkAttachmentConfig] = field(default_factory=list)


@dataclass
class NodeDescription:
    """What a node reports about itself (reference: api/objects.proto Node.Description)."""

    hostname: str = ""
    platform: Platform = field(default_factory=Platform)
    resources: Resources = field(default_factory=Resources)
    engine_labels: dict[str, str] = field(default_factory=dict)
    plugins: list[tuple[str, str]] = field(default_factory=list)  # (type, name)
    fips: bool = False
    csi_plugins: list[str] = field(default_factory=list)
    # plugin name -> NodeCSIInfo (csi node id + accessible topology segments)
    csi_info: dict[str, "NodeCSIInfo"] = field(default_factory=dict)


@dataclass
class NodeCSIInfo:
    """Per-plugin CSI identity a node reports
    (reference: api/objects.proto NodeCSIInfo)."""

    plugin_name: str = ""
    node_id: str = ""  # the *CSI* node id, plugin-scoped
    max_volumes_per_node: int = 0
    accessible_topology: dict[str, str] = field(default_factory=dict)


@dataclass
class NodeSpec:
    annotations: Annotations = field(default_factory=Annotations)
    desired_role: NodeRole = NodeRole.WORKER
    membership: int = 1  # NodeMembership.ACCEPTED
    availability: NodeAvailability = NodeAvailability.ACTIVE


@dataclass
class RaftConfig:
    """reference: api/types.proto RaftConfig; defaults manager/manager.go:1194+."""

    snapshot_interval: int = 10000
    keep_old_snapshots: int = 0
    log_entries_for_slow_followers: int = 500
    election_tick: int = 10
    heartbeat_tick: int = 1


@dataclass
class DispatcherConfig:
    heartbeat_period: float = 5.0  # reference: manager/dispatcher/dispatcher.go:28-53


@dataclass
class CAConfig:
    """reference: api/specs.proto CAConfig — the operator's steering wheel
    for the CA (controlapi/ca_rotation.go validates + applies it)."""

    node_cert_expiry: float = 90 * 24 * 3600.0
    # [{"protocol": "cfssl", "url": "https://...", "ca_cert": pem?}, ...]
    external_cas: list[dict[str, Any]] = field(default_factory=list)
    # bump to force a root rotation with a freshly generated root
    force_rotate: int = 0
    # operator-supplied signing material: cert+key rotates to that root;
    # cert alone requires a matching external CA entry to do the signing
    signing_ca_cert: bytes = b""
    signing_ca_key: bytes = b""


@dataclass
class EncryptionConfig:
    auto_lock_managers: bool = False


@dataclass
class TaskDefaults:
    log_driver: dict[str, Any] | None = None


@dataclass
class ClusterSpec:
    """Replicated runtime configuration (reference: api/specs.proto ClusterSpec)."""

    annotations: Annotations = field(default_factory=Annotations)
    raft: RaftConfig = field(default_factory=RaftConfig)
    dispatcher: DispatcherConfig = field(default_factory=DispatcherConfig)
    ca: CAConfig = field(default_factory=CAConfig)
    encryption: EncryptionConfig = field(default_factory=EncryptionConfig)
    task_defaults: TaskDefaults = field(default_factory=TaskDefaults)
    task_history_retention_limit: int = 5


@dataclass
class SecretSpec:
    annotations: Annotations = field(default_factory=Annotations)
    data: bytes = b""
    driver: dict[str, Any] | None = None
    templating: bool = False


@dataclass
class ConfigSpec:
    annotations: Annotations = field(default_factory=Annotations)
    data: bytes = b""
    templating: bool = False


@dataclass
class NetworkSpec:
    annotations: Annotations = field(default_factory=Annotations)
    driver_config: dict[str, Any] | None = None
    ipv6_enabled: bool = False
    internal: bool = False
    attachable: bool = False
    ingress: bool = False
    ipam: dict[str, Any] | None = None


@dataclass
class VolumeAccessMode:
    """reference: api/types.proto VolumeAccessMode."""

    scope: str = "single"  # "single" | "multi"
    sharing: str = "none"  # "none" | "readonly" | "onewriter" | "all"
    block: bool = False


@dataclass
class VolumeSpec:
    annotations: Annotations = field(default_factory=Annotations)
    group: str = ""
    driver: str = ""
    access_mode: VolumeAccessMode = field(default_factory=VolumeAccessMode)
    secrets: dict[str, str] = field(default_factory=dict)
    accessibility_requirements: dict[str, Any] | None = None
    capacity_range: tuple[int, int] | None = None
    availability: str = "active"  # "active" | "pause" | "drain"


@dataclass
class ExtensionSpec:
    annotations: Annotations = field(default_factory=Annotations)
    description: str = ""


def deepcopy_spec(spec):
    """Uniform deep-copy, standing in for the reference's generated
    CopyFrom — native tree copier when available (specs are tree-shaped
    dataclasses; this runs once per task the orchestrators create)."""
    if _hostops is not None:
        return _hostops.tree_copy(spec, copy.deepcopy)
    return copy.deepcopy(spec)


def spec_equal(a, b) -> bool:
    """Spec equality as used for dirtiness checks (orchestrator/task.go
    IsTaskDirty). Dataclass `==` compares fields recursively and is ~10×
    cheaper than two asdict walks; the asdict comparison remains as the
    widening fallback for structurally-different-but-equivalent `Any`
    payloads (e.g. a dataclass vs its dict form), preserving the old
    result for every pair the fast path can't prove equal."""
    if type(a) is type(b) and a == b:
        return True
    return dataclasses.asdict(a) == dataclasses.asdict(b)


def normalize_nones(obj):
    """Fold hand-crafted Nones back to field defaults, recursively and in
    place (returns `obj` for chaining).

    The reference's wire makes this unrepresentable: a non-pointer proto
    field cannot be null — a client can only OMIT it, which decodes as
    the zero value (specs.proto's Task, Placement, Resources, ... are
    all non-pointer). This framework's msgpack codec rebuilds dataclasses
    without per-field type checks, so a hand-crafted payload CAN carry
    None where the dataclass declares a non-None default — and every
    validator and control loop downstream is written against the proto
    guarantee. Called at the validation boundary so both the checks and
    the stored spec see proto-shaped objects.

    Fields DECLARED optional (default None, e.g. ServiceSpec.rollback,
    TaskSpec.runtime) keep None — those are the proto pointer fields.
    None ELEMENTS inside lists and None dict values are dropped: proto
    repeated and map fields cannot carry null entries either (an absent
    element is simply not sent).
    """
    if not dataclasses.is_dataclass(obj) or isinstance(obj, type):
        return obj
    for f in dataclasses.fields(obj):
        v = getattr(obj, f.name)
        if v is None:
            if f.default_factory is not dataclasses.MISSING:
                setattr(obj, f.name, f.default_factory())
            elif f.default is not dataclasses.MISSING \
                    and f.default is not None:
                setattr(obj, f.name, f.default)
        elif dataclasses.is_dataclass(v) and not isinstance(v, type):
            normalize_nones(v)
        elif isinstance(v, list):
            v[:] = [normalize_nones(item) for item in v if item is not None]
        elif isinstance(v, dict):
            drop = [k for k, item in v.items() if item is None]
            for k in drop:
                del v[k]
            for item in v.values():
                normalize_nones(item)
    return obj
