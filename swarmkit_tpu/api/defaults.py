"""Canonical service-spec defaults.

Re-derivation of api/defaults/service.go:13+. Unlike the reference's protos
(where unset submessages arrive nil and are merged over a canonical default
spec), our dataclasses bake the same canonical values into their field
defaults — RestartPolicy(condition=ANY, delay=5s), UpdateConfig(
parallelism=1, failure_action=PAUSE) — so a freshly-constructed spec is
already canonical. This module holds the one genuinely-optional merge
(rollback config) plus the canonical constructors, so control-API validation
has a single source of truth to cite.
"""
from __future__ import annotations

from .specs import RestartPolicy, ServiceSpec, UpdateConfig
from .types import RestartCondition, UpdateFailureAction

DEFAULT_RESTART_DELAY = 5.0  # defaults/service.go RestartPolicy.Delay 5s
DEFAULT_UPDATE_PARALLELISM = 1


def default_restart_policy() -> RestartPolicy:
    return RestartPolicy(condition=RestartCondition.ANY, delay=DEFAULT_RESTART_DELAY)


def default_update_config() -> UpdateConfig:
    return UpdateConfig(
        parallelism=DEFAULT_UPDATE_PARALLELISM,
        failure_action=UpdateFailureAction.PAUSE,
    )


def merge_service_defaults(spec: ServiceSpec) -> ServiceSpec:
    """Fill genuinely-optional fields in place (defaults/service.go Service
    merge). Restart and update configs are non-optional dataclass fields
    whose defaults already carry the canonical values; rollback is the one
    Optional field to fill. Returns the spec."""
    if spec.rollback is None:
        spec.rollback = default_update_config()
    return spec
