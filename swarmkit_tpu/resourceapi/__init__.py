"""Resource allocator API (reference: manager/resourceapi/, SURVEY.md §2.7)."""
from .allocator import ResourceAllocator

__all__ = ["ResourceAllocator"]
