"""ResourceAllocator API: agent-initiated network attachments.

Re-derivation of manager/resourceapi/allocator.go (124 ln): a worker asks the
manager to attach one of its engine-level containers to a cluster network —
the manager records a node-pinned *attachment task* (no service) that flows
through allocator → dispatcher like any task; detach removes it.
"""
from __future__ import annotations

from ..api.objects import Task
from ..api.specs import (
    Annotations,
    NetworkAttachmentConfig,
    NetworkAttachmentSpec,
    TaskSpec,
)
from ..api.types import TaskState
from ..utils.identity import new_id


class ResourceError(Exception):
    pass


class ResourceAllocator:
    def __init__(self, store):
        self.store = store

    def attach_network(
        self, node_id: str, network_id: str, addresses: list[str] | None = None
    ) -> str:
        """ResourceAllocator.AttachNetwork (allocator.go:21-81): creates the
        attachment task pinned to the calling node; returns the attachment
        (task) id."""
        network = self.store.view(lambda tx: tx.get_network(network_id))
        if network is None:
            raise ResourceError(f"network {network_id} not found")

        task = Task(
            id=new_id(),
            node_id=node_id,
            desired_state=TaskState.RUNNING,
            annotations=Annotations(name=f"attachment-{network_id[:8]}"),
        )
        task.spec = TaskSpec(
            attachment=NetworkAttachmentSpec(),
            networks=[
                NetworkAttachmentConfig(
                    target=network_id, addresses=list(addresses or [])
                )
            ],
        )
        task.status.state = TaskState.NEW

        self.store.update(lambda tx: tx.create(task))
        return task.id

    def detach_network(self, node_id: str, attachment_id: str):
        """ResourceAllocator.DetachNetwork (allocator.go:83-124): only the
        owning node may detach; the task is deleted (the reference sets it
        to REMOVE for the reaper — deletion through the same path here)."""

        def txn(tx):
            t = tx.get_task(attachment_id)
            if t is None:
                raise ResourceError(f"attachment {attachment_id} not found")
            if t.node_id != node_id:
                raise ResourceError("attachment does not belong to this node")
            if t.spec.attachment is None:
                raise ResourceError(f"task {attachment_id} is not an attachment")
            t = t.copy()
            t.desired_state = TaskState.REMOVE
            tx.update(t)

        self.store.update(txn)
