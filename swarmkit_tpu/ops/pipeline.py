"""Sustained-load tick pipeline: hide the counts D2H under the previous
wave's commit work.

Through the dev tunnel a blocking device→host pull costs ~0.1 s fixed plus
bandwidth, which made the steady scheduler tick LOSE to the CPU oracle
(round-2 bench: 0.93× at 100k tasks × 10k nodes) even though the kernel
itself is 8× faster — ~88 % of the tick was the one synchronous counts
pull. The fix mirrors what burst framing did for the raft-replay and
global-diff kernels, applied to the tick structure instead of the kernel:

  wave k:   pull counts(k-1)            ← transfer already completed in
                                          the background (near-zero wait)
            fold_counts(k-1)            ← vectorized encoder fold, ~3 ms;
                                          all the next encode() needs
            encode(k) + dispatch(k)     ← fill + counts copy start riding
                                          the link asynchronously
            commit(k-1)                 ← the heavy host work (one
                                          add_task per placement, slot
                                          materialization, store writes)
                                          runs WHILE counts(k) transfer
            restamp_counts(k-1)         ← fingerprint stamp after add_task

The reorder is legal because `IncrementalEncoder.fold_counts` updates every
array the next `encode()` reads, while the deferred half (`add_task` loop +
`restamp_counts`) only matters for dirty-row detection — so it must merely
precede the NEXT encode's fingerprint scan, which `tick()` guarantees. When
external node mutations are pending (`nodes_clean` False — a node joined,
failed, or was updated between waves), the pipeline completes the deferred
commit first and falls back to the serial order for that wave; correctness
never depends on the overlap.

Placements stay bit-identical to the CPU oracle: the device state at
fill(k) equals the host's post-fold state plus the same quantization-
correction rows `after_apply` queues on the serial path (exercised at
scale by bench.py, at feature depth by tests/test_pipeline.py).

Reference hot loop this beats: manager/scheduler/scheduler.go:694-921 —
its commit (`applySchedulingDecisions`) is synchronous with the next
scheduling pass; here the commit IS the transfer window.
"""
from __future__ import annotations

import time
from typing import Callable

import numpy as np

from ..scheduler.encode import EncodedProblem, IncrementalEncoder
from .resident import PendingCounts, ResidentPlacement


class TickPipeline:
    """Drives ResidentPlacement ticks with the previous wave's commit
    overlapped under the in-flight counts copy.

    commit_cb(problem, counts) must perform EXACTLY one successful
    NodeInfo.add_task per placed task (the apply_counts contract) plus
    whatever store writes the caller needs; the pipeline brackets it with
    fold_counts (before the next encode) and restamp_counts (after).
    """

    def __init__(self, encoder: IncrementalEncoder,
                 resident: ResidentPlacement,
                 commit_cb: Callable[[EncodedProblem, np.ndarray], None]):
        self.encoder = encoder
        self.resident = resident
        self.commit_cb = commit_cb
        self._inflight: tuple[EncodedProblem, PendingCounts] | None = None
        self.timings: list[dict] = []      # per-wave phase seconds (bench)

    # ------------------------------------------------------------------ steps
    def _complete(self) -> tuple[EncodedProblem, np.ndarray, dict] | None:
        """Pull + fold the in-flight wave; commit stays with the caller."""
        if self._inflight is None:
            return None
        p, h = self._inflight
        self._inflight = None
        t0 = time.perf_counter()
        counts = h.get()
        pull_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        if not self.encoder.fold_counts(p, counts):
            # node set diverged under us: device carry is unusable
            self.resident.invalidate()
        self.resident.after_apply(p, counts)
        fold_s = time.perf_counter() - t0
        return p, counts, {"pull_s": pull_s, "fold_s": fold_s}

    def _commit(self, p: EncodedProblem, counts: np.ndarray) -> float:
        t0 = time.perf_counter()
        self.commit_cb(p, counts)
        self.encoder.restamp_counts(p, counts)
        return time.perf_counter() - t0

    # -------------------------------------------------------------------- API
    def tick(self, infos, groups, *, now=None, volume_set=None,
             ) -> tuple[EncodedProblem, np.ndarray] | None:
        """Dispatch one wave; completes (commits) the previous wave under
        the new wave's transfer. Returns the completed previous wave's
        (problem, counts), or None on the first call."""
        t_wave = time.perf_counter()
        prev = self._complete()
        timing = prev[2] if prev else {"pull_s": 0.0, "fold_s": 0.0}

        serial = prev is not None and not self.encoder.nodes_clean(infos)
        if serial:
            # external node changes: dirty rows must re-encode from infos
            # that already include the previous wave's tasks
            timing["commit_s"] = self._commit(prev[0], prev[1])

        t0 = time.perf_counter()
        p = self.encoder.encode(infos, groups, now=now,
                                volume_set=volume_set)
        timing["encode_s"] = time.perf_counter() - t0
        t0 = time.perf_counter()
        h = self.resident.schedule_async(p)
        timing["dispatch_s"] = time.perf_counter() - t0
        self._inflight = (p, h)

        if prev is not None and not serial:
            timing["commit_s"] = self._commit(prev[0], prev[1])
        timing["serial_fallback"] = serial
        timing["wall_s"] = time.perf_counter() - t_wave
        self._record(timing)
        return (prev[0], prev[1]) if prev else None

    def _record(self, timing: dict) -> None:
        # observability ring: a long-lived production driver must not
        # accumulate one dict per tick forever
        if len(self.timings) >= 4096:
            del self.timings[:2048]
        self.timings.append(timing)

    def flush(self) -> tuple[EncodedProblem, np.ndarray] | None:
        """Complete and commit the last in-flight wave (pipeline drain)."""
        prev = self._complete()
        if prev is None:
            return None
        p, counts, timing = prev
        timing["commit_s"] = self._commit(p, counts)
        timing["serial_fallback"] = False
        timing["encode_s"] = timing["dispatch_s"] = 0.0
        timing["wall_s"] = timing["pull_s"] + timing["fold_s"] \
            + timing["commit_s"]
        self._record(timing)
        return p, counts
