"""Sustained-load tick pipeline: hide the counts D2H under host work —
up to `depth` waves deep.

Through the dev tunnel a blocking device→host pull costs ~0.1 s fixed
plus bandwidth, which made the steady scheduler tick LOSE to the CPU
oracle (round-2 bench: 0.93× at 100k tasks × 10k nodes) even though the
kernel itself is 8× faster — ~88 % of the tick was the one synchronous
counts pull. Depth 1 mirrors what burst framing did for the raft-replay
and global-diff kernels, applied to the tick structure:

  wave k:   pull counts(k-1)            ← transfer rode the link in the
                                          background (near-zero wait)
            fold_counts(k-1)            ← vectorized encoder fold, ~3 ms;
                                          all the next encode() needs
            encode(k) + dispatch(k)     ← fill + counts copy start riding
                                          the link asynchronously
            commit(k-1)                 ← the heavy host work runs WHILE
                                          counts(k) transfer
            restamp_counts(k-1)         ← fingerprint stamp after add_task

With the wave-bulk + native commit (round 3) the commit shrank below the
tunnel's fixed RTT at node-heavy shapes, so one wave period no longer
covers the transfer — `depth=D` keeps D waves in flight, giving each
counts copy D full periods to ride the link. The device needs nothing
from the host between waves (its in-scan carry already folded every
dispatched wave, quantized); the HOST-side consequences of depth ≥ 2 are
handled here:

  * encode(k) runs before waves k-D+1..k-1 folded into the encoder —
    legal because their add_task/restamp didn't run either, so no node
    row looks dirty and nothing node-sized ships;
  * the problem emitted for wave k is stale by those pending waves;
    completion applies `encode.fold_problem` (the kernel's quantized
    in-scan fold) for each pending predecessor, in order, BEFORE the
    encoder fold / oracle parity / slot materialization consume it;
  * anything that would ship node rows mid-pipe would clobber the
    device's un-pulled folds, so the pipe DRAINS to serial first on:
    external node mutations (nodes_clean false), queued quantization
    corrections (resident pending rows), hypothetical service rows
    (row numbering is only stable once a fold allocates it), or a
    fold_problem shape mismatch.

Placements stay bit-identical to the CPU oracle at every depth
(tests/test_pipeline.py fuzzes depth ∈ {1, 2, 3} against the serial
path; bench.py exercises it at scale).

Reference hot loop this beats: manager/scheduler/scheduler.go:694-921 —
its commit (`applySchedulingDecisions`) is synchronous with the next
scheduling pass; here the commit and D-1 further whole waves ARE the
transfer window.
"""
from __future__ import annotations

import time
from collections import deque
from typing import Callable

import numpy as np

from ..scheduler.encode import (
    EncodedProblem,
    IncrementalEncoder,
    fold_problem,
)
from .resident import PendingCounts, ResidentPlacement


class TickPipeline:
    """Drives ResidentPlacement ticks with up to `depth` waves in flight.

    commit_cb(problem, counts) must perform EXACTLY one successful
    NodeInfo.add_task per placed task (the apply_counts contract) plus
    whatever store writes the caller needs; the pipeline brackets it
    with fold_counts (before the encoder next re-reads those arrays) and
    restamp_counts (after).
    """

    def __init__(self, encoder: IncrementalEncoder,
                 resident: ResidentPlacement,
                 commit_cb: Callable[[EncodedProblem, np.ndarray], None],
                 depth: int = 1):
        self.encoder = encoder
        self.resident = resident
        self.commit_cb = commit_cb
        self.depth = max(1, depth)
        # (problem, handle, n_pending): n_pending = how many dispatched-
        # but-unfolded waves preceded this one at its encode time
        self._inflight: deque[tuple] = deque()
        # completed (problem, counts) pairs still needed as fold sources
        self._recent: deque[tuple] = deque(maxlen=max(1, self.depth - 1))
        self.timings: list[dict] = []      # per-wave phase seconds (bench)

    # ------------------------------------------------------------------ steps
    def _complete(self) -> tuple[EncodedProblem, np.ndarray, dict] | None:
        """Pull + problem-fold + encoder-fold the OLDEST in-flight wave;
        commit stays with the caller."""
        if not self._inflight:
            return None
        p, h, n_pending = self._inflight.popleft()
        t0 = time.perf_counter()
        counts = h.get()
        pull_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        if n_pending:
            # bring the emitted problem up to the device's view: fold the
            # waves that were still in flight when it was encoded
            assert n_pending <= len(self._recent)
            for pp, cc in list(self._recent)[-n_pending:]:
                if not fold_problem(p, pp, cc):
                    # shapes moved under the pipe (shouldn't happen with
                    # the drain gates): device carry unusable
                    self.resident.invalidate()
                    break
        if not self.encoder.fold_counts(p, counts):
            # node set diverged under us: device carry is unusable
            self.resident.invalidate()
        self.resident.after_apply(p, counts)
        self._recent.append((p, counts))
        fold_s = time.perf_counter() - t0
        return p, counts, {"pull_s": pull_s, "fold_s": fold_s}

    def _commit(self, p: EncodedProblem, counts: np.ndarray) -> float:
        t0 = time.perf_counter()
        self.commit_cb(p, counts)
        self.encoder.restamp_counts(p, counts)
        return time.perf_counter() - t0

    def _hazards(self) -> bool:
        """True when dispatching another wave PAST the current in-flight
        ones would ship node rows (queued quantization corrections —
        their row SET would clobber the device's un-pulled in-scan
        folds) or create ambiguous service-row numbering (hypothetical
        rows only become stable once a fold allocates them). Irrelevant
        at depth 1, where the pipe is always empty at dispatch time."""
        return bool(self.resident.pending_rows
                    or any(p.has_hypo_rows for p, _h, _n in self._inflight))

    # -------------------------------------------------------------------- API
    def tick(self, infos, groups, *, now=None, volume_set=None,
             ) -> list[tuple[EncodedProblem, np.ndarray]]:
        """Dispatch one wave; completes (commits) the oldest in-flight
        wave once the pipe is `depth` deep. Returns the waves completed
        by this call — empty while the pipe is filling, one in steady
        state, up to `depth` on a drain."""
        t_wave = time.perf_counter()
        completed: list[tuple] = []
        timing = {"pull_s": 0.0, "fold_s": 0.0}
        # a completed-but-not-yet-committed wave (commits must stay FIFO
        # and must NEVER be dropped: fold_counts already ran for it)
        deferred: tuple | None = None

        def commit_deferred():
            nonlocal deferred
            if deferred is not None:
                timing["commit_s"] = (timing.get("commit_s", 0.0)
                                      + self._commit(*deferred))
                deferred = None

        def drain_serial():
            # the ONE drain sequence every trigger uses: any deferred
            # commit first (FIFO), then complete+commit everything left
            commit_deferred()
            while self._inflight:
                done = self._complete()
                timing["pull_s"] += done[2]["pull_s"]
                timing["fold_s"] += done[2]["fold_s"]
                timing["commit_s"] = (timing.get("commit_s", 0.0)
                                      + self._commit(done[0], done[1]))
                completed.append((done[0], done[1]))

        # external node mutations: drain fully so dirty rows re-encode
        # from infos that already include every wave's tasks
        serial = bool(self._inflight) \
            and not self.encoder.nodes_clean(infos)
        if serial:
            drain_serial()
        else:
            if len(self._inflight) >= self.depth:
                done = self._complete()
                timing.update(done[2])
                completed.append((done[0], done[1]))
                deferred = completed[-1]
            # hazards may have been CREATED by that completion (e.g.
            # after_apply queued corrections): re-check before dispatching
            # past anything still in flight
            if self._inflight and self._hazards():
                serial = True
                drain_serial()

        t0 = time.perf_counter()
        p = self.encoder.encode(infos, groups, now=now,
                                volume_set=volume_set)
        if self._inflight and self.resident.needs_full_upload(p):
            # bucket/vocab growth (new generic kind, node remap, stale
            # carry) forces a full re-upload, which would be built from
            # host arrays missing the in-flight waves' folds: drain,
            # then re-encode against the folded state
            serial = True
            drain_serial()
            p = self.encoder.encode(infos, groups, now=now,
                                    volume_set=volume_set)
        timing["encode_s"] = time.perf_counter() - t0
        t0 = time.perf_counter()
        h = self.resident.schedule_async(p)
        timing["dispatch_s"] = time.perf_counter() - t0
        self._inflight.append((p, h, len(self._inflight)))

        commit_deferred()
        timing["serial_fallback"] = serial
        timing["wall_s"] = time.perf_counter() - t_wave
        self._record(timing)
        return completed

    def _record(self, timing: dict) -> None:
        # observability ring: a long-lived production driver must not
        # accumulate one dict per tick forever
        if len(self.timings) >= 4096:
            del self.timings[:2048]
        self.timings.append(timing)

    def flush(self) -> list[tuple[EncodedProblem, np.ndarray]]:
        """Complete and commit every in-flight wave (pipeline drain),
        oldest first; one timings entry per completed wave."""
        out = []
        while self._inflight:
            p, counts, timing = self._complete()
            timing["commit_s"] = self._commit(p, counts)
            timing["serial_fallback"] = False
            timing["encode_s"] = timing["dispatch_s"] = 0.0
            timing["wall_s"] = timing["pull_s"] + timing["fold_s"] \
                + timing["commit_s"]
            self._record(timing)
            out.append((p, counts))
        return out
