"""Sustained-load tick pipeline: hide the counts D2H under host work —
up to `depth` waves deep.

Through the dev tunnel a blocking device→host pull costs ~0.1 s fixed
plus bandwidth, which made the steady scheduler tick LOSE to the CPU
oracle (round-2 bench: 0.93× at 100k tasks × 10k nodes) even though the
kernel itself is 8× faster — ~88 % of the tick was the one synchronous
counts pull. Depth 1 mirrors what burst framing did for the raft-replay
and global-diff kernels, applied to the tick structure:

  wave k:   pull counts(k-1)            ← transfer rode the link in the
                                          background (near-zero wait)
            fold_counts(k-1)            ← vectorized encoder fold, ~3 ms;
                                          all the next encode() needs
            encode(k) + dispatch(k)     ← fill + counts copy start riding
                                          the link asynchronously
            commit(k-1)                 ← the heavy host work runs WHILE
                                          counts(k) transfer
            restamp_counts(k-1)         ← fingerprint stamp after add_task

With the wave-bulk + native commit (round 3) the commit shrank below the
tunnel's fixed RTT at node-heavy shapes, so one wave period no longer
covers the transfer — `depth=D` keeps D waves in flight, giving each
counts copy D full periods to ride the link. The device needs nothing
from the host between waves (its in-scan carry already folded every
dispatched wave, quantized); the HOST-side consequences of depth ≥ 2 are
handled here:

  * encode(k) runs before waves k-D+1..k-1 folded into the encoder —
    legal because their add_task/restamp didn't run either, so no node
    row looks dirty and nothing node-sized ships;
  * the problem emitted for wave k is stale by those pending waves;
    completion applies `encode.fold_problem` (the kernel's quantized
    in-scan fold) for each pending predecessor, in order, BEFORE the
    encoder fold / oracle parity / slot materialization consume it;
  * anything that would ship node rows mid-pipe would clobber the
    device's un-pulled folds, so the pipe DRAINS to serial first on:
    external node mutations (nodes_clean false), queued quantization
    corrections (resident pending rows), hypothetical service rows
    (row numbering is only stable once a fold allocates it), or a
    fold_problem shape mismatch.

Round 6 — the ASYNC COMMIT PLANE (`async_commit=True`): even fully
pipelined, the commit's heavy half (slot materialization, the native
add_task segment walk, store write-back, fingerprint restamp) still ran
serially inside every wave period — round-5 bench: ~3/4 of the e2e wave
at the north-star shape. None of it is needed by the NEXT wave's
encode/dispatch, so it moves to ONE background CommitWorker
(ops/commit.py), strict FIFO, and overlaps the next wave's device
dispatch and D2H pull (the blocking pull wait releases the GIL — that
is exactly when the worker runs). What stays synchronous is exactly
what the invariants above require:

  * `fold_counts` + `after_apply` run on the wave loop at completion,
    BEFORE the next encode/dispatch (parity depends on the correction
    rows being known before anything else ships);
  * every tick takes a worker BARRIER before the dirty scan
    (`nodes_clean`), so the deferred add_task/restamp of wave k is
    fully retired before any fingerprint is read — and therefore
    before every drain trigger (external mutations, pending correction
    rows, hypo rows, resident signature change), all of which are
    evaluated post-barrier;
  * the wave's heavy half is enqueued only AFTER this tick's
    encode+dispatch returned, so the encoder is never read mid-walk;
  * a worker exception re-raises out of the NEXT tick's barrier (never
    dies with the thread); the caller owns the heal.

ENCODE/COMMIT OVERLAP (round 6, tracked encoders only): with
`IncrementalEncoder(tracked=True)` a steady tick's nodes_clean check and
its zero-scan encode read neither NodeInfo objects nor fingerprints —
the only host state the riding heavy commit mutates — so when the O(1)
tracked-clean gate holds, the top-of-tick barrier is SKIPPED and the
completed wave's heavy half is submitted BEFORE encode: the encode and
dispatch of wave k+1 run concurrently with commit(k)'s add_task walk +
store write-back + restamp. The moment the gate breaks (pending marks,
node churn, a failed worker, any drain trigger) the tick falls back to
today's serial order — barrier first, heavy submitted after dispatch.
drain_serial always barriers as its first step, so inline commits never
run beside (or ahead of) a riding heavy, and FIFO wave order holds.

Placements stay bit-identical to the CPU oracle at every depth and in
both commit modes (tests/test_pipeline.py fuzzes depth ∈ {1, 2, 3} and
async against the serial path; bench.py exercises both at scale).

Reference hot loop this beats: manager/scheduler/scheduler.go:694-921 —
its commit (`applySchedulingDecisions`) is synchronous with the next
scheduling pass; here the commit and D-1 further whole waves ARE the
transfer window, and the commit itself rides a background plane.
"""
from __future__ import annotations

import functools
import time
from collections import deque
from typing import Callable

import numpy as np

from ..scheduler.encode import (
    EncodedProblem,
    IncrementalEncoder,
    fold_problem,
)
from ..utils import trace
from .commit import CommitWorker
from .resident import PendingCounts, ResidentPlacement

# stage-timing keys -> span names filed into the trace plane per wave
# (utils/trace.py; pull_s is the real value pull — the tunnel rule's one
# device_sync span per burst, never one per kernel). dirty_scan_s is the
# host tail ISSUE 6 hunts: the encoder's sort + fingerprint scan (plus
# the nodes_clean pre-check), ~0 on the tracked zero-scan path.
_STAGE_SPANS = (("barrier_s", "tick.barrier"),
                ("pull_s", "tick.device_sync"),
                ("fold_s", "tick.fold"),
                ("dirty_scan_s", "tick.dirty_scan"),
                ("encode_s", "tick.encode"),
                ("dispatch_s", "tick.dispatch"),
                ("commit_s", "tick.commit"))


class TickPipeline:
    """Drives ResidentPlacement ticks with up to `depth` waves in flight.

    commit_cb(problem, counts) must perform EXACTLY one successful
    NodeInfo.add_task per placed task (the apply_counts contract) plus
    whatever store writes the caller needs; the pipeline brackets it
    with fold_counts (before the encoder next re-reads those arrays) and
    restamp_counts (after).

    async_commit=True runs commit_cb + restamp on a single background
    CommitWorker (FIFO, barriered at the top of every tick and on every
    drain) — commit_cb must then touch ONLY state that nothing else
    reads between the enqueue and the next barrier (NodeInfo objects,
    the store, the encoder's fingerprint stamp). The contract cuts both
    ways: a CALLER that mutates NodeInfos between ticks (node churn,
    external add_task) must call barrier() FIRST — tick()'s own barrier
    runs before its dirty scan, but by then an inter-tick mutation
    would already have raced the riding walk. The production Scheduler
    honors this via _drain_commit_plane in its event handler.
    """

    def __init__(self, encoder: IncrementalEncoder,
                 resident: ResidentPlacement,
                 commit_cb: Callable[[EncodedProblem, np.ndarray], None],
                 depth: int = 1, async_commit: bool = False):
        self.encoder = encoder
        self.resident = resident
        self.commit_cb = commit_cb
        self.depth = max(1, depth)
        self.worker = CommitWorker(name="tick-commit") if async_commit \
            else None
        # (problem, handle, n_pending): n_pending = how many dispatched-
        # but-unfolded waves preceded this one at its encode time
        self._inflight: deque[tuple] = deque()
        # completed (problem, counts) pairs still needed as fold sources
        self._recent: deque[tuple] = deque(maxlen=max(1, self.depth - 1))
        self.timings: list[dict] = []      # per-wave phase seconds (bench)

    # ------------------------------------------------------------------ steps
    def _pull_oldest(self) -> tuple:
        """Pop + pull the oldest in-flight wave WITHOUT folding it.
        In async mode this runs BEFORE the commit barrier: the blocking
        transfer wait releases the GIL, so the worker's in-flight heavy
        commit executes under it — the plane's core overlap."""
        p, h, n_pending = self._inflight.popleft()
        t0 = time.perf_counter()
        counts = h.get()
        return p, counts, n_pending, time.perf_counter() - t0

    def _fold_pulled(self, p: EncodedProblem, counts: np.ndarray,
                     n_pending: int) -> float:
        """The completion's synchronous half: problem-fold (deep pipe),
        encoder array fold, correction-row bookkeeping. Must precede the
        next encode(); in async mode it runs post-barrier."""
        t0 = time.perf_counter()
        if n_pending:
            # bring the emitted problem up to the device's view: fold the
            # waves that were still in flight when it was encoded
            assert n_pending <= len(self._recent)
            for pp, cc in list(self._recent)[-n_pending:]:
                if not fold_problem(p, pp, cc):
                    # shapes moved under the pipe (shouldn't happen with
                    # the drain gates): device carry unusable
                    self.resident.invalidate()
                    break
        if not self.encoder.fold_counts(p, counts):
            # node set diverged under us: device carry is unusable
            self.resident.invalidate()
        self.resident.after_apply(p, counts)
        self._recent.append((p, counts))
        return time.perf_counter() - t0

    def _complete(self) -> tuple[EncodedProblem, np.ndarray, dict] | None:
        """Pull + problem-fold + encoder-fold the OLDEST in-flight wave;
        commit stays with the caller."""
        if not self._inflight:
            return None
        p, counts, n_pending, pull_s = self._pull_oldest()
        fold_s = self._fold_pulled(p, counts, n_pending)
        return p, counts, {"pull_s": pull_s, "fold_s": fold_s}

    def _heavy(self, p: EncodedProblem, counts: np.ndarray) -> None:
        """The commit's heavy half: caller's add_task/store work, then
        the fingerprint restamp. Runs inline (sync mode, drains) or on
        the CommitWorker (async mode)."""
        self.commit_cb(p, counts)
        self.encoder.restamp_counts(p, counts)

    def _commit(self, p: EncodedProblem, counts: np.ndarray) -> float:
        t0 = time.perf_counter()
        self._heavy(p, counts)
        return time.perf_counter() - t0

    def _barrier(self, timing: dict | None = None) -> None:
        """Retire every enqueued heavy commit (async mode). Worker
        exceptions re-raise HERE — i.e. into the next tick."""
        if self.worker is None or self.worker.idle:
            if self.worker is not None:
                self.worker.barrier()     # raises a captured exception
            return
        t0 = time.perf_counter()
        self.worker.barrier()
        if timing is not None:
            timing["barrier_s"] += time.perf_counter() - t0

    def _hazards(self) -> bool:
        """True when dispatching another wave PAST the current in-flight
        ones would ship node rows (queued quantization corrections —
        their row SET would clobber the device's un-pulled in-scan
        folds) or create ambiguous service-row numbering (hypothetical
        rows only become stable once a fold allocates them). Irrelevant
        at depth 1, where the pipe is always empty at dispatch time."""
        return bool(self.resident.pending_rows
                    or any(p.has_hypo_rows for p, _h, _n in self._inflight))

    # -------------------------------------------------------------------- API
    def tick(self, infos, groups, *, now=None, volume_set=None,
             ) -> list[tuple[EncodedProblem, np.ndarray]]:
        """Dispatch one wave; completes (commits) the oldest in-flight
        wave once the pipe is `depth` deep. Returns the waves completed
        by this call — empty while the pipe is filling, one in steady
        state, up to `depth` on a drain. In async mode a returned wave's
        heavy commit may still be riding the worker; it is retired by
        the next tick's barrier (or flush())."""
        # wave root span (trace plane): stage recs file under it; the
        # async heavy commit links back to it via trace.wrap below.
        # Off-stack (trace.start) so an exception mid-tick cannot corrupt
        # the thread's implicit-parent stack; None when disarmed — one
        # truthiness test, nothing allocated. try/finally so a FAILING
        # wave's span (error attr + whatever stages it measured) still
        # reaches the flight recorder — that wave is exactly the
        # forensics payload, and the mirrored Scheduler path records its
        # failed sched.tick the same way.
        _sp = trace.start("tick.wave", inflight=len(self._inflight))
        timing = {"pull_s": 0.0, "fold_s": 0.0, "barrier_s": 0.0,
                  "dirty_scan_s": 0.0}
        try:
            return self._tick_traced(infos, groups, now, volume_set,
                                     timing, _sp)
        except BaseException as exc:
            if _sp is not None:
                _sp.attrs.setdefault("error", repr(exc))
            raise
        finally:
            if _sp is not None:
                self._file_stage_spans(timing, _sp)
                _sp.end(serial=bool(timing.get("serial_fallback")))

    def _tick_traced(self, infos, groups, now, volume_set, timing,
                     _sp) -> list[tuple[EncodedProblem, np.ndarray]]:
        t_wave = time.perf_counter()
        completed: list[tuple] = []
        # a completed-but-not-yet-committed wave (commits must stay FIFO
        # and must NEVER be dropped: fold_counts already ran for it)
        deferred: tuple | None = None
        # async mode: pulled-but-not-yet-folded oldest wave
        pulled: tuple | None = None

        # encode/commit overlap gate (round 6): with a TRACKED encoder and
        # no pending marks, this tick's nodes_clean and encode read NO
        # NodeInfo and NO fingerprint — exactly the state the riding heavy
        # commit mutates — so the top-of-tick barrier may be skipped and
        # the zero-scan encode below runs CONCURRENTLY with the previous
        # wave's heavy half. The gate is O(1) (mark flags + a length
        # check) and never reads what the worker writes; a failed worker
        # closes it so the pending exception re-raises at the barrier.
        # Every drain trigger still barriers (drain_serial's first step).
        overlap = False
        if self.worker is not None:
            if len(self._inflight) >= self.depth:
                p0, c0, np0, pull_s = self._pull_oldest()
                timing["pull_s"] += pull_s
                pulled = (p0, c0, np0)
            t0 = time.perf_counter()
            overlap = (self.encoder.tracked
                       and self.encoder.nodes_clean(infos)
                       and not self.worker.failed)
            timing["dirty_scan_s"] = time.perf_counter() - t0
            if not overlap:
                # barrier BEFORE any host-state read: the previous waves'
                # add_task/restamp must be fully retired before the dirty
                # scan below (and before every drain trigger). Worker
                # exceptions propagate into this tick here.
                self._barrier(timing)

        def finish_pulled():
            nonlocal pulled
            if pulled is None:
                return None
            p, c, n_p = pulled
            pulled = None
            timing["fold_s"] += self._fold_pulled(p, c, n_p)
            completed.append((p, c))
            return (p, c)

        def commit_deferred(sync: bool = False):
            # sync=True (drains, serial fallback): the heavy half must
            # complete before this tick reads/ships node state again.
            # sync=False (steady async): enqueue; the NEXT tick's
            # barrier retires it.
            nonlocal deferred
            if deferred is None:
                return
            p, c = deferred
            deferred = None
            if self.worker is not None and not sync:
                # the heavy half joins THIS wave's trace (the tick that
                # pulled + folded it); trace.wrap is identity when disarmed
                try:
                    self.worker.submit(trace.wrap(
                        "tick.commit_heavy",
                        functools.partial(self._heavy, p, c), parent=_sp))
                except BaseException:
                    # overlap window: a riding heavy failed post-gate and
                    # submit refused this wave, whose fold already ran —
                    # poison its placed-on rows + the carry so the
                    # caller's heal (poison_all_numeric / re-encode)
                    # starts from honest state
                    self.encoder.force_numeric_reencode(
                        np.flatnonzero(c.sum(axis=0)))
                    self.resident.invalidate()
                    raise
            else:
                timing["commit_s"] = (timing.get("commit_s", 0.0)
                                      + self._commit(p, c))

        def drain_serial():
            # the ONE drain sequence every trigger uses, always post-
            # barrier: any deferred/pulled wave first (FIFO — it is the
            # oldest), then complete+commit everything left, inline.
            # The barrier here is a no-op on the ordinary async path
            # (taken at tick top) but REQUIRED on the overlap path,
            # where the top barrier was skipped — an inline commit must
            # never run concurrently with (or ahead of) a riding heavy.
            self._barrier(timing)
            commit_deferred(sync=True)
            done = finish_pulled()
            if done is not None:
                timing["commit_s"] = (timing.get("commit_s", 0.0)
                                      + self._commit(*done))
            while self._inflight:
                done = self._complete()
                timing["pull_s"] += done[2]["pull_s"]
                timing["fold_s"] += done[2]["fold_s"]
                timing["commit_s"] = (timing.get("commit_s", 0.0)
                                      + self._commit(done[0], done[1]))
                completed.append((done[0], done[1]))

        # external node mutations: drain fully so dirty rows re-encode
        # from infos that already include every wave's tasks
        if overlap:
            serial = False      # the gate already proved nodes_clean
        else:
            t0 = time.perf_counter()
            serial = bool(self._inflight or pulled) \
                and not self.encoder.nodes_clean(infos)
            timing["dirty_scan_s"] += time.perf_counter() - t0
        if serial:
            drain_serial()
        else:
            if pulled is not None:
                deferred = finish_pulled()
            elif len(self._inflight) >= self.depth:
                done = self._complete()
                timing["pull_s"] += done[2]["pull_s"]
                timing["fold_s"] += done[2]["fold_s"]
                completed.append((done[0], done[1]))
                deferred = completed[-1]
            # hazards may have been CREATED by that completion (e.g.
            # after_apply queued corrections): re-check before dispatching
            # past anything still in flight
            if self._inflight and self._hazards():
                serial = True
                drain_serial()
            elif overlap and deferred is not None:
                # overlap: the completed wave's heavy half goes to the
                # worker NOW, so the zero-scan encode below runs under
                # it — in the barriered order it waits until after
                # encode+dispatch and only overlaps the NEXT tick's pull
                commit_deferred()

        t0 = time.perf_counter()
        p = self.encoder.encode(infos, groups, now=now,
                                volume_set=volume_set)
        if self._inflight and self.resident.needs_full_upload(p):
            # bucket/vocab growth (new generic kind, node remap, stale
            # carry) forces a full re-upload, which would be built from
            # host arrays missing the in-flight waves' folds: drain,
            # then re-encode against the folded state
            serial = True
            drain_serial()
            p = self.encoder.encode(infos, groups, now=now,
                                    volume_set=volume_set)
        timing["encode_s"] = time.perf_counter() - t0
        # the scan component of encode() (sort + fingerprint compare; ~0
        # on the tracked zero-scan path) files as its own stage so
        # BENCH_r06 can see where the host tail went
        timing["dirty_scan_s"] += self.encoder.last_scan_s
        timing["encode_s"] = max(
            0.0, timing["encode_s"] - self.encoder.last_scan_s)
        t0 = time.perf_counter()
        h = self.resident.schedule_async(p)
        timing["dispatch_s"] = time.perf_counter() - t0
        self._inflight.append((p, h, len(self._inflight)))

        # steady async (barriered order): the heavy half goes to the
        # worker only now, after encode+dispatch stopped reading host
        # state for this tick. On the overlap path it was submitted
        # before encode (deferred is None here) and this is a no-op.
        commit_deferred()
        timing["serial_fallback"] = serial
        timing["commit_overlapped"] = overlap
        timing["wall_s"] = time.perf_counter() - t_wave
        self._record(timing)
        return completed

    @staticmethod
    def _file_stage_spans(timing: dict, parent) -> None:
        """File one completed span per measured nonzero stage (armed
        only; the measurements already exist in `timing`)."""
        # 7 fixed stage keys per WAVE (never per entry), and rec() is
        # one truthiness test disarmed
        for key, name in _STAGE_SPANS:
            v = timing.get(key)
            if v:
                trace.rec(name, v, parent=parent)  # lint: allow(span-in-loop)

    def _record(self, timing: dict) -> None:
        # observability ring: a long-lived production driver must not
        # accumulate one dict per tick forever
        if len(self.timings) >= 4096:
            del self.timings[:2048]
        self.timings.append(timing)

    def flush(self) -> list[tuple[EncodedProblem, np.ndarray]]:
        """Complete and commit every in-flight wave (pipeline drain),
        oldest first; one timings entry per completed wave. In async
        mode the worker is barriered first, so on return NOTHING rides
        the plane (worker exceptions re-raise here)."""
        out = []
        # span opened BEFORE the barrier and ended in a finally: a
        # poisoned worker re-raising here (or a failing drain commit)
        # still files the flush span + its error for the forensics tail
        _sp = trace.start("tick.flush")
        try:
            self._barrier()
            while self._inflight:
                p, counts, timing = self._complete()
                timing["commit_s"] = self._commit(p, counts)
                timing["serial_fallback"] = False
                timing["barrier_s"] = 0.0
                timing["encode_s"] = timing["dispatch_s"] = 0.0
                timing["wall_s"] = timing["pull_s"] + timing["fold_s"] \
                    + timing["commit_s"]
                self._record(timing)
                if _sp is not None:
                    self._file_stage_spans(timing, _sp)
                out.append((p, counts))
        except BaseException as exc:
            if _sp is not None:
                _sp.attrs.setdefault("error", repr(exc))
            raise
        finally:
            if _sp is not None:
                _sp.end(waves=len(out))
        return out

    def barrier(self) -> None:
        """Public commit barrier: callers MUST take it before mutating
        any NodeInfo between ticks in async mode (the riding heavy
        commit walks those same objects). No-op in sync mode; worker
        exceptions re-raise here."""
        self._barrier()

    def close(self) -> None:
        """Stop the commit worker thread (async mode; idempotent). Does
        not flush — call flush() first on an orderly shutdown."""
        if self.worker is not None:
            self.worker.close()
