"""Shared little-endian bit-pack/unpack pair for cold bool-matrix uploads.

Through the tunneled dev link (single-digit MB/s D2H/H2D) the wire bytes
of a cold [R, C] bool upload dominate its cost; shipping uint8 words
(8x fewer bytes) and unpacking device-side is the round-4-verdict move
used by both the raft ack matrix (ops/raft_replay.py) and the global-diff
eligibility matrix (ops/reconcile.py). This module is the single home of
that pair so a backend quirk fix lands once.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def pack_bits(rows) -> "np.ndarray":
    """Host half: bool[R, C] -> uint8[R, ceil(C/8)], little bit order."""
    import numpy as np

    return np.packbits(np.asarray(rows, bool), axis=1, bitorder="little")


@functools.partial(jax.jit, static_argnames=("n_cols",))
def unpack_bits(packed, n_cols: int):
    """Device half: uint8[R, ceil(C/8)] -> bool[R, C]."""
    idx = jnp.arange(n_cols, dtype=jnp.int32)
    words = packed[:, idx // 8]
    return ((words >> (idx % 8).astype(jnp.uint8)) & 1).astype(bool)
