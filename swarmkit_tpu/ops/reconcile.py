"""Batched global-service reconciliation: desired-vs-actual set diff.

The reference's global orchestrator walks every (service, node) pair in Go,
comparing the eligible-node set against the set of nodes that already carry a
runnable task (manager/orchestrator/global/global.go:254-487,
reconcileServices/reconcileOneNode). At fleet scale that product is the same
tasks×nodes shape the scheduler batches, so the decision matrix is computed
here as one jitted program (BASELINE.md: "Global-service reconciliation:
50k desired vs actual diff → vmap set-diff"):

    has[s, n]      = any runnable task of service s on node n
                     (scatter of each service's padded task→node id list)
    create[s, n]   = eligible[s, n] ∧ ¬has[s, n]     (node missing its task)
    shutdown[s, n] = ¬eligible[s, n] ∧ has[s, n]     (task must drain)

Eligibility itself is string/constraint work and stays host-side (the same
split as the scheduler's extra_mask — SURVEY.md §7); this kernel owns the
O(S×N) set algebra. `swarmkit_tpu.orchestrator.global_.bulk_reconcile` is the
store-integrated consumer.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# below this S×N product the numpy fallback wins (device round-trip costs
# more than the diff); mirrors the scheduler's JAX_THRESHOLD idea
DIFF_THRESHOLD = 1_000_000


@jax.jit
def global_diff(eligible, task_nodes):
    """eligible: bool[S, N]; task_nodes: int32[S, T] — for each service the
    node indices of its runnable tasks, padded with -1 (T = max per service).
    Returns (create bool[S, N], shutdown bool[S, N])."""
    S, N = eligible.shape
    rows = jnp.broadcast_to(jnp.arange(S)[:, None], task_nodes.shape)
    cols = jnp.clip(task_nodes, 0, N - 1)
    has = jnp.zeros((S, N), bool).at[rows, cols].max(task_nodes >= 0)
    return eligible & ~has, ~eligible & has


@jax.jit
def global_diff_update(eligible, task_nodes, upd_rows, upd_cols, upd_vals):
    """Device-resident diff step: eligibility and the task→node table LIVE
    on device; a round uploads only the churned slots (task moves/deaths
    as (service row, slot col, new node) triples) and recomputes the diff.
    Returns (task_nodes', create, shutdown) — task_nodes' is the next
    round's carry."""
    task_nodes = task_nodes.at[upd_rows, upd_cols].set(upd_vals)
    create, shutdown = _diff(eligible, task_nodes)
    return task_nodes, create, shutdown


@functools.partial(jax.jit, static_argnames=("cap",))
def global_diff_update_compact(eligible, task_nodes, upd_rows, upd_cols,
                               upd_vals, cap: int):
    """global_diff_update, but the decisions come back as COMPACT index
    lists instead of dense [S, N] matrices: in a converged cluster the
    diff is churn-sized, and the dense pull (tens of MB) would dominate a
    high-latency link. Returns (task_nodes', create_idx[cap, 2],
    shutdown_idx[cap, 2], n_create, n_shutdown); index rows beyond the
    real count are (-1, -1). If a diff overflows `cap` the counts exceed
    cap and the caller falls back to a dense pull."""
    task_nodes = task_nodes.at[upd_rows, upd_cols].set(upd_vals)
    create, shutdown = _diff(eligible, task_nodes)

    def compact(m):
        s_idx, n_idx = jnp.nonzero(m, size=cap, fill_value=-1)
        return jnp.stack([s_idx, n_idx], axis=1).astype(jnp.int32), \
            jnp.sum(m).astype(jnp.int32)

    c_idx, n_c = compact(create)
    s_idx, n_s = compact(shutdown)
    return task_nodes, c_idx, s_idx, n_c, n_s


def pack_eligibility(eligible) -> "np.ndarray":
    """Host half of the bit-packed eligibility upload: bool[S, N] →
    uint8[S, ceil(N/8)] (little bit order), 8× fewer wire bytes. Pair
    with `unpack_eligibility` device-side — through the dev tunnel the
    [S, N] bool matrix is the cold upload's whale (round-4 verdict #5,
    the same move as the resident svc-matrix fix)."""
    from .bitpack import pack_bits

    return pack_bits(eligible)


def unpack_eligibility(packed, n_nodes: int):
    """uint8[S, ceil(N/8)] → bool[S, N], device-side."""
    from .bitpack import unpack_bits

    return unpack_bits(packed, n_nodes)


@functools.partial(jax.jit, static_argnames=("n_nodes",))
def task_count_flat(task_nodes, n_nodes: int):
    """cnt[s * n_nodes + n] = number of runnable tasks of service s on
    node n — the resident carry for the O(churn) incremental diff below.
    Kept FLAT deliberately: this backend's 2D scatter-add lowering is
    broken above ~512 updates (wrong flat offsets), while 1D scatters are
    correct at every size probed — see tests/test_reconcile_kernel.py's
    churn fuzz, which would catch a regression either way."""
    S, T = task_nodes.shape
    flat_idx = (jnp.arange(S, dtype=jnp.int32)[:, None] * n_nodes
                + jnp.clip(task_nodes, 0, n_nodes - 1)).reshape(-1)
    return jnp.zeros(S * n_nodes, jnp.int32).at[flat_idx].add(
        (task_nodes >= 0).reshape(-1).astype(jnp.int32))


def _churn_step(eligible, task_nodes, cnt_flat, rows, cols, vals):
    N = eligible.shape[1]
    old = task_nodes[rows, cols]                               # [U]
    task_nodes = task_nodes.at[rows, cols].set(vals)
    old_v = old >= 0
    new_v = vals >= 0
    oldc = jnp.clip(old, 0)
    newc = jnp.clip(vals, 0)
    cnt_flat = cnt_flat.at[rows * N + oldc].add(jnp.where(old_v, -1, 0))
    cnt_flat = cnt_flat.at[rows * N + newc].add(jnp.where(new_v, 1, 0))

    pr = jnp.concatenate([rows, rows])
    pn = jnp.concatenate([oldc, newc])
    valid = jnp.concatenate([old_v, new_v])
    elig_p = eligible[pr, pn]
    cnt_p = cnt_flat[pr * N + pn]
    create = valid & elig_p & (cnt_p == 0)
    shutdown = valid & ~elig_p & (cnt_p > 0)
    pairs = jnp.stack([pr, pn], axis=1).astype(jnp.int32)
    return task_nodes, cnt_flat, pairs, create, shutdown, valid


@jax.jit
def global_diff_churn_burst(eligible, task_nodes, cnt_flat,
                            rows_b, cols_b, vals_b):
    """A debounced reconcile pass: B churn rounds ([B, U] each) applied in
    one device program (lax.scan). One upload + one dispatch + one pull
    per burst — on a high-latency link the per-call sync would otherwise
    dominate the O(churn) work. The global orchestrator's event debounce
    produces exactly this shape of batch.

    Returns (task_nodes', cnt_flat', codes uint8[B, 2U]): per round, for
    the touched pair i (< U: the slot's OLD node; >= U: the NEW one),
    bit0 = create, bit1 = shutdown, bit2 = valid. The PAIR coordinates
    are deliberately NOT returned — the caller's own events name the
    moved tasks' old/new nodes, and shipping redundant indices would
    quadruple the D2H payload."""

    def step(carry, x):
        tn, cnt = carry
        r, c, v = x
        tn, cnt, _pairs, cre, shut, valid = _churn_step(
            eligible, tn, cnt, r, c, v)
        codes = (cre.astype(jnp.uint8)
                 | (shut.astype(jnp.uint8) << 1)
                 | (valid.astype(jnp.uint8) << 2))
        return (tn, cnt), codes

    (task_nodes, cnt_flat), codes = jax.lax.scan(
        step, (task_nodes, cnt_flat), (rows_b, cols_b, vals_b))
    return task_nodes, cnt_flat, codes


@jax.jit
def global_diff_churn(eligible, task_nodes, cnt_flat, rows, cols, vals):
    """O(churn) incremental reconcile step. State on device: eligibility,
    the task→node table, and the FLAT per-(service, node) task-count
    array (task_count_flat). A round uploads churned slots as (service,
    slot, new node) triples — slots must be unique within one round (a
    task moves once) — and returns the new carries plus the decisions at
    every touched (service, node) pair:

        pairs[2U, 2], create[2U], shutdown[2U], valid[2U]

    (pair i < U is the slot's OLD node, i >= U the NEW one; old/new of -1
    produce a (s, 0) pair with valid=False — callers drop those).
    Decisions anywhere else are unchanged from the previous round, which
    is the point: the consumer updates its view instead of re-reading an
    [S, N] matrix."""
    return _churn_step(eligible, task_nodes, cnt_flat, rows, cols, vals)


def _diff(eligible, task_nodes):
    S, N = eligible.shape
    rows = jnp.broadcast_to(jnp.arange(S)[:, None], task_nodes.shape)
    cols = jnp.clip(task_nodes, 0, N - 1)
    has = jnp.zeros((S, N), bool).at[rows, cols].max(task_nodes >= 0)
    return eligible & ~has, ~eligible & has


# ------------------------------------------------- replicated slot state
# ISSUE 14: the REPLICATED orchestrator's per-service slot census — the
# batched reconciler's one vectorized pass over the columnar task table.
# A slot is "used" when any desired<=RUNNING task occupies it, "runnable"
# when any of its tasks is runnable, "running" when any is observed
# RUNNING. All scatters are FLAT 1D (s * n_slots + slot) per the broken
# 2D-scatter-add rule (see task_count_flat above).

@functools.partial(jax.jit, static_argnames=("n_services", "n_slots"))
def replica_slot_state(service_idx, slot, runnable, running,
                       n_services: int, n_slots: int):
    """service_idx int32[T], slot int32[T] (already clipped to
    [0, n_slots)), runnable/running bool[T]. Returns (slot_used,
    slot_runnable, slot_running) as flat bool[n_services * n_slots]
    plus runnable_slots int32[n_services]."""
    key = service_idx * n_slots + slot
    flat = n_services * n_slots
    used = jnp.zeros(flat, bool).at[key].max(True)
    slot_runnable = jnp.zeros(flat, bool).at[key].max(runnable)
    slot_running = jnp.zeros(flat, bool).at[key].max(running)
    runnable_slots = slot_runnable.reshape(
        n_services, n_slots).sum(axis=1).astype(jnp.int32)
    return used, slot_runnable, slot_running, runnable_slots


def replica_slot_state_np(service_idx, slot, runnable, running,
                          n_services: int, n_slots: int):
    """numpy mirror of `replica_slot_state` (small-scale path and parity
    oracle — exact boolean algebra, identical either way)."""
    import numpy as np

    # HOST numpy only (never traced): 64-bit keys so a 100k-service
    # census cannot overflow the flat index — the jit twin above stays
    # int32 under the no-x64 rule  # lint: allow(int64-in-kernel)
    key = service_idx.astype(np.int64) * n_slots + slot
    flat = n_services * n_slots
    used = np.zeros(flat, bool)
    used[key] = True
    slot_runnable = np.zeros(flat, bool)
    np.maximum.at(slot_runnable, key, runnable)
    slot_running = np.zeros(flat, bool)
    np.maximum.at(slot_running, key, running)
    runnable_slots = slot_runnable.reshape(
        n_services, n_slots).sum(axis=1).astype(np.int32)
    return used, slot_runnable, slot_running, runnable_slots


def compute_slot_state(service_idx, slot, runnable, running,
                       n_services: int, n_slots: int):
    """Backend-selecting wrapper (the compute_diff shape): TPU kernel
    above DIFF_THRESHOLD on the flat census size, numpy below — and
    numpy AGAIN above 2^31 cells: the kernel's flat key is int32 (no
    x64 in kernels) and would silently WRAP, the same
    wrong-results-without-error class as the 2D scatter-add bug; the
    numpy mirror's int64 keys are exact at any size."""
    import numpy as np

    flat = n_services * n_slots
    if DIFF_THRESHOLD <= flat < 2 ** 31:
        out = replica_slot_state(
            jnp.asarray(service_idx, jnp.int32), jnp.asarray(slot, jnp.int32),
            jnp.asarray(runnable), jnp.asarray(running),
            n_services, n_slots)
        return tuple(np.asarray(a) for a in out)
    return replica_slot_state_np(
        np.asarray(service_idx, np.int32), np.asarray(slot, np.int32),
        np.asarray(runnable), np.asarray(running), n_services, n_slots)


def global_diff_np(eligible, task_nodes):
    """numpy mirror of `global_diff` (small-scale path and parity oracle)."""
    import numpy as np

    S, N = eligible.shape
    has = np.zeros((S, N), bool)
    valid = task_nodes >= 0
    rows = np.broadcast_to(np.arange(S)[:, None], task_nodes.shape)[valid]
    has[rows, task_nodes[valid]] = True
    return eligible & ~has, ~eligible & has


def compute_diff(eligible, task_nodes):
    """Backend-selecting wrapper: TPU kernel above DIFF_THRESHOLD, numpy
    below. Output is identical either way (both are exact set algebra)."""
    import numpy as np

    S, N = eligible.shape
    if S * N >= DIFF_THRESHOLD:
        create, shutdown = global_diff(jnp.asarray(eligible),
                                       jnp.asarray(task_nodes))
        return np.asarray(create), np.asarray(shutdown)
    return global_diff_np(np.asarray(eligible), np.asarray(task_nodes))
