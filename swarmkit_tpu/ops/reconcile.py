"""Batched global-service reconciliation: desired-vs-actual set diff.

The reference's global orchestrator walks every (service, node) pair in Go,
comparing the eligible-node set against the set of nodes that already carry a
runnable task (manager/orchestrator/global/global.go:254-487,
reconcileServices/reconcileOneNode). At fleet scale that product is the same
tasks×nodes shape the scheduler batches, so the decision matrix is computed
here as one jitted program (BASELINE.md: "Global-service reconciliation:
50k desired vs actual diff → vmap set-diff"):

    has[s, n]      = any runnable task of service s on node n
                     (scatter of each service's padded task→node id list)
    create[s, n]   = eligible[s, n] ∧ ¬has[s, n]     (node missing its task)
    shutdown[s, n] = ¬eligible[s, n] ∧ has[s, n]     (task must drain)

Eligibility itself is string/constraint work and stays host-side (the same
split as the scheduler's extra_mask — SURVEY.md §7); this kernel owns the
O(S×N) set algebra. `swarmkit_tpu.orchestrator.global_.bulk_reconcile` is the
store-integrated consumer.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

# below this S×N product the numpy fallback wins (device round-trip costs
# more than the diff); mirrors the scheduler's JAX_THRESHOLD idea
DIFF_THRESHOLD = 1_000_000


@jax.jit
def global_diff(eligible, task_nodes):
    """eligible: bool[S, N]; task_nodes: int32[S, T] — for each service the
    node indices of its runnable tasks, padded with -1 (T = max per service).
    Returns (create bool[S, N], shutdown bool[S, N])."""
    S, N = eligible.shape
    rows = jnp.broadcast_to(jnp.arange(S)[:, None], task_nodes.shape)
    cols = jnp.clip(task_nodes, 0, N - 1)
    has = jnp.zeros((S, N), bool).at[rows, cols].max(task_nodes >= 0)
    return eligible & ~has, ~eligible & has


def global_diff_np(eligible, task_nodes):
    """numpy mirror of `global_diff` (small-scale path and parity oracle)."""
    import numpy as np

    S, N = eligible.shape
    has = np.zeros((S, N), bool)
    valid = task_nodes >= 0
    rows = np.broadcast_to(np.arange(S)[:, None], task_nodes.shape)[valid]
    has[rows, task_nodes[valid]] = True
    return eligible & ~has, ~eligible & has


def compute_diff(eligible, task_nodes):
    """Backend-selecting wrapper: TPU kernel above DIFF_THRESHOLD, numpy
    below. Output is identical either way (both are exact set algebra)."""
    import numpy as np

    S, N = eligible.shape
    if S * N >= DIFF_THRESHOLD:
        create, shutdown = global_diff(jnp.asarray(eligible),
                                       jnp.asarray(task_nodes))
        return np.asarray(create), np.asarray(shutdown)
    return global_diff_np(np.asarray(eligible), np.asarray(task_nodes))
