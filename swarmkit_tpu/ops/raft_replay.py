"""Raft log-replay fast path: batched quorum tally + commit-index advance.

The reference advances the raft commit index entry-by-entry inside etcd/raft's
Ready/Advance protocol (SURVEY.md §3.4; vendored raft.MemoryStorage). For
benchmark-scale replay — BASELINE.md: 1M-entry log, 5-manager quorum — this
module recomputes the whole commit frontier as one data-parallel program:

    tally[e]    = Σ_m ack[m, e]          (psum over the manager mesh axis)
    committed[e]= tally[e] >= quorum
    commit      = length of the True-prefix of committed   (cumprod-sum)

Raft's commit rule is prefix-monotone: an entry is committed only if every
earlier entry is, hence the prefix reduction. `replay_commit` is the
single-device jit; `sharded_replay_commit` shards managers across a mesh axis
with shard_map + lax.psum — the ICI-native analogue of the reference's
manager↔manager gRPC vote traffic.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:                                   # jax >= 0.5 top-level export
    _shard_map = jax.shard_map
except AttributeError:                 # 0.4.x: experimental module
    from jax.experimental.shard_map import shard_map as _shard_map


@jax.jit
def replay_commit(acks, quorum):
    """acks: bool[M, E] (manager × log entry, True = durably appended).
    Returns (commit_index int32, committed bool[E]).

    commit_index is the number of committed entries (0 == nothing committed).
    """
    tally = jnp.sum(acks.astype(jnp.int32), axis=0)          # [E]
    committed = tally >= quorum
    prefix = jnp.cumprod(committed.astype(jnp.int32))        # stops at first 0
    return jnp.sum(prefix).astype(jnp.int32), prefix.astype(bool)


def sharded_replay_commit(mesh: Mesh, axis: str = "managers"):
    """Build a shard_map'd replay where each device holds its managers' ack
    rows; the tally is a lax.psum over the mesh axis (ICI collective)."""

    def kernel(acks_local, quorum):
        tally = jnp.sum(acks_local.astype(jnp.int32), axis=0)
        tally = lax.psum(tally, axis)                         # ICI all-reduce
        committed = tally >= quorum
        prefix = jnp.cumprod(committed.astype(jnp.int32))
        return jnp.sum(prefix).astype(jnp.int32), prefix.astype(bool)

    return jax.jit(
        _shard_map(
            kernel,
            mesh=mesh,
            in_specs=(P(axis, None), P()),
            out_specs=(P(), P()),
        )
    )


@jax.jit
def frontier_advance(acks, frontier, quorum):
    """Device-resident replay step: the ack matrix LIVES on device; each
    round uploads only the per-manager durable frontiers (int32[M], a few
    bytes) instead of re-shipping the [M, E] matrix (round-1 verdict: the
    0.04x speedup_with_upload was pure re-upload cost). Returns the
    updated ack matrix (the next round's carry) and the commit index."""
    M, E = acks.shape
    acks = acks | (jnp.arange(E, dtype=jnp.int32)[None, :]
                   < frontier[:, None])
    tally = jnp.sum(acks.astype(jnp.int32), axis=0)
    committed = tally >= quorum
    prefix = jnp.cumprod(committed.astype(jnp.int32))
    return acks, jnp.sum(prefix).astype(jnp.int32)


def unpack_acks(packed, n_entries: int):
    """Bit-packed ack upload → device bool matrix. The [M, E] bool matrix
    ships 8× smaller as uint8 words (ops/bitpack.py pack_bits) and unpacks
    device-side — through a single-digit-MB/s tunnel the wire bytes are
    the whole cold cost (round-4 verdict #6). packed:
    uint8[M, ceil(E/8)]."""
    from .bitpack import unpack_bits

    return unpack_bits(packed, n_entries)


@jax.jit
def frontier_advance_burst(acks, frontiers_b, quorum):
    """A burst of frontier advances in ONE device program: rounds scan over
    frontiers_b int32[B, M], each OR-ing its round's durable frontiers into
    the resident ack matrix and recomputing the commit frontier. One
    upload + one dispatch + one (async-able) pull of the per-round commit
    indices per burst — the Ready/Advance batching shape, with strictly
    MORE information returned than the single end-of-burst commit (the
    applier sees every round's commit index).
    Returns (acks', commits int32[B])."""
    M, E = acks.shape
    entry = jnp.arange(E, dtype=jnp.int32)[None, :]

    def step(a, fr):
        a = a | (entry < fr[:, None])
        tally = jnp.sum(a.astype(jnp.int32), axis=0)
        prefix = jnp.cumprod((tally >= quorum).astype(jnp.int32))
        return a, jnp.sum(prefix).astype(jnp.int32)

    acks, commits = lax.scan(step, acks, frontiers_b)
    return acks, commits


@jax.jit
def match_index_commit(match_index, quorum):
    """Commit index from per-manager match indices (the leader-side rule:
    commit = the quorum'th largest match index). match_index: int32[M]."""
    sorted_desc = -jnp.sort(-match_index)
    return sorted_desc[quorum - 1]


@functools.partial(jax.jit, static_argnames=("chunk",))
def replay_log_scan(acks, quorum, chunk: int = 65536):
    """Streaming variant for logs too large to tally at once: scan over
    chunks carrying the 'prefix still unbroken' flag. Semantically identical
    to replay_commit; bounds peak memory to O(M × chunk)."""
    M, E = acks.shape
    n_chunks = E // chunk

    def step(alive, acks_chunk):
        tally = jnp.sum(acks_chunk.astype(jnp.int32), axis=0)
        committed = tally >= quorum
        prefix = jnp.cumprod(committed.astype(jnp.int32)) * alive
        count = jnp.sum(prefix)
        alive = alive * prefix[-1]
        return alive, count

    chunks = acks[:, :n_chunks * chunk].reshape(M, n_chunks, chunk)
    chunks = jnp.moveaxis(chunks, 1, 0)                       # [C, M, chunk]
    alive, counts = lax.scan(step, jnp.int32(1), chunks)
    total = jnp.sum(counts)
    if E % chunk:
        _, tail_count = step(alive, acks[:, n_chunks * chunk:])
        total = total + tail_count
    return total.astype(jnp.int32)
