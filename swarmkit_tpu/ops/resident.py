"""Device-resident incremental scheduling state.

Round-1 verdict, highest-leverage item: the one-shot path
(`ops.placement.schedule_encoded`) re-ships every [N]-sized table to the
device on every tick, so steady-state ticks are transfer-bound — the
incremental encoder's deltas "die at the host↔device boundary".

This module keeps the node-side tables LIVE on the device across ticks:

  * the jitted tick scatters in only the rows the encoder re-encoded
    since the last tick (`IncrementalEncoder.last_dirty_rows` plus
    quantization-divergence corrections), runs the fill, and returns the
    post-placement node state as the next tick's carry — with donated
    buffers, so the update is in place;
  * the kernel's own in-scan state updates (totals += counts,
    avail -= counts·need, svc rows, port ORs) are exactly the fold the
    host applies after a tick (`IncrementalEncoder.apply_counts`), so in
    the common case NOTHING node-sized crosses the link: deltas up, a
    sliced int16 counts window down.

The host stays authoritative for parity: `apply_counts` subtracts RAW
reservations and re-derives the quantized columns, while the kernel
subtracts QUANTIZED needs — the two can differ by one quantum on nodes
whose reservation is not a quantum multiple. `after_apply` predicts the
device's value with numpy, diffs it against the encoder's, and queues
only the divergent rows for upload next tick. A verify mode pulls the
full device state and asserts bit-equality with the encoder's arrays
(exercised by tests/test_resident.py).

Reference behavior scheduled here: manager/scheduler/scheduler.go's
dirty-only rescheduling semantics (:429-488) — the delta discipline
mirrors its "only changed nodes re-enter the heap" design at the
host↔device boundary.
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from ..scheduler.encode import (
    VOL_TOPO_SEGS,
    EncodedProblem,
    IncrementalEncoder,
    _bucket,
)
from . import placement

# node-state arrays carried on device, in the order _resident_tick takes
# and returns them
STATE_FIELDS = ("ready", "node_val", "node_plat", "node_plugins",
                "port_used", "avail_res", "total0", "svc_mat")

# donated jit positions: EXACTLY the 8 STATE arrays above — donating any
# group-table position would hand the kernel invalidated buffers on a
# group-cache hit (tests/test_mesh_scaleout.py pins this set)
DONATE_STATE_ARGNUMS = tuple(range(len(STATE_FIELDS)))

# module-singleton placeholders for the disabled penalty/extra group
# tables: a FRESH (1, 1) array per tick would defeat the group-table
# cache's identity gate and re-ship two (tiny) arrays every steady tick
_PLACEHOLDER_FALSE = np.zeros((1, 1), bool)
# disabled vol-topo table (ISSUE 19): same identity-gate rationale — a
# cluster with no CSI volumes must keep hitting the cache on this slot
_PLACEHOLDER_VOLTOPO = np.full((1, 1, 1 + 2 * VOL_TOPO_SEGS), -1, np.int32)


def _resident_tick_impl(
    # ---- device-resident node state (donated: updated in place) --------
    ready, node_val, node_plat, node_plugins, port_used, avail_res,
    total0, svc_mat,
    # ---- row deltas (D-padded; ignored when has_deltas=False) ----------
    d_idx, d_ready, d_val, d_plat, d_plug, d_port, d_avail, d_total, d_svc,
    # ---- per-tick group tables -----------------------------------------
    constraints, plat_req, req_plugins, n_tasks, svc_idx, need_res,
    max_replicas, penalty, has_ports, group_ports, spread_rank, extra_mask,
    vol_topo,
    *, use_penalty: bool, use_extra: bool, use_voltopo: bool,
    has_deltas: bool, compact: bool, strategy: int,
):
    if has_deltas:
        ready = ready.at[d_idx].set(d_ready)
        node_val = node_val.at[d_idx].set(d_val)
        node_plat = node_plat.at[d_idx].set(d_plat)
        node_plugins = node_plugins.at[d_idx].set(d_plug)
        port_used = port_used.at[d_idx].set(d_port)
        avail_res = avail_res.at[d_idx].set(d_avail)
        total0 = total0.at[d_idx].set(d_total)
        svc_mat = svc_mat.at[:, d_idx].set(d_svc)
    G = n_tasks.shape[0]
    N = ready.shape[0]
    pen = penalty if use_penalty else jnp.zeros((G, N), bool)
    extra = extra_mask if use_extra else jnp.ones((G, N), bool)
    counts, totals, svc_out, avail_out, port_out = placement._schedule_core(
        ready, node_val, node_plat, node_plugins, extra,
        constraints, plat_req, req_plugins,
        avail_res, total0, svc_mat,
        n_tasks, svc_idx, need_res, max_replicas,
        pen, has_ports, group_ports, port_used, spread_rank,
        vol_topo=vol_topo if use_voltopo else None, strategy=strategy)
    if compact:
        counts = counts.astype(jnp.int16)
    return (counts, ready, node_val, node_plat, node_plugins, port_out,
            avail_out, totals, svc_out)


_STATICS = ("use_penalty", "use_extra", "use_voltopo", "has_deltas",
            "compact", "strategy")
# donated state buffers update in place on accelerators; the CPU test
# backend can't always honor donation and warns per call, so it gets the
# plain variant
_resident_tick_donating = jax.jit(
    _resident_tick_impl, static_argnames=_STATICS,
    donate_argnums=DONATE_STATE_ARGNUMS)
_resident_tick_plain = jax.jit(_resident_tick_impl, static_argnames=_STATICS)

# mesh-mode tick jits, cached per Mesh: a fresh jax.jit wrapper per
# ResidentPlacement instance would discard the compile cache every time a
# scheduler restarts (leadership churn) or a test builds a new instance
_MESH_TICKS: dict = {}


def _mesh_ticks(mesh, shard):
    cached = _MESH_TICKS.get(mesh)
    if cached is None:
        from ..parallel.mesh import node_axis_sharding

        # pin the carry layout: without out_shardings GSPMD is free to
        # return replicated state, silently multiplying memory by the
        # device count and resharding every steady tick
        outs = (node_axis_sharding(mesh, 2, 1),       # counts [G, N]
                shard["ready"], shard["node_val"], shard["node_plat"],
                shard["node_plugins"], shard["port_used"],
                shard["avail_res"], shard["total0"], shard["svc_mat"])
        cached = (
            jax.jit(_resident_tick_impl, static_argnames=_STATICS,
                    donate_argnums=DONATE_STATE_ARGNUMS,
                    out_shardings=outs),
            jax.jit(_resident_tick_impl, static_argnames=_STATICS,
                    out_shardings=outs),
        )
        _MESH_TICKS[mesh] = cached
    return cached


@functools.lru_cache(maxsize=64)
def _sharded_zeros_fn(shape, sharding):
    return jax.jit(lambda: jnp.zeros(shape, np.int32),
                   out_shardings=sharding)


def _sharded_zeros(shape, sharding):
    """Device-side sharded zeros; the jitted builder is cached per
    (shape, sharding) so repeated cold uploads don't re-trace."""
    return _sharded_zeros_fn(shape, sharding)()


@functools.partial(jax.jit, static_argnames=("g", "n"))
def _slice_counts(counts, g: int, n: int):
    """Device-side slice to the real [G, N] window: the padded buckets
    would otherwise inflate the D2H pull (the dominant cost on a
    tunneled link). Compiles per real shape — a trivial program."""
    return counts[:g, :n]


@functools.partial(jax.jit, static_argnames=("g", "n", "k"))
def _sparse_counts(counts, g: int, n: int, k: int):
    """Device-side sparse pack of the real [G, N] window: nonzero flat
    indices (static size k ≥ the placed-task bound) plus their values.
    At node counts ≫ task counts the dense window is almost all zeros —
    20 groups × 131072 padded nodes is a 4–5 MB pull where the placed
    entries fit in ~600 KB — and D2H bytes are the steady tick's floor.
    fill_value=0 duplicates index 0; densification scatter-sets the SAME
    value there, so duplicates are harmless."""
    flat = counts[:g, :n].reshape(-1)
    idx = jnp.nonzero(flat != 0, size=k, fill_value=0)[0].astype(jnp.int32)
    return idx, flat[idx]


class PendingCounts:
    """Handle to a dispatched tick's counts, D2H copy already in flight.

    Dense form carries the sliced [G, N] window; sparse form carries
    (flat indices, values) and densifies on arrival."""

    __slots__ = ("_dev", "_out", "_shape")

    def __init__(self, dev, shape=None):
        self._dev = dev
        self._shape = shape          # (G, N) → sparse; None → dense
        self._out = None

    def get(self) -> np.ndarray:
        """Block until the counts arrive; returns int32[G, N]. Idempotent."""
        if self._out is None:
            if self._shape is None:
                self._out = np.asarray(self._dev).astype(np.int32)
            else:
                idx_dev, val_dev = self._dev
                g, n = self._shape
                idx = np.asarray(idx_dev)
                val = np.asarray(val_dev).astype(np.int32)
                dense = np.zeros(g * n, np.int32)
                dense[idx] = val     # dup fill idx 0 rewrites one value
                self._out = dense.reshape(g, n)
            self._dev = None
        return self._out


class ResidentPlacement:
    """Owns the device copy of one IncrementalEncoder's node tables.

    Usage (what Scheduler.tick does):
        counts = rp.schedule(problem)          # problem from enc.encode()
        ... scheduler applies, enc.apply_counts(problem, counts) ...
        rp.after_apply(problem, counts)        # or rp.invalidate()

    Thread discipline under the async commit plane (ops/commit.py):
    `after_apply` belongs to the commit's SYNCHRONOUS half — it must run
    on the wave loop at fold time, before the next dispatch, because the
    correction rows it queues are what keeps the next wave's emitted
    problem bit-identical to the device's carry (parity would silently
    break if they trailed a dispatch). The resulting `pending_rows`
    UPLOAD then rides the worker's completion: every dispatch happens
    post-barrier, so a queued correction can never ship while the heavy
    half of the wave that produced it is still in flight. `invalidate`
    is the one method the worker may call (a bare stale-flag set); all
    other mutation stays on the wave loop.
    """

    def __init__(self, encoder: IncrementalEncoder, mesh=None):
        """mesh: a jax.sharding.Mesh with a `nodes` axis — the PRODUCTION
        multi-device mode (parallel/mesh.py layout): device state shards
        over the node axis, group tables replicate, and the tick jit runs
        under GSPMD with XLA-inserted collectives. None = single device."""
        self.enc = encoder
        self.mesh = mesh
        if mesh is not None:
            from ..parallel.mesh import resident_shardings

            n_dev = int(mesh.devices.size)
            if n_dev & (n_dev - 1):
                # buckets are powers of two; a non-power-of-two mesh axis
                # could never divide them (jax.device_put would raise a
                # cryptic divisibility error on first upload)
                raise ValueError(
                    f"mesh node axis must be a power of two, got {n_dev} "
                    "devices (Scheduler rounds down automatically; "
                    "pass mesh=<n> to pick explicitly)")
            self._shard = resident_shardings(mesh)
            self._mesh_devs = n_dev
            self._tick_donating, self._tick_plain = _mesh_ticks(
                mesh, self._shard)
        else:
            self._shard = None
            self._mesh_devs = 1
            self._tick_donating = _resident_tick_donating
            self._tick_plain = _resident_tick_plain
        self._state = None          # tuple of device arrays, STATE_FIELDS
        self._meta = None           # bucket/vocab signature of the state
        self._pending = np.zeros(0, np.int64)  # rows to upload next tick
        self._stale = True
        self.uploads_full = 0       # observability
        self.uploads_delta_rows = 0
        self.uploads_group_tables = 0
        self._gcache = None         # [(host array, device array)] per slot
        self._gsrc = None           # per-slot SOURCE object (identity gate)
        self._gdims = None          # padded dims + N the cache was built at
        self.uploads_h2d_bytes = 0  # delta + group-table wire bytes shipped
        # buffer donation invalidates the donated arrays; on CPU test
        # meshes jax warns per call — keep it for accelerators only
        self._donate = jax.default_backend() != "cpu"

    # ------------------------------------------------------------ internals
    def _signature(self, p: EncodedProblem) -> tuple:
        """Everything that forces a full re-upload when it changes.

        Node-id remaps are handled by the caller via enc.last_remap. A new
        constraint KEY backfills a node_val column for every row
        (_ensure_key), so key-set size is here; value-vocab growth touches
        no existing row and is deliberately absent. Plugin/port/kind vocab
        growth widens the respective arrays, so their shapes cover it.
        Service-row growth inside the Sp bucket is delta-safe (new rows
        start zero on both sides); only crossing the bucket re-uploads."""
        return (
            len(p.node_ids),
            len(self.enc.key_cols),
            _bucket(max(p.n_svc_rows, 1)),
            p.node_val.shape[1], p.node_plugins.shape[1],
            p.port_used0.shape[1], p.avail_res.shape[1],
        )

    def _svc_block(self, cols: np.ndarray | slice, sp: int) -> np.ndarray:
        """Persistent service matrix columns, padded to the Sp bucket."""
        enc = self.enc
        s_used = len(enc._svc_row)
        block = enc._svc_mat[:s_used, cols]
        if block.shape[0] < sp:
            block = np.concatenate(
                [block, np.zeros((sp - block.shape[0],) + block.shape[1:],
                                 np.int32)], axis=0)
        return block

    def _padded_dims(self, p: EncodedProblem) -> tuple:
        """Bucketed (N, K, PL, PV, R, S) — must agree with pad_buckets so
        the node state lines up with the per-tick group tables. In mesh
        mode the node bucket floors at the device count so the sharded
        axis divides evenly (buckets and mesh sizes are both powers of
        two); phantom pad nodes are never eligible, so results match."""
        return (_bucket(len(p.node_ids), floor=self._mesh_devs),
                _bucket(p.node_val.shape[1]),
                _bucket(p.node_plugins.shape[1]),
                _bucket(p.port_used0.shape[1]),
                _bucket(p.avail_res.shape[1]),
                _bucket(max(p.n_svc_rows, 1)))

    @staticmethod
    def _pad2(a: np.ndarray, rows: int, cols: int | None = None,
              fill=0) -> np.ndarray:
        shape = (rows,) + ((cols,) + a.shape[2:] if cols is not None
                           else a.shape[1:])
        if a.shape == shape:
            return a
        out = np.full(shape, fill, a.dtype)
        out[tuple(slice(0, s) for s in a.shape)] = a
        return out

    def _upload_full(self, p: EncodedProblem):
        np_b, kp, plp, pvp, rp, sp = self._padded_dims(p)
        n = len(p.node_ids)
        host = [
            self._pad2(p.ready, np_b, fill=False),
            self._pad2(p.node_val, np_b, kp),
            self._pad2(p.node_plat, np_b, 2),
            self._pad2(p.node_plugins, np_b, plp, fill=False),
            self._pad2(p.port_used0, np_b, pvp, fill=False),
            self._pad2(p.avail_res, np_b, rp),
            self._pad2(p.total0, np_b),
        ]
        if self._shard is not None:
            state = jax.device_put(host, [
                self._shard[f] for f in STATE_FIELDS[:7]])
        else:
            state = jax.device_put(host)
        self.uploads_h2d_bytes += sum(a.nbytes for a in host)
        # the [S, N] per-service count matrix is the cold upload's whale
        # (at 100k nodes it alone is 17-67 MB through a single-digit-MB/s
        # tunnel) and on a cold cluster / post-failover first contact it
        # is all zeros or nearly so: materialize it device-side instead
        # of shipping zero bytes. Sparse (row, col, val) scatter covers
        # the nearly-empty case; dense ship only when actually dense.
        svc = self._svc_block(slice(None), sp)
        nnz = int(np.count_nonzero(svc))
        if nnz == 0:
            if self._shard is not None:
                svc_dev = _sharded_zeros(
                    (sp, np_b), self._shard["svc_mat"])
            else:
                svc_dev = jnp.zeros((sp, np_b), np.int32)
        elif nnz * 3 * 4 < svc.size:
            # sparse ships 8 bytes/nnz (int32 flat idx + int32 val) vs 4
            # bytes/cell dense, so breakeven is nnz*2 < cells; the
            # 12x-margin threshold here is deliberately conservative —
            # the scatter program has its own device cost, and dense is
            # only painful when it is 10s of MB through the tunnel
            # FLAT 1d scatter (CLAUDE.md: the axon backend's 2d scatter
            # silently corrupts above ~512 updates); reshape afterwards
            # as a separate eager op, never fused with the scatter
            r, c = np.nonzero(svc)
            flat = (r.astype(np.int64) * np_b + c).astype(np.int32)
            vals = svc[r, c]
            svc_flat = jnp.zeros(sp * np_b, np.int32).at[
                jax.device_put(flat)].add(jax.device_put(vals))
            svc_dev = svc_flat.reshape(sp, np_b)
            if self._shard is not None:
                svc_dev = jax.device_put(svc_dev, self._shard["svc_mat"])
            self.uploads_h2d_bytes += flat.nbytes + vals.nbytes
        else:
            pad = np.ascontiguousarray(
                np.pad(svc, ((0, 0), (0, np_b - n))))
            svc_dev = (jax.device_put(pad, self._shard["svc_mat"])
                       if self._shard is not None else jax.device_put(pad))
            self.uploads_h2d_bytes += pad.nbytes
        state.append(svc_dev)
        self._state = state
        self._meta = self._signature(p)
        self._pending = np.zeros(0, np.int64)
        self._stale = False
        self.uploads_full += 1

    def needs_full_upload(self, p: EncodedProblem) -> bool:
        """Would scheduling `p` force a full state re-upload (stale
        carry, node remap, or bucket/vocab signature growth)? A deep
        pipeline drains first — the upload would be built from host
        arrays that haven't folded the in-flight waves."""
        return bool(self._stale or self._state is None
                    or self.enc.last_remap
                    or self._meta != self._signature(p))

    @property
    def pending_rows(self) -> bool:
        """True when quantization-correction rows are queued for the next
        dispatch — a deep pipeline must drain before shipping them (the
        row SET would clobber the device's un-pulled in-scan folds)."""
        return bool(self._pending.size)

    # ------------------------------------------------------------------ API
    def invalidate(self):
        """Force a full re-upload next tick (apply fold skipped, external
        surgery on the encoder, …)."""
        self._stale = True

    def schedule(self, p: EncodedProblem) -> np.ndarray:
        """Run one tick on device-resident state; returns int32[G, N]."""
        return self.schedule_async(p).get()

    def schedule_async(self, p: EncodedProblem) -> "PendingCounts":
        """Dispatch one tick and START the counts D2H copy without blocking.

        Through a tunneled link the blocking counts pull is the dominant
        per-tick cost (~0.1 s fixed + bandwidth); the copy initiated here
        rides the link in the background — measured to make full progress
        even under GIL-bound host work — so a caller that commits the
        PREVIOUS wave between dispatch and `PendingCounts.get()` pays a
        near-zero residual (ops/pipeline.py orchestrates exactly that).
        """
        enc = self.enc
        G, N = p.extra_mask.shape

        fresh = self.needs_full_upload(p)
        if fresh:
            self._upload_full(p)
            dirty = np.zeros(0, np.int64)
        else:
            dirty = np.union1d(self._pending, enc.last_dirty_rows) \
                .astype(np.int64)
            self._pending = np.zeros(0, np.int64)

        np_b, kp, plp, pvp, rp, sp = self._padded_dims(p)
        has_deltas = dirty.size > 0
        if has_deltas:
            db = _bucket(dirty.size)
            idx = np.full(db, dirty[0], np.int64)
            idx[:dirty.size] = dirty
            deltas = [
                idx.astype(np.int32),
                p.ready[idx],
                self._pad2(p.node_val[idx], db, kp),
                p.node_plat[idx],
                self._pad2(p.node_plugins[idx], db, plp, fill=False),
                self._pad2(p.port_used0[idx], db, pvp, fill=False),
                self._pad2(p.avail_res[idx], db, rp),
                p.total0[idx],
                np.ascontiguousarray(self._svc_block(idx, sp)),
            ]
            self.uploads_delta_rows += int(dirty.size)
        else:
            z = np.zeros(1, np.int32)
            deltas = [z, np.zeros(1, bool),
                      np.zeros((1, kp), np.int32),
                      np.zeros((1, 2), np.int32),
                      np.zeros((1, plp), bool),
                      np.zeros((1, pvp), bool),
                      np.zeros((1, rp), np.int32),
                      np.zeros(1, np.int32), np.zeros((sp, 1), np.int32)]

        # group tables only — padding the node-side arrays too (the shared
        # pad_buckets) would memcpy tens of MB per tick for arrays the
        # resident path never ships. The builder-stamped flags replace the
        # O(G·N) penalty/extra scans at scale (None = unknown, scan).
        use_penalty = (bool(p.penalty_nonzero)
                       if p.penalty_nonzero is not None
                       else bool(p.penalty.any()))
        use_extra = ((not p.extra_mask_all)
                     if p.extra_mask_all is not None
                     else not bool(p.extra_mask.all()))
        # vol-topo dispatch flag (ISSUE 19): the builder-stamped
        # vol_topo_any is exact; None = unknown → inspect the table shape
        vt = getattr(p, "vol_topo", None)
        vt_any = getattr(p, "vol_topo_any", None)
        use_voltopo = (bool(vt_any) if vt_any is not None
                       else vt is not None and vt.shape[1] > 0)
        strategy = 1 if getattr(p, "strategy", "spread") == "binpack" else 0
        gp = _bucket(G)
        pad2 = self._pad2
        lmax = p.spread_rank.shape[1]
        lp = _bucket(lmax) if lmax else 0
        vp = _bucket(vt.shape[1]) if use_voltopo else 0
        dims = (gp, np_b, kp, plp, pvp, rp, lp, vp, N)

        def build_slot(i):
            if i == 0:
                return pad2(p.constraints, gp, fill=-1)
            if i == 1:
                return pad2(p.plat_req, gp, fill=-2)
            if i == 2:
                return pad2(p.req_plugins, gp, plp, fill=False)
            if i == 3:
                return pad2(p.n_tasks, gp)
            if i == 4:
                return _pad1(p.svc_idx_persistent, gp)
            if i == 5:
                return pad2(p.need_res, gp, rp)
            if i == 6:
                return pad2(p.max_replicas, gp)
            if i == 7:
                return pad2(p.penalty, gp, np_b, fill=False)
            if i == 8:
                return pad2(p.has_ports, gp, fill=False)
            if i == 9:
                return pad2(p.group_ports, gp, pvp, fill=False)
            if i == 10:
                spread = np.zeros((gp, lp, np_b), np.int32)
                if lmax:
                    spread[:G, :lmax, :N] = p.spread_rank
                    if lp > lmax:
                        # replicate each group's deepest real level
                        # (self-parented pours are no-ops), like
                        # pad_buckets
                        spread[:G, lmax:, :N] = \
                            p.spread_rank[:, lmax - 1:lmax, :]
                return spread
            if i == 11:
                return pad2(p.extra_mask, gp, np_b, fill=False)
            return pad2(vt, gp, vp, fill=-1)                     # 12

        compact = bool(p.n_tasks.size == 0 or int(p.n_tasks.max()) < (1 << 15))

        # group-table device cache: successive waves of the SAME services
        # re-encode identical constraint/platform/spread/... tables — only
        # n_tasks (and penalty, when failures decay) actually move. TWO
        # gates, cheapest first (docs/mesh.md): (1) source IDENTITY — the
        # encoder re-emits unchanged [·, N]-sized tables as the same
        # object (spread-table cache; placeholder singletons), an O(1)
        # hit that skips BOTH the padded rebuild and the memcmp, which at
        # 100k–1M nodes would themselves be the steady tick's largest
        # host cost; (2) host value equality on the padded copy (a memcmp
        # is ~100x cheaper than the upload it saves). In mesh mode the
        # cached device arrays keep their node-axis NamedShardings, so a
        # hit reuses SHARD-resident tables — sound only because no
        # group-table jit position is ever donated (DONATE_STATE_ARGNUMS
        # covers exactly the 8 STATE arrays).
        srcs = [p.constraints, p.plat_req, p.req_plugins, p.n_tasks,
                p.svc_idx_persistent, p.need_res, p.max_replicas,
                p.penalty if use_penalty else _PLACEHOLDER_FALSE,
                p.has_ports, p.group_ports, p.spread_rank,
                p.extra_mask if use_extra else _PLACEHOLDER_FALSE,
                vt if use_voltopo else _PLACEHOLDER_VOLTOPO]
        n_slots = len(srcs)
        cache = self._gcache
        prev_src = self._gsrc
        if cache is None or len(cache) != n_slots or self._gdims != dims:
            cache = [None] * n_slots
            prev_src = [None] * n_slots
        group_dev: list = [None] * n_slots
        group_host: list = [None] * n_slots
        ship_slots: list[int] = []
        to_ship: list[np.ndarray] = []
        for i, src in enumerate(srcs):
            c = cache[i]
            if c is not None and prev_src[i] is src:
                group_host[i], group_dev[i] = c          # identity hit
                continue
            h = (src if src is _PLACEHOLDER_FALSE
                 or src is _PLACEHOLDER_VOLTOPO else build_slot(i))
            group_host[i] = h
            if c is not None and c[0].shape == h.shape \
                    and c[0].dtype == h.dtype and np.array_equal(c[0], h):
                group_dev[i] = c[1]
            else:
                ship_slots.append(i)
                to_ship.append(h)
        if self._shard is not None:
            # group-table slots whose trailing axis is the (bucketed) node
            # axis shard over it; everything else — including the delta
            # rows, which scatter INTO the sharded state — replicates.
            # Placeholder (1, 1) penalty/extra tables stay replicated.
            node_sharded = {7: 1, 10: 2, 11: 1}    # slot -> node axis
            repl = self._shard[None]
            shards = [repl] * len(deltas)
            from ..parallel.mesh import node_axis_sharding
            for slot, h in zip(ship_slots, to_ship):
                ax = node_sharded.get(slot)
                if ax is not None and h.shape[-1] == np_b:
                    shards.append(
                        node_axis_sharding(self.mesh, h.ndim, ax))
                else:
                    shards.append(repl)
            dev = jax.device_put(deltas + to_ship, shards)
        else:
            dev = jax.device_put(deltas + to_ship)
        for slot, d in zip(ship_slots, dev[9:]):
            group_dev[slot] = d
        self._gcache = [(h, d) for h, d in zip(group_host, group_dev)]
        self._gsrc = srcs
        self._gdims = dims
        self.uploads_group_tables += len(ship_slots)
        # O(delta) H2D accounting (the op-count guard's byte counter):
        # everything this tick shipped is the delta rows + missed slots
        self.uploads_h2d_bytes += sum(a.nbytes for a in deltas) \
            + sum(a.nbytes for a in to_ship)
        tick = (self._tick_donating if self._donate
                else self._tick_plain)
        out = tick(
            *self._state, *dev[:9], *group_dev,
            use_penalty=use_penalty, use_extra=use_extra,
            use_voltopo=use_voltopo, has_deltas=has_deltas,
            compact=compact, strategy=strategy)
        counts_dev, self._state = out[0], tuple(out[1:])
        # pull form: dense [G, N] window vs sparse (idx, val) — pick by
        # wire bytes. k bounds the nonzero count by the tick's total tasks
        # (bucketed so the pack program caches across similar ticks).
        total = int(p.n_tasks.sum())
        k = _bucket(max(total, 1))
        dense_bytes = G * N * (2 if compact else 4)
        sparse_bytes = k * (4 + (2 if compact else 4))
        if k < G * N and sparse_bytes < dense_bytes:
            dev = _sparse_counts(counts_dev, G, N, k)
            shape = (G, N)
        else:
            dev = _slice_counts(counts_dev, G, N)
            shape = None
        try:
            arrs = dev if isinstance(dev, tuple) else (dev,)
            for a in arrs:
                a.copy_to_host_async()
        except Exception:      # backend without async copy: get() still works
            pass
        return PendingCounts(dev, shape)

    def after_apply(self, p: EncodedProblem, counts: np.ndarray):
        """Called after the scheduler applied this tick's placements and
        the encoder folded them (`apply_counts`). Computes where the
        device's quantized in-kernel fold diverges from the host's
        raw-subtraction fold and queues those rows for upload."""
        enc = self.enc
        if self._stale or self._state is None:
            return
        if p.node_ids != enc._ids:
            self._stale = True
            return
        # device carried: p.avail_res (pre-tick) - counts^T @ quantized
        # need. Compare the problem's column width only: a vocab-growth
        # encode may have widened the encoder arrays after this wave
        # dispatched — the new kind columns reach the device via the
        # full re-upload that growth forces, not via correction rows.
        r = p.avail_res.shape[1]
        if enc.avail_res.shape[1] < r:
            self._stale = True
            return
        dev_avail = p.avail_res.astype(np.int64) - \
            counts.astype(np.int64).T @ p.need_res.astype(np.int64)
        diff = (dev_avail != enc.avail_res[:, :r]).any(axis=1)
        self._pending = np.union1d(self._pending, np.flatnonzero(diff)) \
            .astype(np.int64)

    # ------------------------------------------------------------ debugging
    def pull_state(self) -> dict:
        """Device state as numpy, keyed by STATE_FIELDS (tests/verify)."""
        return {k: np.asarray(v)
                for k, v in zip(STATE_FIELDS, self._state)}


def _pad1(a: np.ndarray, n: int) -> np.ndarray:
    if a.shape[0] == n:
        return a
    out = np.zeros(n, a.dtype)
    out[:a.shape[0]] = a
    return out
