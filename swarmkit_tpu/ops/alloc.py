"""Batched IPAM / port grant kernels (ISSUE 11).

One primitive powers both allocators: given an occupancy mask over a
pool and the scalar allocator's probe cursor, emit every FREE slot in
the exact circular probe order the scalar oracle
(`allocator/ipam.py _Pool.allocate` / `allocator/allocator.py
PortAllocator._find_dynamic`) would visit it — so a batch of K grants is
bit-identical to K sequential scalar calls (no releases interleave
inside a batch by construction).

Kernel shape rules (CLAUDE.md): everything is FLAT 1D — the rank key is
a 1D mask/scan and the order comes from `jnp.argsort`, which is stable
here and therefore the sanctioned tie-break; no 2D scatters, no int64.
The numpy twin is both the oracle the kernel fuzz pins against and the
small-pool fast path (a /24 pool is 256 slots — jit dispatch would
dominate; the jax path earns its keep on /16+ pools and the port span).
"""
from __future__ import annotations

import functools

import numpy as np

# pools at or under this size take the numpy path; above it the jitted
# kernel (cached per (size, lo, hi) static shape) amortizes
JAX_POOL_THRESHOLD = 4096


def grant_order_np(taken: np.ndarray, cursor: int, lo: int,
                   hi: int) -> np.ndarray:
    """Free offsets of `taken[lo..hi]` in circular probe order starting
    at `cursor` (clamped to `lo` when outside the range, matching the
    scalar wrap reset). Pure numpy — the kernel's oracle."""
    span = hi - lo + 1
    start = cursor if lo <= cursor <= hi else lo
    pos = np.arange(lo, hi + 1, dtype=np.int32)
    key = (pos - np.int32(start)) % np.int32(span)
    free = ~taken[lo:hi + 1]
    order = np.argsort(np.where(free, key, np.int32(span)), kind="stable")
    n_free = int(free.sum())
    return pos[order[:n_free]]


@functools.lru_cache(maxsize=64)
def _grant_kernel(size: int, lo: int, hi: int):
    import jax
    import jax.numpy as jnp

    span = hi - lo + 1

    @jax.jit
    def kern(taken, cursor):
        start = jnp.where((cursor >= lo) & (cursor <= hi), cursor, lo)
        pos = jnp.arange(lo, hi + 1, dtype=jnp.int32)
        key = (pos - start.astype(jnp.int32)) % jnp.int32(span)
        free = ~taken[lo:hi + 1]
        # stable argsort over the masked scan key: free slots sort to
        # the front in probe order, taken slots sink behind the span
        # sentinel — the whole kernel is one flat-1D mask/scan
        order = jnp.argsort(jnp.where(free, key, jnp.int32(span)))
        return pos[order], free.sum()

    return kern


def grant_order(taken: np.ndarray, cursor: int, lo: int, hi: int,
                use_jax: bool | None = None) -> np.ndarray:
    """Dispatch wrapper: numpy under JAX_POOL_THRESHOLD (or use_jax
    False), the cached jit kernel above it. Output is bit-identical
    either way (tests/test_batched_alloc.py fuzzes the pair)."""
    if use_jax is None:
        use_jax = taken.shape[0] > JAX_POOL_THRESHOLD
    if not use_jax:
        return grant_order_np(taken, cursor, lo, hi)
    kern = _grant_kernel(int(taken.shape[0]), int(lo), int(hi))
    order, n_free = kern(taken, np.int32(cursor))
    return np.asarray(order)[:int(n_free)]
