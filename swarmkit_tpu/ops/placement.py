"""Batched task×node placement kernel.

This is the TPU execution of the reference's scheduler hot loop
(manager/scheduler/scheduler.go:694-921 + the filter chain of filter.go),
re-architected per SURVEY.md §7: instead of per-(task, node) Go string
compares, one jitted program computes

  1. a dense static eligibility mask[G, N] — ready ∧ constraints ∧ platform ∧
     plugins ∧ host-corrections — from interned int tables;
  2. a `lax.scan` over task groups, each step water-filling the group's tasks
     over eligible nodes with per-node dynamic capacity (resource depletion,
     max-replicas, host-port exclusivity) under the canonical spread order
     (penalty, svc_count, total_count, node_idx);

and returns per-(group, node) assignment counts that are bit-identical to the
greedy CPU oracle (`swarmkit_tpu.scheduler.spread.greedy_fill`) — the proof
is that greedy with uniform (+1,+1) key increments consumes exactly the
globally smallest slots of the merged per-node slot sequences, which is what
the closed-form water level computes.

Sharding: every per-node array is shardable on its N axis; see
`swarmkit_tpu.parallel.sharded_placement` for the multi-chip wrapper.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..scheduler.encode import VOL_TOPO_MOUNTS
from ..scheduler.spread import PENALTY_BASE

UNLIMITED = 1 << 30  # plain int: keep module import free of backend init


def _vol_topo_ok(node_val, vol_topo):
    """Volume/topology feasibility[G, N] (SURVEY volumes.go/topology.go).

    vol_topo: int32[G, VA, 1+2*SEGS] rows of (mount_id, k0, v0, k1, v1,
    ...), -1 padded. Each row is one candidate-volume × accessible-
    topology alternative of one mount; it passes on a node when EVERY
    present (key, value) segment matches the node's interned column
    value (the encoder emits csi pseudo-keys into node_val, so this is
    the same gather shape as the constraint check). Feasibility = AND
    over mounts present in the group of (OR over that mount's rows).
    """
    G, VA, W = vol_topo.shape
    mount = vol_topo[:, :, 0]                                # [G, VA]
    row_ok = jnp.ones((G, VA, node_val.shape[0]), bool)
    for s in range((W - 1) // 2):
        k = vol_topo[:, :, 1 + 2 * s]                        # [G, VA]
        v = vol_topo[:, :, 2 + 2 * s]
        nv = node_val[:, jnp.clip(k, 0)]                     # [N, G, VA]
        ok = (k < 0)[None] | (nv == v[None])                 # [N, G, VA]
        row_ok = row_ok & jnp.transpose(ok, (1, 2, 0))
    vol_ok = jnp.ones((G, node_val.shape[0]), bool)
    for m in range(VOL_TOPO_MOUNTS):
        is_m = mount == m                                    # [G, VA]
        has_m = jnp.any(is_m, axis=1)                        # [G]
        m_ok = jnp.any(row_ok & is_m[:, :, None], axis=1)    # [G, N]
        vol_ok = vol_ok & jnp.where(has_m[:, None], m_ok, True)
    return vol_ok


def build_static_mask(
    ready,        # bool[N]
    node_val,     # int32[N, K]
    node_plat,    # int32[N, 2]
    node_plugins, # bool[N, PL]
    constraints,  # int32[G, C, 3]
    plat_req,     # int32[G, P, 2]
    req_plugins,  # bool[G, PL]
    extra_mask,   # bool[G, N]
    vol_topo=None,  # int32[G, VA, 1+2*SEGS] or None
):
    """Fused eligibility mask[G, N]. Pure elementwise/gather work — XLA fuses
    this into a handful of kernels; the matmul-shaped plugin check rides the
    MXU when PL is large."""
    G = constraints.shape[0]
    N = node_val.shape[0]

    # Constraints: gather each group's key columns from every node.
    cols = jnp.clip(constraints[:, :, 0], 0)            # [G, C]
    ops = constraints[:, :, 1]                           # [G, C]
    vals = constraints[:, :, 2]                          # [G, C]
    padded = constraints[:, :, 0] < 0                    # [G, C]
    nv = node_val[:, cols]                               # [N, G, C]
    hit = nv == vals[None, :, :]                         # [N, G, C]
    ok = jnp.where(ops[None] == 0, hit, ~hit)            # == vs !=
    cons_ok = jnp.all(ok | padded[None], axis=2)         # [N, G]
    cons_ok = cons_ok.T                                  # [G, N]

    # Platforms: any requested row matches; wildcard id 0; pad rows -2.
    pr = plat_req                                        # [G, P, 2]
    row_valid = pr[:, :, 0] > -2                         # [G, P]
    has_plat = jnp.any(row_valid, axis=1)                # [G]
    os_ok = (pr[:, :, 0][:, :, None] == 0) | (
        pr[:, :, 0][:, :, None] == node_plat[:, 0][None, None, :])
    arch_ok = (pr[:, :, 1][:, :, None] == 0) | (
        pr[:, :, 1][:, :, None] == node_plat[:, 1][None, None, :])
    plat_hit = jnp.any(os_ok & arch_ok & row_valid[:, :, None], axis=1)  # [G, N]
    plat_ok = jnp.where(has_plat[:, None], plat_hit, True)

    # Plugins: fail when any required plugin is absent on the node.
    missing = jnp.einsum(
        "gp,np->gn", req_plugins.astype(jnp.float32),
        (~node_plugins).astype(jnp.float32),
        preferred_element_type=jnp.float32) > 0.5
    plug_ok = ~missing

    out = ready[None, :] & cons_ok & plat_ok & plug_ok & extra_mask
    # VA == 0 is the common case (no CSI volumes): the shape is static
    # under jit, so the whole leg compiles away
    if vol_topo is not None and vol_topo.shape[1] > 0:
        out = out & _vol_topo_ok(node_val, vol_topo)
    return out


def _segment_sum(data, seg, n):
    return jax.ops.segment_sum(data, seg, num_segments=n)


def _segment_min(data, seg, n):
    return jax.ops.segment_min(data, seg, num_segments=n)


_POUR_BITS = 30  # water-level search range for branch totals


def _segmented_pour(quota_seg, k_child, cap_child, parent_of, valid, n):
    """Per-parent water fill over child segments (the branch-level split of
    scheduler.go:772-822 in closed form).

    All arrays are child-indexed ([n], ids padded to n); `quota_seg` is
    parent-indexed. Children of one parent occupy a CONTIGUOUS child-id
    range (the encoder ranks value-path prefixes lexicographically), which
    makes the remainder rank a cumsum minus a per-parent offset.
    Returns per-child give, the next level's quotas.
    """
    cap = jnp.where(valid, cap_child, 0).astype(jnp.int32)
    cap_parent = _segment_sum(cap, parent_of, n)
    q = jnp.minimum(quota_seg, cap_parent)                      # per parent

    def filled(lp):
        f = jnp.minimum(cap, jnp.maximum(0, lp[parent_of] - k_child))
        return _segment_sum(f, parent_of, n)

    def bisect(state, _):
        lo, hi = state
        mid = lo + (hi - lo + 1) // 2  # overflow-free upper midpoint
        take = filled(mid) <= q
        return (jnp.where(take, mid, lo), jnp.where(take, hi, mid - 1)), None

    (level, _), _ = lax.scan(
        bisect,
        (jnp.zeros(n, jnp.int32), jnp.full(n, 1 << _POUR_BITS, jnp.int32)),
        None, length=_POUR_BITS + 1)
    give = jnp.minimum(cap, jnp.maximum(0, level[parent_of] - k_child))
    give = jnp.where(valid, give, 0)
    rem = q - _segment_sum(give, parent_of, n)                  # per parent
    boundary = valid & (cap > give) & (k_child <= level[parent_of]) \
        & (give == level[parent_of] - k_child)
    b32 = boundary.astype(jnp.int32)
    cum = jnp.cumsum(b32) - b32                                 # exclusive
    offset = _segment_min(jnp.where(valid, cum, 1 << 30), parent_of, n)
    rank = cum - offset[parent_of]
    extra = boundary & (rank < rem[parent_of])
    return give + extra.astype(jnp.int32)


def _flat_water_fill(cap, penalty, svc, total, n_tasks):
    """Flat canonical fill (no spread preferences): one SCALAR water-level
    bisect over plain reductions — no segment scatters, no lexsort. This is
    the hot shape (most services carry no placement preferences), and on TPU
    it fuses into a handful of reduction kernels; the segmented tree path
    below costs ~an order of magnitude more in scatter traffic."""
    N = cap.shape[0]
    idx = jnp.arange(N, dtype=jnp.int32)
    k = (jnp.where(penalty, PENALTY_BASE, 0) + svc).astype(jnp.int32)
    q = jnp.minimum(n_tasks, jnp.sum(cap)).astype(jnp.int32)

    def filled(level):
        return jnp.sum(jnp.minimum(cap, jnp.maximum(0, level - k)))

    def bisect(state, _):
        lo, hi = state
        mid = lo + (hi - lo + 1) // 2
        take = filled(mid) <= q
        return (jnp.where(take, mid, lo), jnp.where(take, hi, mid - 1)), None

    (level, _), _ = lax.scan(
        bisect,
        (jnp.zeros((), jnp.int32), jnp.full((), 1 << _POUR_BITS, jnp.int32)),
        None, length=_POUR_BITS + 1)
    counts = jnp.minimum(cap, jnp.maximum(0, level - k))
    rem = q - jnp.sum(counts)
    boundary = (cap > counts) & (k <= level) & (counts == level - k)
    # remainder rank by (secondary, node idx): jnp.argsort is stable, so
    # equal secondaries keep index order — exactly the canonical tie-break
    sec = jnp.where(boundary, total + counts, 1 << 30).astype(jnp.int32)
    order = jnp.argsort(sec, stable=True)
    pos = jnp.zeros(N, jnp.int32).at[order].set(idx)
    extra = boundary & (pos < rem)
    return counts + extra.astype(jnp.int32)


def _tree_water_fill(eligible, capacity, penalty, svc, total, n_tasks,
                     spread_rank):
    """Hierarchical canonical spread fill of one group.

    spread_rank: int32[LMAX, N] branch ids per level (prefix ranks). The
    quota pours down the levels — each a `_segmented_pour` over branch
    aggregates (existing totals count ALL branch nodes, capacity only
    eligible ones, nodeset.go:88-104) — and the last pour places nodes
    within their leaf branch under the flat canonical order
    (penalty, svc, total, node_idx). LMAX == 0 degenerates to the flat
    fill (single segment). Bit-identical to spread.tree_fill.
    """
    N = eligible.shape[0]
    lmax = spread_rank.shape[0]
    cap = jnp.minimum(jnp.where(eligible, capacity, 0), n_tasks) \
        .astype(jnp.int32)
    if lmax == 0:   # static shape: compiles to the scatter-free flat fill
        return _flat_water_fill(cap, penalty, svc, total, n_tasks)
    idx = jnp.arange(N, dtype=jnp.int32)
    zeros = jnp.zeros(N, jnp.int32)

    # ---- branch levels: pour the root quota down the prefix tree --------
    parent_seg = zeros                     # level -1: a single root segment
    quota_seg = zeros.at[0].set(jnp.minimum(n_tasks, jnp.sum(cap)))
    for li in range(lmax):
        seg = spread_rank[li]                                   # [N] per node
        # child aggregates (child id = segment id at this level)
        k_child = _segment_sum(svc.astype(jnp.int32), seg, N)
        cap_child = _segment_sum(cap, seg, N)
        node_count = _segment_sum(jnp.ones(N, jnp.int32), seg, N)
        valid = node_count > 0
        parent_of = _segment_min(parent_seg, seg, N)            # nesting
        parent_of = jnp.where(valid, parent_of, 0)
        quota_seg = _segmented_pour(quota_seg, k_child, cap_child,
                                    parent_of, valid, N)
        parent_seg = seg

    # ---- node level: fill within each leaf branch -----------------------
    leaf = parent_seg
    k_node = (jnp.where(penalty, PENALTY_BASE, 0) + svc).astype(jnp.int32)

    def filled(lp):
        f = jnp.minimum(cap, jnp.maximum(0, lp[leaf] - k_node))
        return _segment_sum(f, leaf, N)

    q = jnp.minimum(quota_seg, _segment_sum(cap, leaf, N))

    def bisect(state, _):
        lo, hi = state
        mid = lo + (hi - lo + 1) // 2  # overflow-free upper midpoint
        take = filled(mid) <= q
        return (jnp.where(take, mid, lo), jnp.where(take, hi, mid - 1)), None

    (level, _), _ = lax.scan(
        bisect,
        (jnp.zeros(N, jnp.int32), jnp.full(N, 1 << _POUR_BITS, jnp.int32)),
        None, length=_POUR_BITS + 1)
    counts = jnp.minimum(cap, jnp.maximum(0, level[leaf] - k_node))
    rem = q - _segment_sum(counts, leaf, N)                     # per leaf
    boundary = (cap > counts) & (k_node <= level[leaf]) \
        & (counts == level[leaf] - k_node)
    # remainder rank within leaf by (secondary, node idx): nodes of a leaf
    # are NOT contiguous — order by (leaf, sec, idx), exclusive-cumsum the
    # boundary flags, subtract each leaf's offset, scatter back
    sec = jnp.where(boundary, total + counts, (1 << 30))
    order = jnp.lexsort((idx, sec, leaf))
    b_sorted = boundary[order].astype(jnp.int32)
    cum = jnp.cumsum(b_sorted) - b_sorted
    leaf_sorted = leaf[order]
    offset = _segment_min(cum, leaf_sorted, N)
    rank_sorted = cum - offset[leaf_sorted]
    rank = jnp.zeros(N, jnp.int32).at[order].set(rank_sorted)
    extra = boundary & (rank < rem[leaf])
    return counts + extra.astype(jnp.int32)


def _binpack_fill(eligible, capacity, penalty, svc, total, n_tasks):
    """Binpack fill of one group: prefer the FULLEST feasible node.

    Canonical order (penalty, -svc, -total, node_idx) — see
    spread.binpack_fill. Because each assignment strictly improves the
    assigned node's key, greedy equals sequential capacity consumption
    in INITIAL-key order, which is the closed form here: stable lexsort
    by the initial key, then prefix-sum the sorted capacities against
    the quota. Bit-identical to spread.binpack_fill/binpack_reference.
    """
    N = eligible.shape[0]
    idx = jnp.arange(N, dtype=jnp.int32)
    cap = jnp.minimum(jnp.where(eligible, capacity, 0), n_tasks) \
        .astype(jnp.int32)
    pen = jnp.where(penalty, 1, 0).astype(jnp.int32)
    order = jnp.lexsort((idx, -total.astype(jnp.int32),
                         -svc.astype(jnp.int32), pen))
    cap_sorted = cap[order]
    prefix = jnp.cumsum(cap_sorted)
    q = jnp.minimum(n_tasks, jnp.sum(cap)).astype(jnp.int32)
    counts_sorted = jnp.clip(q - (prefix - cap_sorted), 0, cap_sorted)
    return jnp.zeros(N, jnp.int32).at[order].set(counts_sorted)


def _schedule_core(
    ready, node_val, node_plat, node_plugins, extra_mask,
    constraints, plat_req, req_plugins,
    avail_res,      # int32[N, R]
    total0,         # int32[N]
    svc_count0,     # int32[S, N]
    n_tasks,        # int32[G]
    svc_idx,        # int32[G]
    need_res,       # int32[G, R]
    max_replicas,   # int32[G]
    penalty,        # bool[G, N]
    has_ports,      # bool[G]
    group_ports,    # bool[G, PV]
    port_used0,     # bool[N, PV]
    spread_rank,    # int32[G, LMAX, N]; LMAX may be 0 (no preferences)
    vol_topo=None,  # int32[G, VA, 1+2*SEGS]; VA may be 0 (no CSI volumes)
    unroll: int = 1,
    strategy: int = 0,   # static: 0 = spread/topology (tree), 1 = binpack
):
    """Traced core shared by the one-shot and device-resident entry points.
    Schedules every group sequentially (groups interact through node
    state), each step fully data-parallel over nodes. Returns the counts
    AND the full post-placement node state carry."""
    static_mask = build_static_mask(
        ready, node_val, node_plat, node_plugins,
        constraints, plat_req, req_plugins, extra_mask, vol_topo)

    def step(carry, xs):
        totals, svc_counts, avail, port_used = carry
        (g_mask, g_need, g_ntasks, g_svc, g_maxrep, g_pen, g_hasports,
         g_ports, g_spread) = xs

        svc = svc_counts[g_svc]                                    # [N]

        # dynamic capacity: resources
        need = jnp.maximum(g_need, 1)                              # avoid /0
        caps = jnp.where(g_need[None, :] > 0, avail // need[None, :], UNLIMITED)
        cap_res = jnp.min(caps, axis=1)                            # [N]
        # max replicas
        cap_mr = jnp.where(g_maxrep > 0, g_maxrep - svc, UNLIMITED)
        # host ports: at most one task of a port-publishing group per node,
        # and only when none of its ports are already taken
        conflict = jnp.any(g_ports[None, :] & port_used, axis=1)   # [N]
        cap_port = jnp.where(g_hasports,
                             jnp.where(conflict, 0, 1), UNLIMITED)
        cap = jnp.clip(jnp.minimum(jnp.minimum(cap_res, cap_mr), cap_port),
                       0, UNLIMITED)

        if strategy == 1:     # static: binpack ignores spread preferences
            counts = _binpack_fill(g_mask, cap, g_pen, svc, totals,
                                   g_ntasks)
        else:                 # spread / topology (topology = encoder-
            counts = _tree_water_fill(g_mask, cap, g_pen, svc, totals,
                                      g_ntasks, g_spread)  # prepended level

        totals = totals + counts
        # audited vs the axon flat-1D rule (ISSUE 8): g_svc is a SCALAR
        # per scan step, so this is a single-ROW vector add — row
        # scatter ops are probed-safe at every size (CLAUDE.md); only
        # multi-axis .at[r, c].add index scatters corrupt, and the
        # scatter-2d lint rule fires on exactly that form (no pragma
        # needed here — adding a tuple index to this line WOULD fire it)
        svc_counts = svc_counts.at[g_svc].add(counts)
        avail = avail - counts[:, None] * g_need[None, :]
        port_used = port_used | (g_ports[None, :] & (counts > 0)[:, None])
        return (totals, svc_counts, avail, port_used), counts

    (totals, svc_counts, avail, port_used), counts = lax.scan(
        step,
        (total0, svc_count0, avail_res, port_used0),
        (static_mask, need_res, n_tasks, svc_idx, max_replicas,
         penalty, has_ports, group_ports, spread_rank),
        unroll=unroll,
    )
    return counts, totals, svc_counts, avail, port_used


@functools.partial(jax.jit, static_argnames=("unroll", "strategy"))
def schedule_groups(*args, unroll: int = 1, strategy: int = 0):
    """One-shot entry: (counts[G, N], totals[N], svc_counts[S, N])."""
    counts, totals, svc_counts, _, _ = _schedule_core(
        *args, unroll=unroll, strategy=strategy)
    return counts, totals, svc_counts


@functools.partial(jax.jit, static_argnames=("compact", "strategy"))
def schedule_groups_compact(*args, compact: bool = True, strategy: int = 0):
    """schedule_groups + an int16 downcast when counts provably fit — the
    result crosses the host↔device link (a high-latency tunnel in dev; PCIe
    in prod), so halving the bytes matters. The real [G, N] window is sliced
    HOST-side: making it static here would re-trace the whole kernel per
    exact shape, defeating pad_buckets' bucket-and-pad."""
    counts, totals, svc_counts = schedule_groups(*args, strategy=strategy)
    if compact:
        return counts.astype(jnp.int16)
    return counts


def schedule_encoded(p, backend=None):
    """Run the kernel on an EncodedProblem; returns numpy counts[G, N].

    The problem is bucket-padded first (encode.pad_buckets) so growth in any
    dimension recompiles only at power-of-two boundaries. All input arrays
    ship in ONE batched device_put (per-array transfers each pay a full
    link round trip), and the result comes back downcast; the slice back to
    the real window happens after the pull."""
    import numpy as np

    from ..scheduler.encode import kernel_args, pad_buckets

    G, N = p.extra_mask.shape
    args = jax.device_put(list(kernel_args(pad_buckets(p))))
    compact = bool(p.n_tasks.size == 0 or int(p.n_tasks.max()) < (1 << 15))
    strategy = 1 if getattr(p, "strategy", "spread") == "binpack" else 0
    counts = schedule_groups_compact(*args, compact=compact,
                                     strategy=strategy)
    return np.asarray(counts)[:G, :N].astype(np.int32)
