"""Asynchronous commit plane: one background worker for the heavy half
of a scheduler wave commit.

The round-5 verdict's top item: the TPU water-fill tick is ~11x the CPU
oracle, but the end-to-end wave sits at ~3.4x because the host-side
commit — slot materialization, the native add_task segment walk, store
write-back, fingerprint restamp — runs serially inside every wave
period.  None of that work is needed by the NEXT wave's encode/dispatch;
it only has to be finished before anything re-READS host scheduling
state (the encoder's dirty scan, NodeInfo objects, the store's view of
the unassigned pool).  So the commit splits:

  * synchronous half (stays on the wave loop, ops/pipeline.py):
    `fold_counts` before the next encode, `after_apply` correction
    bookkeeping before the next dispatch — the two pieces placement
    parity depends on;
  * heavy half (this worker): materialize_orders + the one-add_task-per-
    placement walk + store transaction + `restamp_counts`, enqueued
    FIFO and overlapped with the next wave's device dispatch and D2H
    pull (the pull's blocking transfer wait releases the GIL, which is
    exactly when this thread runs).

This is the same overlap discipline a training step uses to hide
optimizer/host work under device dispatch; the reference scheduler pays
the equivalent walk synchronously in applySchedulingDecisions
(manager/scheduler/scheduler.go:490-643).

Discipline (the invariant CLAUDE.md records):

  * ONE worker thread, bounded queue, strict FIFO — wave k's heavy
    commit fully precedes wave k+1's;
  * every consumer of host scheduling state takes `barrier()` first.
    In TickPipeline that is the top of every tick (before the dirty
    scan) and every drain trigger; in the production Scheduler it is
    additionally the event handler and the stop path;
  * a worker-side exception NEVER dies with the thread (the test
    harness turns unhandled thread crashes into failures): it is
    captured, the queue is poisoned (queued jobs are dropped — they
    were built on state the failed commit left undefined), and the
    exception re-raises on the next barrier/submit, i.e. into the next
    tick, whose caller owns the heal (resident invalidate + re-encode).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable

from ..analysis.lockgraph import make_rlock
from ..utils import failpoints

# a commit plane never needs depth beyond the tick pipeline's (the
# barrier at each tick keeps at most one wave's heavy half in flight
# per pipeline slot); the bound exists so a driver bug fails loudly
# instead of queueing unbounded closures
DEFAULT_MAX_PENDING = 8


class CommitWorker:
    """Single background thread running submitted thunks FIFO.

    submit() enqueues; barrier() blocks until everything submitted so
    far has retired, then re-raises the first worker exception if one
    occurred.  Exceptions poison the worker: jobs queued behind the
    failure are dropped unrun (their input state is undefined), and
    every subsequent submit()/barrier() re-raises until the owner heals
    and calls `reset()`.
    """

    def __init__(self, name: str = "commit-worker",
                 max_pending: int = DEFAULT_MAX_PENDING):
        self.name = name
        self.max_pending = max_pending
        self._jobs: deque[Callable[[], None]] = deque()
        self._cond = threading.Condition(make_rlock("ops.commit.cond"))
        self._pending = 0            # submitted, not yet retired
        self._exc: BaseException | None = None
        self._thread: threading.Thread | None = None
        self._closed = False
        # observability (bench): seconds the worker spent inside jobs,
        # and per-job durations in retirement (= submission) order
        self.busy_s = 0.0
        self.job_s: list[float] = []
        # observability (/metrics): jobs retired and poison episodes —
        # a rising poison count with the suite green means heals are
        # eating real commits (the operator signal ISSUE 5 exports)
        self.jobs_total = 0
        self.poisoned_total = 0

    # ---------------------------------------------------------------- thread
    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name=self.name, daemon=True)
            self._thread.start()

    def _run(self):
        while True:
            with self._cond:
                while not self._jobs and not self._closed:
                    self._cond.wait()
                if self._closed and not self._jobs:
                    return
                job = self._jobs.popleft()
            t0 = time.perf_counter()
            try:
                # failpoint `commit.worker.job`: a worker-side crash at
                # the job boundary — exercises the poison/heal contract
                # without reaching into any particular commit stage. The
                # run loop iterates per JOB (a whole wave's heavy half),
                # not per entry — the boundary IS the decision point.
                # lint: allow(span-in-loop)
                failpoints.fp("commit.worker.job")
                job()
            except BaseException as exc:  # noqa: BLE001 — must not kill
                # the thread (the harness fails the suite on unhandled
                # thread crashes); captured for the next barrier instead
                with self._cond:
                    if self._exc is None:
                        self._exc = exc
                    self.poisoned_total += 1
                    # poison: queued jobs were built on state this
                    # failed commit left undefined — drop, don't run
                    n = len(self._jobs)
                    self._jobs.clear()
                    self._pending -= n
            finally:
                dt = time.perf_counter() - t0
                with self._cond:
                    self.busy_s += dt
                    self.jobs_total += 1
                    # observability ring, same rationale as
                    # TickPipeline.timings: a production daemon's worker
                    # lives for the scheduler's lifetime and must not
                    # accumulate one float per wave forever (consumers
                    # indexing job_s by wave — the bench — read it well
                    # before the first trim)
                    if len(self.job_s) >= 4096:
                        del self.job_s[:2048]
                    self.job_s.append(dt)
                    self._pending -= 1
                    self._cond.notify_all()

    # ------------------------------------------------------------------- API
    @property
    def failed(self) -> bool:
        return self._exc is not None

    def _raise_pending(self):
        exc = self._exc
        if exc is not None:
            raise exc

    def submit(self, job: Callable[[], None]):
        """Enqueue `job` (FIFO). Raises the pending worker exception
        first, if any — a failed plane refuses new work until reset()."""
        with self._cond:
            self._raise_pending()
            if self._closed:
                raise RuntimeError(f"{self.name}: submit after close")
            while self._pending >= self.max_pending and self._exc is None:
                self._cond.wait()
            self._raise_pending()
            self._pending += 1
            self._jobs.append(job)
            self._cond.notify_all()
        self._ensure_thread()

    def barrier(self):
        """Block until every submitted job retired; re-raise the first
        worker exception. After an exception the plane stays poisoned
        (subsequent barriers keep raising) until reset()."""
        with self._cond:
            while self._pending > 0:
                self._cond.wait()
            self._raise_pending()

    def reset(self):
        """Clear a captured exception after the owner healed (resident
        invalidate + re-encode). Any still-queued jobs were already
        dropped by the poison path."""
        with self._cond:
            self._exc = None

    def close(self):
        """Drain and stop the thread (idempotent). Does NOT raise a
        pending exception — close runs on teardown paths that must not
        mask the original failure; call barrier() first if you need it."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=10)

    @property
    def idle(self) -> bool:
        with self._cond:
            return self._pending == 0

    @property
    def pending(self) -> int:
        """Queue depth (submitted, not yet retired) — the /metrics gauge."""
        with self._cond:
            return self._pending
