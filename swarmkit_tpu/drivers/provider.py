"""Secret-driver plugins: secrets whose VALUE comes from an external
provider, fetched per task at assignment time.

Re-derivation of manager/drivers/provider.go:11-34 + secrets.go: a secret
whose spec names a driver carries no payload in the store; when the
dispatcher builds a node's assignments it asks the driver for the value,
scoped to the exact task (the driver sees task/service/node identity and
may mint per-task credentials). The dispatcher clones the secret per task
— id `<secret-id>.<task-id>` — and rewrites the task copy's references,
so one task can never read another's materialized value
(dispatcher/assignments.go:51-81 task-specific cloning).
"""
from __future__ import annotations

import threading
from ..analysis.lockgraph import make_lock
from typing import Callable, Protocol


class SecretDriver(Protocol):
    """One plugin: returns the secret payload for a (secret, task, node)."""

    def get(self, secret, task, node_id: str) -> bytes: ...


class _CallableDriver:
    def __init__(self, fn: Callable):
        self._fn = fn

    def get(self, secret, task, node_id: str) -> bytes:
        return self._fn(secret, task, node_id)


class DriverRegistry:
    """Named driver lookup (provider.go DriverProvider)."""

    def __init__(self):
        self._drivers: dict[str, SecretDriver] = {}
        self._lock = make_lock('drivers.provider.lock')

    def register(self, name: str, driver) -> None:
        if callable(driver) and not hasattr(driver, "get"):
            driver = _CallableDriver(driver)
        with self._lock:
            self._drivers[name] = driver

    def get(self, name: str):
        with self._lock:
            return self._drivers.get(name)

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._drivers)
