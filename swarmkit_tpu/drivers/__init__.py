from .provider import DriverRegistry, SecretDriver

__all__ = ["DriverRegistry", "SecretDriver"]
