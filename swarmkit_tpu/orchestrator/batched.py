"""Array-native orchestration plane (ISSUE 14 tentpole).

Three batched twins of the scalar manager orchestration hot loops, all
decision-identical to their scalar oracles and all killable with
SWARMKIT_TPU_NO_BATCHED_ORCH=1 (the batched-allocator shape):

  * `BatchedReconciler` — per-service slot state for EVERY replicated
    service in one vectorized pass over the columnar task table
    (store/columnar.py hot columns + the `compute_slot_state` kernel in
    ops/reconcile.py): runnable-slot census vs spec.replicas, scale-up
    slot fills, scale-down victim ordering, and dirty-slot candidates
    via the spec-version column. Steady services (the overwhelming
    majority of a 100k-service pass) are classified with ZERO object
    reads and ZERO store transactions; only actionable services pay a
    per-service transaction, which re-validates in-tx with the SAME
    decision code the scalar path runs (the bulk_reconcile shape).

  * `batch_should_restart` — the restart gate
    (`RestartSupervisor.should_restart`) vectorized over a batch of
    dead tasks: the condition/job/state ladder is pure array algebra;
    only tasks under a max_attempts policy fall back to the sequential
    history walk, simulating the interleaved `_record` bookkeeping so a
    batch decides bit-identically to N sequential scalar calls.

  * `UpdateWavePlanner` — ONE clock-driven thread schedules dirty-slot
    replacement waves for ALL updating services, replacing the
    thread-per-service `Updater`: parallelism is a per-service budget
    of concurrent slot flips + delay cooldowns, monitor windows and the
    max_failure_ratio verdict use the scalar formulas, and every store
    write rides the SHARED slot-flip helpers in orchestrator/updater.py
    (the mirror pair "orch-update" pins that). Spec supersede (a live
    pass re-reads the service each step) and cancel (stop() without a
    terminal status write) keep the scalar semantics.

The decision primitives `fill_slots` / `victim_order` are shared with
the scalar `ReplicatedOrchestrator` — both paths call the same
functions on the same summaries, so victim order and slot fills cannot
drift; the ≥20-seed fuzz in tests/test_batched_orch.py pins that the
SUMMARIES (and therefore the decisions) match too. docs/orchestrator.md
has the full plane contract.
"""
from __future__ import annotations

import logging
import os
import threading
from collections import Counter
from dataclasses import dataclass, field

import numpy as np

from ..analysis.lockgraph import make_lock
from ..api.types import (
    TaskState,
    UpdateFailureAction,
    UpdateOrder,
    UpdateStatusState,
)
from ..utils.clock import REAL_CLOCK

log = logging.getLogger("swarmkit_tpu.orchestrator.batched")

# plane-wide op counters (bench `orchestrator_storm` pins the disarmed
# plane at zero entries here — a disabled plane must never be touched)
stats: Counter = Counter()

# per-service slot-census width bound: a service carrying a slot number
# beyond this falls back to the scalar per-service decision (the dense
# flat census would explode); ordinary slots are 1..replicas
MAX_CENSUS_SLOT = 4096


def plane_enabled(store=None) -> bool:
    """Batched orchestration gate: env kill-switch + the columnar plane
    (the reconciler reads hot columns; without them only the wave
    planner could run, and a half-enabled plane is harder to reason
    about than a disabled one)."""
    if os.environ.get("SWARMKIT_TPU_NO_BATCHED_ORCH"):
        return False
    if store is not None and getattr(store, "columnar", None) is None:
        return False
    return True


# ------------------------------------------------------ shared primitives
def fill_slots(used: set, count: int) -> list[int]:
    """Scale-up slot choice: the lowest free slot numbers, from 1
    (replicated/services.go scale-up walk). Shared by the scalar
    reconcile and the batched one — the fill cannot drift."""
    out: list[int] = []
    used = set(used)
    slot_num = 1
    while len(out) < count:
        if slot_num not in used:
            out.append(slot_num)
            used.add(slot_num)
        slot_num += 1
    return out


def victim_order(summaries: dict[int, tuple[bool, list]],
                 excess: int) -> list[int]:
    """Scale-down victim choice, shared by both reconcile paths:
    iteratively drop the slot with (non-running first, busiest node,
    highest slot number), recomputing node load after each pick so ties
    rebalance instead of draining one node. `summaries` maps slot ->
    (any_running, node keys of the slot's tasks); node keys only need
    identity (the scalar passes node-id strings, the batched path vocab
    ints — the arithmetic is identical)."""
    node_load: dict = {}
    for running, nids in summaries.values():
        for nid in nids:
            node_load[nid] = node_load.get(nid, 0) + 1

    def removal_key(item):
        slot, (running, nids) = item
        load = max((node_load.get(n, 0) for n in nids), default=0)
        return (0 if not running else 1, -load, -slot)

    remaining = dict(summaries)
    out: list[int] = []
    for _ in range(min(excess, len(remaining))):
        slot, (running, nids) = min(remaining.items(), key=removal_key)
        del remaining[slot]
        out.append(slot)
        for nid in nids:
            node_load[nid] = max(node_load.get(nid, 1) - 1, 0)
    return out


@dataclass
class ReconcileDecision:
    """One replicated service's reconcile verdict. `dirty_slots` carries
    task OBJECTS (the updater's unit of work); create/victim carry slot
    numbers — application resolves tasks in-tx. `kick_update` flags a
    service whose update status is non-terminal (updating /
    rollback_started) with NO dirty slot left: the update pass must
    still run so it writes its terminal status — the restart supervisor
    can converge the slots on its own (the reference invokes the
    updater on every reconcile; a no-op pass completes the status)."""

    create_slots: list[int] = field(default_factory=list)
    victim_slots: list[int] = field(default_factory=list)
    dirty_slots: list[list] = field(default_factory=list)
    kick_update: bool = False

    @property
    def actionable(self) -> bool:
        return bool(self.create_slots or self.victim_slots)

    @property
    def empty(self) -> bool:
        return not (self.create_slots or self.victim_slots
                    or self.dirty_slots or self.kick_update)


# -------------------------------------------------------- batched reconcile
class BatchedReconciler:
    """The columnar reconciler: classify + decide for many replicated
    services in one array pass. Reads ONLY derived-truth columns
    (store/columnar.py) plus task/service objects for the actionable
    residue; never writes the store (application is the caller's)."""

    def __init__(self, store):
        self.store = store
        self.stats: Counter = Counter()

    # the vectorized pass -------------------------------------------------
    def decide_many(self, service_ids: list[str],
                    view=None) -> dict[str, ReconcileDecision]:
        """One decision per service id (ids that are not live replicated
        services — deleted, global, pending_delete — are omitted, the
        scalar reconcile's entry gate). Decisions are computed from a
        single consistent columnar snapshot; appliers must re-validate
        in-tx (the scalar decision code IS the re-validation)."""
        stats["decide_passes"] += 1
        self.stats["decide_passes"] += 1
        col = getattr(self.store, "columnar", None)
        if col is None:
            raise RuntimeError("batched reconcile needs the columnar plane")
        if view is None:
            view = self.store.view()
        out: dict[str, ReconcileDecision] = {}
        if not service_ids:
            return out

        with self.store._lock:
            scol = col.service_cols
            rows = np.fromiter((scol.row_of(sid) for sid in service_ids),
                               np.int64, len(service_ids))
            known = rows >= 0
            in_scope = known.copy()
            r_safe = np.where(known, rows, 0)
            in_scope &= scol.replicated[r_safe] & \
                ~scol.pending_delete[r_safe]
            scope_rows = rows[in_scope]
            scope_ids = [sid for sid, ok in zip(service_ids, in_scope)
                         if ok]
            # ids the columns have never seen: fall back to the scalar
            # per-service decision (they still get a verdict)
            fallback_ids = [sid for sid, k in zip(service_ids, known)
                            if not k]
            self.stats["services_scanned"] += len(scope_ids)
            if len(scope_ids):
                scoped, oversized_ids = self._decide_scope(
                    view, col, scope_ids, scope_rows)
                out.update(scoped)
                fallback_ids.extend(oversized_ids)
        # scalar fallbacks (never-seen ids, oversized slots) run OUTSIDE
        # the store lock: they walk objects and spec-compare, and a
        # commit must not block behind that
        for sid in fallback_ids:
            d = self._decide_scalar(view, sid)
            if d is not None:
                out[sid] = d
                self.stats["scalar_fallbacks"] += 1
        return out

    def _decide_scope(self, view, col, scope_ids, scope_rows):
        from ..ops.reconcile import compute_slot_state

        scol = col.service_cols
        S = len(scope_ids)
        # compact service index over the vocab domain
        inv = np.full(len(col.services), -1, np.int64)
        inv[scope_rows] = np.arange(S)
        wanted = np.zeros(len(col.services), bool)
        wanted[scope_rows] = True

        n_rows = len(col.ids)
        live = col.valid[:n_rows] & \
            (col.desired[:n_rows] <= int(TaskState.RUNNING))
        sel = np.flatnonzero(live)
        svc_vocab = col.service_idx[sel]
        sel = sel[wanted[svc_vocab]]
        self.stats["task_rows_scanned"] += int(sel.size)

        compact = inv[col.service_idx[sel]]
        sl_raw = col.slot[sel]
        replicas = scol.replicas[scope_rows]
        spec_ver = scol.spec_version[scope_rows]

        # services with out-of-range slots (dense census would explode;
        # negative values would WRAP the flat index) take the scalar
        # fallback — deferred to the CALLER, outside the store lock
        oversize_mask = (sl_raw >= MAX_CENSUS_SLOT) | (sl_raw < 0)
        oversized = np.unique(compact[oversize_mask]) \
            if oversize_mask.any() else np.empty(0, np.int64)
        out: dict[str, ReconcileDecision] = {}
        oversized_ids = [scope_ids[ci] for ci in oversized.tolist()]
        if oversized.size:
            keep_svc = np.ones(S, bool)
            keep_svc[oversized] = False
            keep = keep_svc[compact]
            sel, compact, sl_raw = sel[keep], compact[keep], sl_raw[keep]
        else:
            keep_svc = np.ones(S, bool)

        state = col.state[sel]
        runnable = state <= int(TaskState.RUNNING)
        running = state == int(TaskState.RUNNING)
        M = int(sl_raw.max()) + 1 if sl_raw.size else 1
        used_f, slot_runnable_f, slot_running_f, runnable_slots = \
            compute_slot_state(compact, sl_raw, runnable, running, S, M)
        self.stats["census_cells"] += S * M

        # dirty candidates: spec-version mismatch in a RUNNABLE slot —
        # exactly the rows the scalar is_task_dirty would spec-compare
        key = compact * M + sl_raw
        cand = (col.spec_version[sel] != spec_ver[compact]) \
            & slot_runnable_f[key]
        any_cand = np.zeros(S, bool)
        if cand.any():
            np.maximum.at(any_cand, compact[cand], True)

        scale_up = (runnable_slots < replicas) & keep_svc
        scale_down = (runnable_slots > replicas) & keep_svc
        actionable = scale_up | scale_down | (any_cand & keep_svc)
        # non-terminal update status with nothing else to do: the pass
        # must still be kicked so it writes its terminal status
        in_upd = scol.in_update[scope_rows] & keep_svc
        kick_only = in_upd & ~actionable
        for ci in np.flatnonzero(kick_only).tolist():
            out[scope_ids[ci]] = ReconcileDecision(kick_update=True)
        self.stats["services_steady"] += int(S - int(actionable.sum())
                                             - int(kick_only.sum())
                                             - int((~keep_svc).sum()))
        if not actionable.any():
            return out, oversized_ids

        # group task rows by service once for the actionable residue
        order = np.argsort(compact, kind="stable")
        compact_sorted = compact[order]
        bounds = np.searchsorted(compact_sorted,
                                 np.arange(S + 1))
        act_idx = np.flatnonzero(actionable)
        self.stats["services_actionable"] += int(act_idx.size)
        for ci in act_idx.tolist():
            sid = scope_ids[ci]
            rows_s = sel[order[bounds[ci]:bounds[ci + 1]]]
            d = ReconcileDecision()
            base = ci * M
            if scale_up[ci]:
                used = set(np.flatnonzero(
                    used_f[base:base + M]).tolist())
                d.create_slots = fill_slots(
                    used, int(replicas[ci]) - int(runnable_slots[ci]))
            elif scale_down[ci]:
                summaries: dict[int, tuple[bool, list]] = {}
                for r in rows_s.tolist():
                    s_slot = int(col.slot[r])
                    if not slot_runnable_f[base + s_slot]:
                        continue
                    entry = summaries.get(s_slot)
                    if entry is None:
                        entry = (bool(slot_running_f[base + s_slot]), [])
                        summaries[s_slot] = entry
                    nd = int(col.node_idx[r])
                    if nd > 0:
                        entry[1].append(col.nodes.name(nd))
                d.victim_slots = victim_order(
                    summaries,
                    int(runnable_slots[ci]) - int(replicas[ci]))
            if any_cand[ci]:
                d.dirty_slots = self._dirty_residue(
                    view, col, sid, rows_s, cand, sel, order,
                    bounds[ci], bounds[ci + 1], base, M,
                    slot_runnable_f)
            d.kick_update = bool(in_upd[ci]) and not d.dirty_slots
            if not d.empty:
                out[sid] = d
        return out, oversized_ids

    def _dirty_residue(self, view, col, sid, rows_s, cand, sel, order,
                       lo, hi, base, M, slot_runnable_f):
        """Host residue of the dirty check: spec-compare ONLY the
        version-mismatch candidates, then materialize the dirty slots'
        live task lists (the updater's input shape)."""
        from .task import is_task_dirty

        service = view.get_service(sid)
        if service is None:
            return []
        cand_local = cand[order[lo:hi]]
        dirty_slot_nums: set[int] = set()
        for j, r in enumerate(rows_s.tolist()):
            if not cand_local[j]:
                continue
            t = view.get_task(col.ids[r])
            self.stats["object_reads"] += 1
            if t is not None and is_task_dirty(service, t):
                dirty_slot_nums.add(int(col.slot[r]))
        if not dirty_slot_nums:
            return []
        by_slot: dict[int, list] = {s: [] for s in sorted(dirty_slot_nums)}
        for r in rows_s.tolist():
            s_slot = int(col.slot[r])
            if s_slot in by_slot:
                t = view.get_task(col.ids[r])
                self.stats["object_reads"] += 1
                if t is not None:
                    by_slot[s_slot].append(t)
        return [sorted(ts, key=lambda t: t.id)
                for ts in by_slot.values() if ts]

    # scalar fallback ------------------------------------------------------
    def _decide_scalar(self, view, service_id) -> ReconcileDecision | None:
        from .replicated import decide_service
        from .task import is_replicated
        from ..store import by

        service = view.get_service(service_id)
        if service is None or not is_replicated(service) \
                or service.pending_delete:
            return None
        tasks = [t for t in view.find_tasks(by.ByServiceID(service_id))
                 if t.desired_state <= TaskState.RUNNING]
        return decide_service(service, tasks)


# ---------------------------------------------------- batched restart gate
def batch_should_restart(restart, pairs, now: float | None = None):
    """Vectorized `RestartSupervisor.should_restart` over `pairs` =
    [(service, task), ...], decided bit-identically to N sequential
    scalar calls INCLUDING the interleaved `_record` bookkeeping a
    restarting caller performs: grants earlier in the batch count
    against later same-key grants' max_attempts windows (simulated here;
    the caller's subsequent `_record` makes them real). Window pruning
    of the live history matches the scalar side effect. Returns a bool
    ndarray aligned with `pairs`."""
    from ..api.types import RestartCondition
    from .task import is_job

    n = len(pairs)
    grants = np.zeros(n, bool)
    if not n:
        return grants
    stats["restart_gate_batches"] += 1
    if now is None:
        now = restart._clock.time()

    # pure ladder, one pass of array algebra
    state = np.fromiter((int(t.status.state) for _s, t in pairs),
                        np.int32, n)
    cond_none = np.fromiter(
        (s.spec.task.restart.condition == RestartCondition.NONE
         for s, _t in pairs), bool, n)
    cond_on_failure = np.fromiter(
        (s.spec.task.restart.condition == RestartCondition.ON_FAILURE
         for s, _t in pairs), bool, n)
    job = np.fromiter((is_job(s) for s, _t in pairs), bool, n)
    max_attempts = np.fromiter(
        (s.spec.task.restart.max_attempts for s, _t in pairs), np.int64, n)
    complete = state == int(TaskState.COMPLETE)
    maybe = ~(job & complete) & ~cond_none & ~(cond_on_failure & complete)
    grants[:] = maybe

    # history residue: only policies with max_attempts > 0, walked in
    # batch order with simulated records (scalar interleaving)
    residue = np.flatnonzero(maybe & (max_attempts > 0))
    if residue.size:
        sim_total: dict = {}
        sim_times: dict = {}
        for i in residue.tolist():
            service, task = pairs[i]
            policy = service.spec.task.restart
            key = restart._instance_key(task)
            info = restart._history.get(key)
            total = (info.total_restarts if info is not None else 0) \
                + sim_total.get(key, 0)
            if policy.window <= 0:
                if total >= policy.max_attempts:
                    grants[i] = False
                    continue
            else:
                recent = []
                if info is not None:
                    recent = [r for r in info.restarted_instances
                              if now - r.timestamp <= policy.window]
                    info.restarted_instances = recent  # scalar prune
                n_recent = len(recent) + len([
                    t0 for t0 in sim_times.get(key, ())
                    if now - t0 <= policy.window])
                if n_recent >= policy.max_attempts:
                    grants[i] = False
                    continue
            # granted: simulate the _record the caller will perform
            sim_total[key] = sim_total.get(key, 0) + 1
            if policy.window > 0:
                sim_times.setdefault(key, []).append(now)
    return grants


# ------------------------------------------------------ update wave planner
class _SlotFlip:
    __slots__ = ("slot", "old_tasks", "new_id", "phase", "deadline")

    def __init__(self, slot, old_tasks, new_id, phase, deadline):
        self.slot = slot
        self.old_tasks = old_tasks
        self.new_id = new_id
        self.phase = phase          # 'wait_run' | 'wait_stop'
        self.deadline = deadline


class _ServiceUpdate:
    """Per-service rolling-update state machine inside the shared
    planner: one scalar `Updater._run` unrolled into non-blocking steps.
    Store writes go through the SHARED slot-flip helpers in updater.py
    (the "orch-update" mirror pair's vocabulary)."""

    def __init__(self, service_id: str):
        self.service_id = service_id
        self.phase = "init"          # init -> rolling -> drain -> (done)
        self.cfg = None
        self.rolling_back = False
        self.monitored: dict[str, float] = {}   # new task id -> deadline
        self.failed: set[str] = set()
        self.updated = 0
        self.in_flight: dict[int, _SlotFlip] = {}
        self.pending: list = []                 # queued dirty slot lists
        self.queued_slots: set[int] = set()
        self.cooldowns: list[float] = []        # worker busy-until stamps
        self.retry_at = 0.0                     # store-error backoff
        self.aborted = False
        self.done = False

    # ---- scalar-formula verdicts
    def over_threshold(self) -> bool:
        total = max(self.updated, 1)
        return (self.cfg.max_failure_ratio >= 0 and bool(self.failed)
                and len(self.failed) / total > self.cfg.max_failure_ratio)

    def poll_failures(self, store, now: float) -> None:
        if not self.monitored:
            return
        view = store.view()
        for tid in list(self.monitored):
            t = view.get_task(tid)
            if t is not None and t.status.state in (
                    TaskState.FAILED, TaskState.REJECTED):
                self.failed.add(tid)
                del self.monitored[tid]
            elif now > self.monitored[tid]:
                del self.monitored[tid]    # window expired healthy


class UpdateWavePlanner:
    """ONE thread drives every service's rolling update (ISSUE 14): the
    thread-per-service Updater does not survive a 100k-service mass
    update. Clock-injectable (FakeClock pins monitor-window and delay
    edges deterministically); per-service decisions are pinned
    decision-identical to the threaded Updater by the fuzz in
    tests/test_batched_orch.py."""

    POLL = 0.05

    def __init__(self, store, restart, clock=None):
        self.store = store
        self.restart = restart
        self._clock = clock or REAL_CLOCK
        self._lock = make_lock("orchestrator.updater.planner")
        self._states: dict[str, _ServiceUpdate] = {}
        self._wake = threading.Event()
        self._stop_ev = threading.Event()
        self._thread: threading.Thread | None = None
        self.stats: Counter = Counter()

    # ---------------------------------------------------------------- api
    def update(self, service, dirty_slots) -> None:
        """Supervisor entry: start (or keep) this service's update pass.
        A live pass supersedes in place — it re-reads the service every
        step, so a newer spec redirects the remaining waves (the scalar
        Supervisor.update alive-gate semantics)."""
        with self._lock:
            if self._stop_ev.is_set():
                return
            st = self._states.get(service.id)
            if st is not None and not st.done:
                return
            self._states[service.id] = _ServiceUpdate(service.id)
            self.stats["updates_started"] += 1
            stats["planner_updates"] += 1
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, daemon=True,
                    name="update-wave-planner")
                self._thread.start()
        self._wake.set()

    def active(self) -> list[str]:
        with self._lock:
            return [sid for sid, st in self._states.items() if not st.done]

    def stop(self) -> None:
        """Cancel semantics: in-flight passes stop without a terminal
        status write (the scalar cancel path)."""
        self._stop_ev.set()
        self._wake.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2)

    # --------------------------------------------------------------- loop
    def _run(self):
        while not self._stop_ev.is_set():
            with self._lock:
                done = [sid for sid, st in self._states.items() if st.done]
                for sid in done:
                    del self._states[sid]
                states = list(self._states.values())
            if not states:
                # idle: real-time wait for the next update() regardless
                # of the injected clock (nothing is pacing)
                self._wake.wait(0.2)
                self._wake.clear()
                continue
            for st in states:
                if self._stop_ev.is_set():
                    return
                try:
                    self._step(st)
                except Exception:
                    # store hiccup mid-step: the pass stays live and
                    # retries after the scalar error backoff (1s)
                    log.exception("wave planner: step failed for %s",
                                  st.service_id[:8])
                    st.retry_at = self._clock.monotonic() + 1.0
            self._clock.wait(self._stop_ev, self.POLL)

    # -------------------------------------------------------------- steps
    def _step(self, st: _ServiceUpdate) -> None:
        now = self._clock.monotonic()
        if now < st.retry_at:
            return
        if st.phase == "init":
            self._step_init(st)
            if st.done or st.phase != "rolling":
                return
            now = self._clock.monotonic()
        if st.phase == "rolling":
            self._step_rolling(st, now)
        elif st.phase == "drain":
            self._step_drain(st, now)

    def _step_init(self, st: _ServiceUpdate) -> None:
        from .updater import set_update_status

        service = self.store.view().get_service(st.service_id)
        if service is None:
            st.done = True
            return
        state = (service.update_status or {}).get("state")
        if state in (UpdateStatusState.PAUSED.value,
                     UpdateStatusState.ROLLBACK_PAUSED.value):
            # paused stays paused until the operator acts (updater.go
            # Run:129-134)
            st.done = True
            return
        st.rolling_back = \
            state == UpdateStatusState.ROLLBACK_STARTED.value
        if st.rolling_back:
            from ..api.defaults import default_update_config

            st.cfg = service.spec.rollback or default_update_config()
        else:
            st.cfg = service.spec.update
            set_update_status(self.store, st.service_id,
                              UpdateStatusState.UPDATING,
                              "update in progress")
        st.phase = "rolling"

    def _step_rolling(self, st: _ServiceUpdate, now: float) -> None:
        from .updater import dirty_slots

        st.poll_failures(self.store, now)
        if st.over_threshold() and \
                st.cfg.failure_action != UpdateFailureAction.CONTINUE:
            st.aborted = True
            self._abort_in_flight(st, now)
            self._finalize(st)
            return
        service = self.store.view().get_service(st.service_id)
        if service is None:
            # flips are moot; unwind like the scalar abort-and-drain,
            # with no terminal status write
            st.aborted = True
            self._abort_in_flight(st, now)
            st.done = True
            return
        # advance in-flight flips BEFORE the dirty scan so an errored /
        # finished slot is re-discoverable in the same step
        for slot in list(st.in_flight):
            flip = st.in_flight.get(slot)
            if flip is not None:
                self._advance_slot(st, flip, now)
        fresh = [ts for ts in dirty_slots(self.store, service)
                 if ts[0].slot not in st.queued_slots]
        for ts in fresh:
            st.queued_slots.add(ts[0].slot)
            st.pending.append(ts)
        st.cooldowns = [c for c in st.cooldowns if c > now]
        backlog = len(st.pending) + len(st.in_flight)
        limit = st.cfg.parallelism or (backlog + len(st.cooldowns))
        while st.pending and \
                (len(st.in_flight) + len(st.cooldowns)) < limit:
            ts = st.pending.pop(0)
            try:
                self._start_flip(st, ts, now)
            except Exception:
                st.pending.insert(0, ts)
                raise
        if not st.in_flight and not st.pending and not fresh:
            st.phase = "drain"

    def _step_drain(self, st: _ServiceUpdate, now: float) -> None:
        st.poll_failures(self.store, now)
        if st.monitored and not st.over_threshold():
            return    # monitor tail still open
        self._finalize(st)

    # --------------------------------------------------------- slot flips
    def _start_flip(self, st: _ServiceUpdate, slot_tasks, now: float):
        from .updater import Updater, create_replacement

        slot = slot_tasks[0].slot
        if st.cfg.order == UpdateOrder.START_FIRST:
            new_id = create_replacement(self.store, st.service_id, slot,
                                        TaskState.RUNNING)
            if new_id is None:
                # service vanished mid-create: the rolling step's
                # service-gone gate ends the pass next step
                st.queued_slots.discard(slot)
                return
            st.in_flight[slot] = _SlotFlip(
                slot, slot_tasks, new_id, "wait_run",
                now + Updater.START_FIRST_TIMEOUT)
        else:
            new_id = create_replacement(self.store, st.service_id, slot,
                                        TaskState.READY,
                                        shutdown=slot_tasks)
            if new_id is None:
                st.queued_slots.discard(slot)
                return
            st.in_flight[slot] = _SlotFlip(
                slot, slot_tasks, new_id, "wait_stop",
                now + Updater.SLOT_PHASE_TIMEOUT)
        self.stats["flips_started"] += 1

    def _advance_slot(self, st: _ServiceUpdate, flip: _SlotFlip,
                      now: float) -> None:
        from .updater import promote_task, remove_task, shutdown_tasks

        view = self.store.view()
        if flip.phase == "wait_run":
            t = view.get_task(flip.new_id)
            if t is None or t.status.state >= TaskState.FAILED:
                # died before RUNNING: flows through the monitor window
                # like any young-task death
                self._finish_slot(st, flip, "ok", now)
            elif t.status.state >= TaskState.RUNNING:
                shutdown_tasks(self.store, flip.old_tasks)
                self._finish_slot(st, flip, "ok", now)
            elif now > flip.deadline:
                # wedged replacement: remove it, keep the old task, and
                # count the failure so the policy can act
                remove_task(self.store, flip.new_id)
                self._finish_slot(st, flip, "failed", now)
        else:   # wait_stop
            live = [tid for tid in (t.id for t in flip.old_tasks)
                    if (cur := view.get_task(tid)) is not None
                    and cur.status.state <= TaskState.RUNNING]
            if not live or now > flip.deadline:
                promote_task(self.store, flip.new_id)
                self._finish_slot(st, flip, "ok", now)

    def _finish_slot(self, st: _ServiceUpdate, flip: _SlotFlip,
                     outcome: str, now: float) -> None:
        st.in_flight.pop(flip.slot, None)
        st.queued_slots.discard(flip.slot)
        if outcome == "ok":
            st.updated += 1
            if st.cfg.monitor > 0:
                st.monitored[flip.new_id] = now + st.cfg.monitor
        elif outcome == "failed":
            st.updated += 1
            st.failed.add(flip.new_id or f"slot-{flip.slot}")
        if st.cfg.delay > 0:
            st.cooldowns.append(now + st.cfg.delay)

    def _abort_in_flight(self, st: _ServiceUpdate, now: float) -> None:
        """Policy abort: start-first waiters must not leave an unstarted
        replacement in the slot (removed, uncounted); stop-first waiters
        complete their promote and count (the scalar worker processes a
        returned outcome even after _abort)."""
        from .updater import promote_task, remove_task

        for flip in list(st.in_flight.values()):
            if flip.phase == "wait_run":
                try:
                    remove_task(self.store, flip.new_id)
                except Exception:
                    log.exception("wave planner: abort cleanup failed")
                st.in_flight.pop(flip.slot, None)
                st.queued_slots.discard(flip.slot)
            else:
                try:
                    promote_task(self.store, flip.new_id)
                except Exception:
                    log.exception("wave planner: abort promote failed")
                self._finish_slot(st, flip, "ok", now)
        for ts in st.pending:
            st.queued_slots.discard(ts[0].slot)
        st.pending.clear()

    def _finalize(self, st: _ServiceUpdate) -> None:
        from .updater import finalize_update

        total = max(st.updated, 1)
        finalize_update(self.store, st.service_id, st.cfg,
                        st.rolling_back,
                        st.over_threshold() or st.aborted,
                        len(st.failed), total)
        self.stats["updates_finished"] += 1
        st.done = True
