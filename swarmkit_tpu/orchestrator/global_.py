"""Global-service orchestrator.

Behavioral re-derivation of manager/orchestrator/global/global.go: one task
per eligible node per global service. Constraints are pre-filtered here
(constraint.NodeMatches before creating, global.go:254-487) so tasks are
created with node_id preset and the scheduler only *validates* fit. Drained,
paused or down nodes get their tasks shut down; new/recovered nodes get
tasks created.
"""
from __future__ import annotations

from ..api.objects import (
    EventCreate,
    EventDelete,
    EventUpdate,
    Node,
    Service,
    Task,
)
from ..api.types import NodeAvailability, NodeStatusState, TaskState
from ..scheduler import constraint as constraint_mod
from ..store import by
from .base import EventLoopComponent
from .restart import RestartSupervisor
from .task import mark_shutdown, is_global, new_task, task_runnable


def _constraints_met(node: Node, service: Service) -> bool:
    exprs = service.spec.task.placement.constraints
    if exprs:
        try:
            constraints = constraint_mod.parse(exprs)
        except constraint_mod.InvalidConstraint:
            return False
        if not constraint_mod.node_matches(constraints, node):
            return False
    return True


def _node_eligible(node: Node, service: Service) -> bool:
    """May a NEW global task be added (or a failed one restarted) here?
    Reference global.go:389-392: PAUSE means no add/update."""
    if node.status.state != NodeStatusState.READY:
        return False
    if node.spec.availability != NodeAvailability.ACTIVE:
        return False
    return _constraints_met(node, service)


def _node_keeps_tasks(node: Node, service: Service) -> bool:
    """May EXISTING global tasks keep running here? Distinct from
    eligibility: a PAUSED node keeps its tasks (no add/update only), and
    so does a transiently-UNKNOWN node (leadership change demotes every
    node to UNKNOWN until it re-registers — evicting would churn all
    global services on each election). Shutdown only on DOWN, DRAIN, or
    constraints no longer met (global.go:383-392 + invalid-node check)."""
    if node.status.state == NodeStatusState.DOWN:
        return False
    if node.spec.availability == NodeAvailability.DRAIN:
        return False
    return _constraints_met(node, service)


class GlobalOrchestrator(EventLoopComponent):
    name = "global-orchestrator"

    def __init__(self, store):
        super().__init__(store)
        self.restart = RestartSupervisor(store)

    def stop(self):
        self.restart.stop()
        super().stop()

    def setup(self, tx):
        return [s for s in tx.find_services() if is_global(s)]

    def on_start(self, services):
        # taskinit/init.go CheckTasks — see ReplicatedOrchestrator.on_start
        from .taskinit import check_tasks

        try:
            check_tasks(self.store, self.restart, is_global)
        except Exception:
            pass
        # startup reconciliation of ALL global services in one batched
        # desired-vs-actual diff (ops/reconcile.py) instead of S separate
        # (service × node) walks; identical semantics to reconcile_service
        self.bulk_reconcile([s.id for s in services])

    def bulk_reconcile(self, service_ids: list[str]):
        """Reconcile many global services at once: host-side eligibility
        (string/constraint work), then one `ops.reconcile.compute_diff`
        set-diff for the whole S×N decision matrix, then one store batch
        applying creates/shutdowns."""
        if not service_ids:
            return
        import numpy as np

        from ..ops.reconcile import compute_diff

        plan: list[tuple[str, str, bool]] = []  # (service, node, create?)

        def scan(tx):
            nodes = sorted(tx.find_nodes(), key=lambda n: n.id)
            node_row = {n.id: i for i, n in enumerate(nodes)}
            svcs = []
            for sid in service_ids:
                s = tx.get_service(sid)
                if s is not None and is_global(s) and not s.pending_delete:
                    svcs.append(s)
            if not svcs or not nodes:
                return
            S, N = len(svcs), len(nodes)
            eligible = np.zeros((S, N), bool)   # gates ADDS
            keeps = np.zeros((S, N), bool)      # gates SHUTDOWNS (pause keeps)
            for si, s in enumerate(svcs):
                for ni, n in enumerate(nodes):
                    eligible[si, ni] = _node_eligible(n, s)
                    keeps[si, ni] = _node_keeps_tasks(n, s)
            # two 'actual' sets, as in reconcile_service: create is gated on
            # RUNNABLE tasks; shutdown covers any task with desired<=RUNNING
            runnable_rows: list[list[int]] = []
            active_rows: list[list[int]] = []
            for s in svcs:
                run, act = [], []
                for t in tx.find_tasks(by.ByServiceID(s.id)):
                    if t.desired_state > TaskState.RUNNING:
                        continue
                    ni = node_row.get(t.node_id)
                    if ni is None:
                        continue
                    act.append(ni)
                    if task_runnable(t):
                        run.append(ni)
                runnable_rows.append(run)
                active_rows.append(act)

            def pack(rows_list):
                T = max((len(r) for r in rows_list), default=0) or 1
                out = np.full((S, T), -1, np.int32)
                for si, rows in enumerate(rows_list):
                    out[si, :len(rows)] = rows
                return out

            create, _ = compute_diff(eligible, pack(runnable_rows))
            _, shutdown = compute_diff(keeps, pack(active_rows))
            for si, s in enumerate(svcs):
                for ni in np.flatnonzero(create[si]):
                    plan.append((s.id, nodes[ni].id, True))
                for ni in np.flatnonzero(shutdown[si]):
                    plan.append((s.id, nodes[ni].id, False))

        with_view = getattr(self.store, "view", None)
        tx = with_view()
        scan(tx)
        if not plan:
            return

        def apply(batch):
            for sid, nid, is_create in plan:
                def one(tx, sid=sid, nid=nid, is_create=is_create):
                    service = tx.get_service(sid)
                    if service is None or not is_global(service) \
                            or service.pending_delete:
                        return
                    if is_create:
                        node = tx.get_node(nid)
                        # re-validate inside the tx (state may have moved)
                        if node is None or not _node_eligible(node, service):
                            return
                        exists = any(
                            t.desired_state <= TaskState.RUNNING
                            and task_runnable(t) and t.node_id == nid
                            for t in tx.find_tasks(by.ByServiceID(sid)))
                        if not exists:
                            tx.create(new_task(None, service, 0, node_id=nid))
                    else:
                        node = tx.get_node(nid)
                        if node is not None and \
                                _node_keeps_tasks(node, service):
                            return  # node recovered between scan and apply
                        for t in tx.find_tasks(by.ByServiceID(sid)):
                            if t.node_id != nid or \
                                    t.desired_state > TaskState.RUNNING:
                                continue
                            cur = tx.get_task(t.id)
                            if cur is not None and \
                                    cur.desired_state < TaskState.SHUTDOWN:
                                cur = cur.copy()
                                mark_shutdown(cur)
                                tx.update(cur)
                batch.update(one)

        self.store.batch(apply)

    def handle(self, event):
        obj = getattr(event, "obj", None)
        if isinstance(obj, Service):
            if isinstance(event, EventDelete) or obj.pending_delete:
                self._delete_service_tasks(obj)
            elif is_global(obj):
                self.reconcile_service(obj.id)
        elif isinstance(obj, Node):
            if isinstance(event, EventDelete):
                self._node_removed(obj)
            else:
                self.reconcile_node(obj.id)
        elif isinstance(obj, Task) and isinstance(event, EventUpdate):
            self._handle_task_change(obj)

    # ------------------------------------------------------------- reconcile
    def reconcile_service(self, service_id: str):
        def cb(tx):
            service = tx.get_service(service_id)
            if service is None or not is_global(service) \
                    or service.pending_delete:
                return
            nodes = tx.find_nodes()
            tasks = tx.find_tasks(by.ByServiceID(service_id))
            by_node: dict[str, list[Task]] = {}
            for t in tasks:
                if t.desired_state <= TaskState.RUNNING:
                    by_node.setdefault(t.node_id, []).append(t)
            for node in nodes:
                eligible = _node_eligible(node, service)
                existing = [t for t in by_node.get(node.id, [])
                            if task_runnable(t)]
                if eligible and not existing:
                    t = new_task(None, service, 0, node_id=node.id)
                    tx.create(t)
                elif not _node_keeps_tasks(node, service):
                    for t in by_node.get(node.id, []):
                        cur = tx.get_task(t.id)
                        if cur is not None and cur.desired_state < TaskState.SHUTDOWN:
                            cur = cur.copy()
                            mark_shutdown(cur)
                            tx.update(cur)

        self.store.update(cb)

    def reconcile_node(self, node_id: str):
        def cb(tx):
            node = tx.get_node(node_id)
            if node is None:
                return
            services = [s for s in tx.find_services()
                        if is_global(s) and not s.pending_delete]
            tasks = tx.find_tasks(by.ByNodeID(node_id))
            by_service: dict[str, list[Task]] = {}
            for t in tasks:
                if t.desired_state <= TaskState.RUNNING:
                    by_service.setdefault(t.service_id, []).append(t)
            for service in services:
                eligible = _node_eligible(node, service)
                existing = [t for t in by_service.get(service.id, [])
                            if task_runnable(t)]
                if eligible and not existing:
                    tx.create(new_task(None, service, 0, node_id=node_id))
                elif not _node_keeps_tasks(node, service):
                    for t in by_service.get(service.id, []):
                        cur = tx.get_task(t.id)
                        if cur is not None and cur.desired_state < TaskState.SHUTDOWN:
                            cur = cur.copy()
                            mark_shutdown(cur)
                            tx.update(cur)

        self.store.update(cb)

    def _node_removed(self, node: Node):
        def cb(tx):
            for t in tx.find_tasks(by.ByNodeID(node.id)):
                service = tx.get_service(t.service_id)
                if service is not None and is_global(service):
                    if tx.get_task(t.id) is not None:
                        tx.delete(Task, t.id)

        self.store.update(cb)

    def _handle_task_change(self, task: Task):
        if task.status.state <= TaskState.RUNNING:
            return
        if task.desired_state > TaskState.RUNNING:
            return

        def cb(tx):
            service = tx.get_service(task.service_id)
            if service is None or not is_global(service) \
                    or service.pending_delete:
                return
            node = tx.get_node(task.node_id) if task.node_id else None
            if node is None or not _node_eligible(node, service):
                return
            self.restart.restart(tx, None, service, task)

        self.store.update(cb)

    def _delete_service_tasks(self, service: Service):
        def cb(batch):
            tasks = self.store.view().find_tasks(by.ByServiceID(service.id))
            for t in tasks:
                def delete_one(tx, t=t):
                    if tx.get_task(t.id) is not None:
                        tx.delete(Task, t.id)
                batch.update(delete_one)

        self.store.batch(cb)
