"""Task reaper: garbage collection of dead and removed tasks.

Behavioral re-derivation of manager/orchestrator/taskreaper/task_reaper.go:
  * per-slot history retention — keep at most TaskHistoryRetentionLimit dead
    tasks per (service, slot) / (service, node);
  * tasks with desired_state == REMOVE are deleted once observed shut down
    (their service scaled down or was deleted);
  * ORPHANED tasks are deleted once no longer referenced.
Runs on commit events, batching deletes (task_reaper.go:68-220, tick :232-387).
"""
from __future__ import annotations

from collections import defaultdict

from ..api.objects import EventCommit, EventCreate, EventUpdate, Task
from ..api.types import TaskState
from ..store import by
from .base import EventLoopComponent


class TaskReaper(EventLoopComponent):
    name = "task-reaper"

    def __init__(self, store, retention_limit: int | None = None):
        super().__init__(store)
        self._retention_override = retention_limit
        self._dirty: set[tuple[str, int, str]] = set()
        self._maybe_remove: set[str] = set()

    def _retention(self, tx) -> int:
        if self._retention_override is not None:
            return self._retention_override
        clusters = tx.find_clusters()
        if clusters:
            return clusters[0].spec.task_history_retention_limit
        return 5

    def setup(self, tx):
        # initial sweep: anything already eligible
        for t in tx.find_tasks():
            self._note(t)
        return None

    def on_start(self, _):
        self.tick()

    def _note(self, t: Task):
        if t.desired_state == TaskState.REMOVE or t.status.state == TaskState.ORPHANED:
            self._maybe_remove.add(t.id)
        self._dirty.add((t.service_id, t.slot, t.node_id))

    def handle(self, event):
        if isinstance(event, (EventCreate, EventUpdate)) and isinstance(
                event.obj, Task):
            self._note(event.obj)
        elif isinstance(event, EventCommit):
            if self._dirty or self._maybe_remove:
                self.tick()

    def tick(self):
        dirty, self._dirty = self._dirty, set()
        maybe_remove, self._maybe_remove = self._maybe_remove, set()
        deletes: list[str] = []

        view = self.store.view()
        retention = self._retention(view)

        for task_id in maybe_remove:
            t = view.get_task(task_id)
            if t is None:
                continue
            # reference task_reaper.go:181: REMOVE-desired tasks go once they
            # were never assigned (slot removed before scheduling) or once the
            # agent observed them past COMPLETE
            if t.desired_state == TaskState.REMOVE and (
                    t.status.state < TaskState.ASSIGNED
                    or t.status.state >= TaskState.COMPLETE):
                deletes.append(t.id)
            elif t.status.state == TaskState.ORPHANED:
                deletes.append(t.id)

        if retention >= 0:
            by_slot: dict[tuple, list[Task]] = defaultdict(list)
            for service_id, slot, node_id in dirty:
                if not service_id:
                    continue
                sel = (by.BySlot(service_id, slot) if slot
                       else by.ByServiceID(service_id))
                for t in view.find_tasks(sel):
                    if slot == 0 and t.node_id != node_id:
                        continue
                    key = (service_id, slot, node_id if not slot else "")
                    by_slot[key].append(t)
            for key, ts in by_slot.items():
                dead = sorted(
                    (t for t in ts
                     if t.desired_state > TaskState.RUNNING
                     and t.status.state > TaskState.RUNNING
                     and t.desired_state != TaskState.REMOVE),
                    key=lambda t: t.status.timestamp,
                )
                excess = len(dead) - retention
                for t in dead[:max(excess, 0)]:
                    deletes.append(t.id)

        if not deletes:
            return

        def cb(batch):
            for tid in deletes:
                def delete_one(tx, tid=tid):
                    if tx.get_task(tid) is not None:
                        tx.delete(Task, tid)
                batch.update(delete_one)

        self.store.batch(cb)
