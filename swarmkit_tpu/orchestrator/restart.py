"""Restart supervisor.

Behavioral re-derivation of manager/orchestrator/restart/restart.go: decides
whether a dead task restarts (condition any/on-failure/none), enforces
MaxAttempts within Window via per-slot history, marks the old task
desired=SHUTDOWN, creates the replacement in the same slot with
desired=READY, and promotes it to RUNNING after the configured delay
(DelayStart, restart.go:433-524).
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field

from ..analysis.lockgraph import make_lock
from ..api.objects import Service, Task
from ..api.types import RestartCondition, TaskState
from ..store.memory import MemoryStore
from .task import mark_shutdown, is_job, new_task


@dataclass
class RestartedInstance:
    timestamp: float


@dataclass
class InstanceRestartInfo:
    total_restarts: int = 0
    restarted_instances: list[RestartedInstance] = field(default_factory=list)


class RestartSupervisor:
    def __init__(self, store: MemoryStore, clock=None):
        from ..utils.clock import REAL_CLOCK

        self.store = store
        self._history: dict[tuple[str, int | str], InstanceRestartInfo] = {}
        self._delays: dict[str, threading.Timer] = {}
        self._lock = make_lock('orchestrator.restart.lock')
        self._stopped = False
        # injectable time source: the batched restart gate
        # (orchestrator/batched.py) and FakeClock window-edge pins read
        # the same clock the scalar gate does
        self._clock = clock or REAL_CLOCK

    def stop(self):
        with self._lock:
            self._stopped = True
            timers = list(self._delays.values())
            self._delays.clear()
        for t in timers:
            t.cancel()

    # ------------------------------------------------------------------ api
    def restart(self, tx, cluster, service: Service, task: Task) -> None:
        """Called within a store transaction when a task died
        (reference restart.go:117-213)."""
        # mark old task for shutdown if not already
        cur = tx.get_task(task.id)
        if cur is not None and cur.desired_state < TaskState.SHUTDOWN:
            cur = cur.copy()
            mark_shutdown(cur)
            tx.update(cur)

        if not self.should_restart(task, service):
            return

        self._spawn_replacement(tx, cluster, service, task)

    def restart_many(self, tx, cluster, pairs) -> None:
        """Batch form of `restart` for many dead tasks in ONE
        transaction (node-down rescheduling): the gate runs VECTORIZED
        (orchestrator/batched.py batch_should_restart, bit-identical to
        sequential scalar calls including interleaved history records),
        then each granted task spawns its replacement exactly like the
        scalar path."""
        from .batched import batch_should_restart

        grants = batch_should_restart(self, pairs)
        for (service, task), granted in zip(pairs, grants):
            cur = tx.get_task(task.id)
            if cur is not None and cur.desired_state < TaskState.SHUTDOWN:
                cur = cur.copy()
                mark_shutdown(cur)
                tx.update(cur)
            if granted:
                self._spawn_replacement(tx, cluster, service, task)

    def _spawn_replacement(self, tx, cluster, service: Service,
                           task: Task) -> None:
        replacement = new_task(cluster, service, task.slot,
                               task.node_id if not task.slot else "")
        replacement.desired_state = TaskState.READY
        tx.create(replacement)

        self._record(task, service)
        delay = service.spec.task.restart.delay
        # job tasks run to completion; service tasks run indefinitely
        target = TaskState.COMPLETE if is_job(service) else TaskState.RUNNING
        self._delay_start(replacement.id, delay, target)

    def should_restart(self, task: Task, service: Service) -> bool:
        """reference restart.go:215+ shouldRestart."""
        if is_job(service) and task.status.state == TaskState.COMPLETE:
            return False
        condition = service.spec.task.restart.condition
        if condition == RestartCondition.NONE:
            return False
        if condition == RestartCondition.ON_FAILURE and task.status.state in (
                TaskState.COMPLETE,):
            return False
        restart_policy = service.spec.task.restart
        if restart_policy.max_attempts > 0:
            key = self._instance_key(task)
            info = self._history.get(key)
            if info is not None:
                if restart_policy.window <= 0:
                    if info.total_restarts >= restart_policy.max_attempts:
                        return False
                else:
                    now = self._clock.time()
                    recent = [
                        r for r in info.restarted_instances
                        if now - r.timestamp <= restart_policy.window
                    ]
                    info.restarted_instances = recent
                    if len(recent) >= restart_policy.max_attempts:
                        return False
        return True

    # ------------------------------------------------------------ internals
    def _instance_key(self, task: Task):
        return (task.service_id, task.slot if task.slot else task.node_id)

    def _record(self, task: Task, service: Service) -> None:
        key = self._instance_key(task)
        info = self._history.setdefault(key, InstanceRestartInfo())
        info.total_restarts += 1
        if service.spec.task.restart.window > 0:
            info.restarted_instances.append(
                RestartedInstance(self._clock.time()))

    def resume_delay(self, task: Task, service: Service) -> None:
        """Re-arm the READY→RUNNING promote timer for a task found in
        delayed-start limbo at startup (the timer is in-memory state that
        dies with its leader; taskinit re-creates it on the successor)."""
        delay = service.spec.task.restart.delay if service is not None else 0.0
        self._delay_start(task.id, delay)

    def _delay_start(self, task_id: str, delay: float,
                     target: TaskState = TaskState.RUNNING) -> None:
        """Promote READY→target after the restart delay."""

        def promote():
            with self._lock:
                self._delays.pop(task_id, None)
                if self._stopped:
                    return

            def cb(tx):
                cur = tx.get_task(task_id)
                if cur is None or cur.desired_state != TaskState.READY:
                    return
                cur = cur.copy()
                cur.desired_state = target
                tx.update(cur)

            try:
                self.store.update(cb)
            except Exception:
                pass

        with self._lock:
            if self._stopped:
                return
            # served by the injected clock's timer service (the shared
            # TimerWheel under the real clock — no thread per armed
            # delay; FakeClock in tests fires on advance()). A zero
            # delay still goes through the wheel: we are called inside
            # the transaction that created the task, so the promote
            # must run on a fresh one
            self._delays[task_id] = self._clock.timer(max(delay, 0.0),
                                                      promote)

    def cancel_delay(self, task_id: str) -> None:
        with self._lock:
            t = self._delays.pop(task_id, None)
        if t:
            t.cancel()
