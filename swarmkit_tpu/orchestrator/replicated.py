"""Replicated-service orchestrator.

Behavioral re-derivation of manager/orchestrator/replicated/: reconciles each
replicated service's slot set against spec.replicas — scale-up creates NEW
tasks in free slots, scale-down prefers shutting slots on the most-loaded
nodes and non-running slots first (services.go:95-190) — and closes the
failure loop by routing dead tasks through the restart supervisor
(tasks.go:47-149). Node-down rescheduling (restartTasksByNodeID) also lives
here, shared with the global orchestrator via OrchestratorBase.
"""
from __future__ import annotations

import logging

from ..api.objects import (
    EventCommit,
    EventCreate,
    EventDelete,
    EventUpdate,
    Node,
    Service,
    Task,
)
from ..api.types import NodeAvailability, NodeStatusState, TaskState
from ..store import by
from .base import EventLoopComponent
from .batched import (
    BatchedReconciler,
    ReconcileDecision,
    fill_slots,
    plane_enabled,
    victim_order,
)
from .restart import RestartSupervisor
from .task import (
    is_replicated,
    is_task_dirty,
    new_task,
    slot_runnable,
    slots_by_service,
)
from .updater import UpdateSupervisor

log = logging.getLogger("swarmkit_tpu.orchestrator")

# bursts at or below this size skip the columnar pass (it scans the
# whole task table; an indexed per-service reconcile is cheaper until
# the burst amortizes the scan)
SMALL_RECONCILE_BATCH = 4


def decide_service(service, tasks) -> ReconcileDecision:
    """The scalar reconcile DECISION (replicated/services.go:95-190),
    separated from application: slot census over the live tasks
    (desired <= RUNNING), scale-up fills / scale-down victims via the
    shared primitives in orchestrator/batched.py, and the dirty-slot
    set for the rolling updater. The batched reconciler's vectorized
    pass is pinned decision-identical to this function (the ≥20-seed
    fuzz in tests/test_batched_orch.py)."""
    d = ReconcileDecision()
    slots = slots_by_service(tasks).get(service.id, {})
    runnable = {
        slot: ts for slot, ts in slots.items() if slot_runnable(ts)
    }
    specified = service.spec.replicas
    if len(runnable) < specified:
        # scale up: fill the lowest free slot numbers
        d.create_slots = fill_slots(set(slots.keys()),
                                    specified - len(runnable))
    elif len(runnable) > specified:
        # scale down: keep running slots on least-loaded nodes,
        # iteratively recomputing load after each pick (victim_order)
        summaries = {
            slot: (any(t.status.state == TaskState.RUNNING for t in ts),
                   [t.node_id for t in ts if t.node_id])
            for slot, ts in runnable.items()
        }
        d.victim_slots = victim_order(summaries,
                                      len(runnable) - specified)
    # dirty slots (spec changed) → rolling updater; normalized slot /
    # task-id order so both deciders emit the identical structure
    d.dirty_slots = [
        sorted(runnable[slot], key=lambda t: t.id)
        for slot in sorted(runnable)
        if any(is_task_dirty(service, t) for t in runnable[slot])
    ]
    # a non-terminal update status with no dirty slot left still needs
    # its pass kicked (the restart supervisor can converge the slots on
    # its own; only the update pass writes the terminal status)
    d.kick_update = not d.dirty_slots and (
        (service.update_status or {}).get("state")
        in ("updating", "rollback_started"))
    return d


class ReplicatedOrchestrator(EventLoopComponent):
    name = "replicated-orchestrator"

    def __init__(self, store):
        super().__init__(store)
        self.restart = RestartSupervisor(store)
        self.updater = UpdateSupervisor(store, self.restart)
        # batched orchestration plane (ISSUE 14): vectorized reconcile
        # passes over the columnar hot columns; scalar per-service path
        # stays the oracle (SWARMKIT_TPU_NO_BATCHED_ORCH=1 reverts)
        self.batched: BatchedReconciler | None = (
            BatchedReconciler(store) if plane_enabled(store) else None)
        self._pending_reconcile: set[str] = set()

    def stop(self):
        self.updater.stop()
        self.restart.stop()
        super().stop()

    # ----------------------------------------------------------------- setup
    def setup(self, tx):
        return [s for s in tx.find_services() if is_replicated(s)]

    def on_start(self, services):
        # startup fix-up first (taskinit/init.go CheckTasks): a fresh leader
        # inherits tasks stranded mid-lifecycle — dead-but-unreplaced, in
        # flight on nodes that went down unwatched, or parked in restart
        # -delay limbo whose promote timer died with the old leader
        from .taskinit import check_tasks

        try:
            check_tasks(self.store, self.restart, is_replicated)
        except Exception:
            log.exception("%s: startup task fix-up failed", self.name)
        if self.batched is not None:
            # one vectorized classification pass instead of S serial
            # find_tasks walks; only actionable services pay a tx
            self.reconcile_many([s.id for s in services])
        else:
            for s in services:
                self.reconcile(s.id)

    # ---------------------------------------------------------------- events
    def handle(self, event):
        if isinstance(event, (EventCreate, EventUpdate)) and isinstance(
                event.obj, Service):
            if event.obj.pending_delete:
                # wind the tasks down so the deallocator can finish the
                # removal (deallocator.go waits for the last task)
                self._delete_service_tasks(event.obj)
            elif is_replicated(event.obj):
                if self.batched is not None:
                    # coalesce the burst; flush_events applies ONE
                    # vectorized pass over it
                    self._pending_reconcile.add(event.obj.id)
                else:
                    self.reconcile(event.obj.id)
        elif isinstance(event, EventDelete) and isinstance(event.obj, Service):
            self._delete_service_tasks(event.obj)
        elif isinstance(event, EventUpdate) and isinstance(event.obj, Task):
            self._handle_task_change(event.obj)
        elif isinstance(event, EventDelete) and isinstance(event.obj, Task):
            t = event.obj
            if t.service_id:
                if self.batched is not None:
                    self._pending_reconcile.add(t.service_id)
                else:
                    self.reconcile(t.service_id)
        elif isinstance(event, EventUpdate) and isinstance(event.obj, Node):
            self._handle_node_change(event.obj)

    def flush_events(self):
        if not self._pending_reconcile:
            return
        ids = sorted(self._pending_reconcile)
        self._pending_reconcile.clear()
        try:
            self.reconcile_many(ids)
        except Exception:
            # a crashed burst must not drop its reconciles (the
            # dispatcher's crashed-flush re-dirty contract): re-dirty
            # everything and let idle()/the next burst retry — the
            # per-service reconcile is idempotent
            self._pending_reconcile.update(ids)
            raise

    def idle(self):
        # retry a re-dirtied burst even when no further event arrives
        self.flush_events()

    # ------------------------------------------------------------- reconcile
    def reconcile(self, service_id: str):
        """reference: replicated/services.go:95-190 (scalar path: decide
        + apply in one transaction)."""
        self.store.update(
            lambda tx: self._reconcile_in_tx(tx, service_id))

    def _reconcile_in_tx(self, tx, service_id: str):
        service = tx.get_service(service_id)
        if service is None or not is_replicated(service) \
                or service.pending_delete:
            return
        tasks = [
            t for t in tx.find_tasks(by.ByServiceID(service_id))
            if t.desired_state <= TaskState.RUNNING
        ]
        decision = decide_service(service, tasks)
        slots = slots_by_service(tasks).get(service_id, {})
        for slot_num in decision.create_slots:
            tx.create(new_task(None, service, slot_num))
        for slot_num in decision.victim_slots:
            for t in slots.get(slot_num, ()):
                cur = tx.get_task(t.id)
                if cur is not None \
                        and cur.desired_state < TaskState.REMOVE:
                    cur = cur.copy()
                    cur.desired_state = TaskState.REMOVE
                    tx.update(cur)
        if decision.dirty_slots or decision.kick_update:
            self.updater.update(service, decision.dirty_slots)

    def reconcile_many(self, service_ids: list[str]):
        """Batched reconcile (ISSUE 14): classify every service in one
        columnar array pass; steady services cost zero transactions and
        zero object reads. Actionable services re-validate IN-TX with
        the scalar decision code (the bulk_reconcile shape — decisions
        from the snapshot select WHO pays a transaction, the tx decides
        WHAT it does), batched into one store.batch. Dirty-only
        services just feed the updater."""
        if not service_ids:
            return
        if self.batched is None or \
                len(service_ids) <= SMALL_RECONCILE_BATCH:
            # tiny bursts (a lone task-delete event) keep the indexed
            # per-service path: the columnar pass scans ALL task rows,
            # which only pays off when the burst amortizes it (the
            # compute_slot_state DIFF_THRESHOLD idea, one level up)
            for sid in service_ids:
                self.reconcile(sid)
            return
        view = self.store.view()
        decisions = self.batched.decide_many(service_ids, view=view)
        actionable = {sid for sid, d in decisions.items() if d.actionable}
        for sid, d in decisions.items():
            if (d.dirty_slots or d.kick_update) and sid not in actionable:
                service = view.get_service(sid)
                if service is not None:
                    self.updater.update(service, d.dirty_slots)

        if actionable:
            def apply(batch):
                for sid in sorted(actionable):
                    def one(tx, sid=sid):
                        self._reconcile_in_tx(tx, sid)
                    batch.update(one)

            self.store.batch(apply)

    # ----------------------------------------------------------- task events
    def _handle_task_change(self, task: Task):
        """Dead task whose slot is still desired → restart
        (reference replicated/tasks.go:47-149)."""
        if task.status.state <= TaskState.RUNNING:
            return
        if task.desired_state > TaskState.RUNNING:
            return  # shutdown was requested; reaper handles cleanup

        def cb(tx):
            service = tx.get_service(task.service_id)
            if service is None or not is_replicated(service) \
                    or service.pending_delete:
                return
            if task.slot > service.spec.replicas:
                return
            self.restart.restart(tx, None, service, task)

        self.store.update(cb)

    # ----------------------------------------------------------- node events
    def _handle_node_change(self, node: Node):
        down = (node.status.state == NodeStatusState.DOWN
                or node.spec.availability == NodeAvailability.DRAIN)
        if not down:
            return

        batched = self.batched is not None

        def cb(tx):
            pairs = []
            for task in tx.find_tasks(by.ByNodeID(node.id)):
                if task.desired_state > TaskState.RUNNING:
                    continue
                if task.status.state > TaskState.RUNNING:
                    continue
                service = tx.get_service(task.service_id)
                if service is None or not is_replicated(service):
                    continue
                if batched:
                    pairs.append((service, task))
                else:
                    self.restart.restart(tx, None, service, task)
            if pairs:
                # one vectorized restart gate for the whole node's
                # victims (bit-identical to the sequential calls)
                self.restart.restart_many(tx, None, pairs)

        self.store.update(cb)

    def _delete_service_tasks(self, service: Service):
        def cb(batch):
            tasks = self.store.view().find_tasks(by.ByServiceID(service.id))
            for t in tasks:
                def delete_one(tx, t=t):
                    if tx.get_task(t.id) is not None:
                        tx.delete(Task, t.id)
                batch.update(delete_one)

        self.store.batch(cb)
