"""Replicated-service orchestrator.

Behavioral re-derivation of manager/orchestrator/replicated/: reconciles each
replicated service's slot set against spec.replicas — scale-up creates NEW
tasks in free slots, scale-down prefers shutting slots on the most-loaded
nodes and non-running slots first (services.go:95-190) — and closes the
failure loop by routing dead tasks through the restart supervisor
(tasks.go:47-149). Node-down rescheduling (restartTasksByNodeID) also lives
here, shared with the global orchestrator via OrchestratorBase.
"""
from __future__ import annotations

import logging
from collections import defaultdict

from ..api.objects import (
    EventCommit,
    EventCreate,
    EventDelete,
    EventUpdate,
    Node,
    Service,
    Task,
)
from ..api.types import NodeAvailability, NodeStatusState, TaskState
from ..store import by
from .base import EventLoopComponent
from .restart import RestartSupervisor
from .task import (
    is_replicated,
    is_task_dirty,
    new_task,
    slot_runnable,
    slots_by_service,
    task_runnable,
)
from .updater import UpdateSupervisor

log = logging.getLogger("swarmkit_tpu.orchestrator")


class ReplicatedOrchestrator(EventLoopComponent):
    name = "replicated-orchestrator"

    def __init__(self, store):
        super().__init__(store)
        self.restart = RestartSupervisor(store)
        self.updater = UpdateSupervisor(store, self.restart)

    def stop(self):
        self.updater.stop()
        self.restart.stop()
        super().stop()

    # ----------------------------------------------------------------- setup
    def setup(self, tx):
        return [s for s in tx.find_services() if is_replicated(s)]

    def on_start(self, services):
        # startup fix-up first (taskinit/init.go CheckTasks): a fresh leader
        # inherits tasks stranded mid-lifecycle — dead-but-unreplaced, in
        # flight on nodes that went down unwatched, or parked in restart
        # -delay limbo whose promote timer died with the old leader
        from .taskinit import check_tasks

        try:
            check_tasks(self.store, self.restart, is_replicated)
        except Exception:
            log.exception("%s: startup task fix-up failed", self.name)
        for s in services:
            self.reconcile(s.id)

    # ---------------------------------------------------------------- events
    def handle(self, event):
        if isinstance(event, (EventCreate, EventUpdate)) and isinstance(
                event.obj, Service):
            if event.obj.pending_delete:
                # wind the tasks down so the deallocator can finish the
                # removal (deallocator.go waits for the last task)
                self._delete_service_tasks(event.obj)
            elif is_replicated(event.obj):
                self.reconcile(event.obj.id)
        elif isinstance(event, EventDelete) and isinstance(event.obj, Service):
            self._delete_service_tasks(event.obj)
        elif isinstance(event, EventUpdate) and isinstance(event.obj, Task):
            self._handle_task_change(event.obj)
        elif isinstance(event, EventDelete) and isinstance(event.obj, Task):
            t = event.obj
            if t.service_id:
                self.reconcile(t.service_id)
        elif isinstance(event, EventUpdate) and isinstance(event.obj, Node):
            self._handle_node_change(event.obj)

    # ------------------------------------------------------------- reconcile
    def reconcile(self, service_id: str):
        """reference: replicated/services.go:95-190."""

        def cb(tx):
            service = tx.get_service(service_id)
            if service is None or not is_replicated(service) \
                    or service.pending_delete:
                return
            tasks = [
                t for t in tx.find_tasks(by.ByServiceID(service_id))
                if t.desired_state <= TaskState.RUNNING
            ]
            slots = slots_by_service(tasks).get(service_id, {})
            runnable = {
                slot: ts for slot, ts in slots.items() if slot_runnable(ts)
            }
            specified = service.spec.replicas

            if len(runnable) < specified:
                # scale up: fill the lowest free slot numbers
                used = set(slots.keys())
                slot_num = 1
                to_create = specified - len(runnable)
                created = 0
                while created < to_create:
                    if slot_num not in used:
                        t = new_task(None, service, slot_num)
                        tx.create(t)
                        used.add(slot_num)
                        created += 1
                    slot_num += 1
            elif len(runnable) > specified:
                # scale down: keep running slots on least-loaded nodes
                # (reference sorts by running-state then node balance)
                node_load: dict[str, int] = defaultdict(int)
                for ts in runnable.values():
                    for t in ts:
                        if t.node_id:
                            node_load[t.node_id] += 1

                # iterative removal: repeatedly drop a slot from the
                # currently busiest node (non-running slots first),
                # recomputing load after each pick so ties rebalance —
                # a static sort would drain one node completely
                def removal_key(item):
                    slot, ts = item
                    running = any(
                        t.status.state == TaskState.RUNNING for t in ts)
                    load = max((node_load.get(t.node_id, 0)
                                for t in ts if t.node_id), default=0)
                    # non-running slots go first, then busiest node,
                    # then highest slot number
                    return (0 if not running else 1, -load, -slot)

                remaining = dict(runnable)
                for _ in range(len(runnable) - specified):
                    slot, ts = min(remaining.items(), key=removal_key)
                    del remaining[slot]
                    for t in ts:
                        if t.node_id:
                            node_load[t.node_id] = max(
                                node_load.get(t.node_id, 1) - 1, 0)
                        cur = tx.get_task(t.id)
                        if cur is not None and cur.desired_state < TaskState.REMOVE:
                            cur = cur.copy()
                            cur.desired_state = TaskState.REMOVE
                            tx.update(cur)

            # dirty slots (spec changed) → rolling updater
            dirty = [
                ts for ts in runnable.values()
                if any(is_task_dirty(service, t) for t in ts)
            ]
            if dirty:
                self.updater.update(service, dirty)

        self.store.update(cb)

    # ----------------------------------------------------------- task events
    def _handle_task_change(self, task: Task):
        """Dead task whose slot is still desired → restart
        (reference replicated/tasks.go:47-149)."""
        if task.status.state <= TaskState.RUNNING:
            return
        if task.desired_state > TaskState.RUNNING:
            return  # shutdown was requested; reaper handles cleanup

        def cb(tx):
            service = tx.get_service(task.service_id)
            if service is None or not is_replicated(service) \
                    or service.pending_delete:
                return
            if task.slot > service.spec.replicas:
                return
            self.restart.restart(tx, None, service, task)

        self.store.update(cb)

    # ----------------------------------------------------------- node events
    def _handle_node_change(self, node: Node):
        down = (node.status.state == NodeStatusState.DOWN
                or node.spec.availability == NodeAvailability.DRAIN)
        if not down:
            return

        def cb(tx):
            for task in tx.find_tasks(by.ByNodeID(node.id)):
                if task.desired_state > TaskState.RUNNING:
                    continue
                if task.status.state > TaskState.RUNNING:
                    continue
                service = tx.get_service(task.service_id)
                if service is None or not is_replicated(service):
                    continue
                self.restart.restart(tx, None, service, task)

        self.store.update(cb)

    def _delete_service_tasks(self, service: Service):
        def cb(batch):
            tasks = self.store.view().find_tasks(by.ByServiceID(service.id))
            for t in tasks:
                def delete_one(tx, t=t):
                    if tx.get_task(t.id) is not None:
                        tx.delete(Task, t.id)
                batch.update(delete_one)

        self.store.batch(cb)
