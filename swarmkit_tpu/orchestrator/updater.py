"""Rolling-update supervisor.

Behavioral re-derivation of manager/orchestrator/update/updater.go: dirty
slots are replaced `parallelism` at a time with `delay` between batches,
honoring stop-first vs start-first order; new-task failures within the
monitor window count toward max_failure_ratio, and crossing it triggers the
configured failure action (pause / continue / rollback —
updater.go:204-260, 566-626). One Updater thread runs per service; a newer
spec supersedes the running update (Supervisor.Update spec-diff gate,
updater.go:49-75).
"""
from __future__ import annotations

import logging
import threading
import time

from ..api.objects import EventUpdate, Task
from ..api.specs import deepcopy_spec
from ..api.types import (
    TaskState,
    UpdateFailureAction,
    UpdateOrder,
    UpdateStatusState,
)
from ..store import by
from .task import is_task_dirty, new_task

log = logging.getLogger("swarmkit_tpu.orchestrator.updater")


class Updater(threading.Thread):
    def __init__(self, store, restart, service_id: str, supervisor):
        super().__init__(daemon=True, name=f"updater-{service_id[:8]}")
        self.store = store
        self.restart = restart
        self.service_id = service_id
        self.supervisor = supervisor
        self._cancel = threading.Event()

    def cancel(self):
        self._cancel.set()

    def run(self):
        try:
            self._run()
        finally:
            self.supervisor._done(self.service_id, self)

    def _run(self):
        service = self.store.view().get_service(self.service_id)
        if service is None:
            return
        cfg = service.spec.update
        self._set_update_status(UpdateStatusState.UPDATING, "update in progress")

        # monitored: task_id -> monitor deadline; failures accrue
        # asynchronously so batches are NOT serialized behind the window
        # (the reference overlaps monitoring with subsequent batches)
        monitored: dict[str, float] = {}
        failed: set[str] = set()
        updated = 0

        def poll_failures():
            if not monitored:
                return
            view = self.store.view()
            now = time.monotonic()
            for tid in list(monitored):
                t = view.get_task(tid)
                if t is not None and t.status.state in (
                        TaskState.FAILED, TaskState.REJECTED):
                    failed.add(tid)
                    del monitored[tid]
                elif now > monitored[tid]:
                    del monitored[tid]  # window expired healthy

        def over_threshold() -> bool:
            total = max(updated, 1)
            return (cfg.max_failure_ratio >= 0 and failed
                    and len(failed) / total > cfg.max_failure_ratio)

        while not self._cancel.is_set():
            service = self.store.view().get_service(self.service_id)
            if service is None:
                return
            dirty = self._dirty_slots(service)
            if not dirty:
                break
            parallelism = cfg.parallelism or len(dirty)
            batch = dirty[:parallelism]
            # slot flips observe task states (two-phase orders), so the
            # batch runs them concurrently like the reference's worker
            # pool (updater.go:190-200)
            new_ids: list[str | None] = [None] * len(batch)

            def flip(i, slot_tasks):
                try:
                    new_ids[i] = self._update_slot(slot_tasks, cfg.order)
                except Exception:
                    log.exception("updater %s: slot flip failed",
                                  self.service_id[:8])
                    new_ids[i] = None

            workers = [threading.Thread(target=flip, args=(i, st),
                                        daemon=True)
                       for i, st in enumerate(batch)]
            for w in workers:
                w.start()
            for w in workers:
                w.join()
            for nid in new_ids:
                if nid is None:
                    continue  # failed flips don't dilute the failure ratio
                if cfg.monitor > 0:
                    monitored[nid] = time.monotonic() + cfg.monitor
                updated += 1
            if not any(new_ids):
                # every flip failed (store unavailable during churn): back
                # off instead of hot-spinning fresh batches
                if self._cancel.wait(1.0):
                    return
            poll_failures()
            # CONTINUE keeps rolling despite failures; PAUSE/ROLLBACK stop
            if over_threshold() and \
                    cfg.failure_action != UpdateFailureAction.CONTINUE:
                break
            if cfg.delay > 0 and self._cancel.wait(cfg.delay):
                return

        # drain remaining monitor windows (non-blocking batches above mean
        # only the tail waits here), still reacting to failures promptly
        while monitored and not self._cancel.is_set() and not over_threshold():
            if self._cancel.wait(0.05):
                return
            poll_failures()

        if over_threshold():
            total = max(updated, 1)
            if cfg.failure_action == UpdateFailureAction.PAUSE:
                self._set_update_status(
                    UpdateStatusState.PAUSED,
                    f"update paused due to failure ratio {len(failed)}/{total}")
            elif cfg.failure_action == UpdateFailureAction.ROLLBACK:
                self._rollback(self.store.view().get_service(self.service_id))
            else:
                self._set_update_status(
                    UpdateStatusState.COMPLETED,
                    f"update completed with {len(failed)} failures")
            return
        if not self._cancel.is_set():
            self._set_update_status(UpdateStatusState.COMPLETED, "update completed")

    # ------------------------------------------------------------------ steps
    def _dirty_slots(self, service) -> list[list[Task]]:
        tasks = self.store.view().find_tasks(by.ByServiceID(self.service_id))
        from .task import slots_by_service, slot_runnable
        slots = slots_by_service(tasks).get(self.service_id, {})
        dirty = []
        for slot, ts in sorted(slots.items()):
            live = [t for t in ts if t.desired_state <= TaskState.RUNNING]
            if not live or not slot_runnable(live):
                continue
            if any(is_task_dirty(service, t) for t in live):
                dirty.append(live)
        return dirty

    # bound for the stop-first old-task drain
    SLOT_PHASE_TIMEOUT = 30.0
    # bound for the start-first replacement start: generous (slow prepares
    # are legitimate), and on expiry the stuck replacement is REMOVED so
    # the retry can't accumulate duplicates in the slot
    START_FIRST_TIMEOUT = 600.0

    def _update_slot(self, slot_tasks: list[Task], order) -> str | None:
        """Replace one slot's tasks with a fresh-spec task. Returns new id.

        Both orders are two-phase (update/updater.go:367-451):
          start-first: create + start the replacement, WAIT until it is
          observed RUNNING (replica count never dips below desired), then
          shut the old tasks down; if the replacement dies first, the old
          tasks are left running and the failure feeds the monitor.
          stop-first: shut the old tasks down, WAIT until they stopped,
          then create the replacement.
        """
        slot = slot_tasks[0].slot
        if order == UpdateOrder.START_FIRST:
            new_id = self._create_replacement(slot, TaskState.RUNNING)
            if new_id is None:
                return None
            outcome = self._wait_task_state(new_id, TaskState.RUNNING,
                                            timeout=self.START_FIRST_TIMEOUT)
            if outcome == "running":
                self._shutdown_tasks(slot_tasks)
            elif outcome == "timeout":
                # a replacement that never starts (unschedulable on a full
                # cluster) must not pile up: remove it, keep the old task,
                # report failure so the batch backs off and retries
                self._remove_task(new_id)
                return None
            return new_id
        # stop-first: the replacement is created (desired READY) in the
        # SAME transaction that brings the old tasks down, so the slot
        # never looks empty to the orchestrator's reconcile — else it
        # races in a duplicate replica (updater.go:385-409 does the
        # create + removeOldTasks in one batch for this exact reason).
        # The READY→RUNNING promote happens once the old tasks stopped.
        new_id = self._create_replacement(slot, TaskState.READY,
                                          shutdown=slot_tasks)
        if new_id is None:
            return None
        self._wait_tasks_stopped(slot_tasks)
        self._promote(new_id)
        return new_id

    def _create_replacement(self, slot: int, desired: TaskState,
                            shutdown: list[Task] = ()) -> str | None:
        new_task_id: list[str | None] = [None]

        def cb(tx):
            cur_service = tx.get_service(self.service_id)
            if cur_service is None:
                return
            replacement = new_task(None, cur_service, slot)
            replacement.desired_state = desired
            tx.create(replacement)
            for t in shutdown:
                cur = tx.get_task(t.id)
                if cur is not None and cur.desired_state < TaskState.SHUTDOWN:
                    cur = cur.copy()
                    cur.desired_state = TaskState.SHUTDOWN
                    tx.update(cur)
            new_task_id[0] = replacement.id

        self.store.update(cb)
        return new_task_id[0]

    def _shutdown_tasks(self, slot_tasks: list[Task]):
        def cb(tx):
            for t in slot_tasks:
                cur = tx.get_task(t.id)
                if cur is not None and cur.desired_state < TaskState.SHUTDOWN:
                    cur = cur.copy()
                    cur.desired_state = TaskState.SHUTDOWN
                    tx.update(cur)

        self.store.update(cb)

    def _remove_task(self, task_id: str):
        def cb(tx):
            cur = tx.get_task(task_id)
            if cur is not None and cur.desired_state < TaskState.REMOVE:
                cur = cur.copy()
                cur.desired_state = TaskState.REMOVE
                tx.update(cur)

        self.store.update(cb)

    def _promote(self, task_id: str):
        def cb(tx):
            cur = tx.get_task(task_id)
            if cur is not None and cur.desired_state == TaskState.READY:
                cur = cur.copy()
                cur.desired_state = TaskState.RUNNING
                tx.update(cur)

        self.store.update(cb)

    def _wait_task_state(self, task_id: str, want: TaskState,
                         timeout: float | None = SLOT_PHASE_TIMEOUT) -> str:
        """Poll until the task is observed at `want`, dies first, the
        updater is cancelled, or (when bounded) the phase times out.
        Returns 'running' | 'failed' | 'timeout'."""
        deadline = (time.monotonic() + timeout) if timeout is not None \
            else float("inf")
        while not self._cancel.is_set() and time.monotonic() < deadline:
            t = self.store.view().get_task(task_id)
            if t is None:
                return "failed"
            if t.status.state >= TaskState.FAILED:
                return "failed"
            if t.status.state >= want:
                return "running"
            if self._cancel.wait(0.05):
                break
        return "timeout"

    def _wait_tasks_stopped(self, slot_tasks: list[Task]):
        deadline = time.monotonic() + self.SLOT_PHASE_TIMEOUT
        ids = [t.id for t in slot_tasks]
        while not self._cancel.is_set() and time.monotonic() < deadline:
            view = self.store.view()
            live = [tid for tid in ids
                    if (t := view.get_task(tid)) is not None
                    and t.status.state <= TaskState.RUNNING]
            if not live:
                return
            if self._cancel.wait(0.05):
                return

    def _rollback(self, service):
        def cb(tx):
            cur = tx.get_service(self.service_id)
            if cur is None or cur.previous_spec is None:
                return
            cur = cur.copy()
            cur.spec, cur.previous_spec = cur.previous_spec, None
            cur.spec_version.index += 1
            cur.update_status = {
                "state": UpdateStatusState.ROLLBACK_STARTED.value,
                "message": "update rolled back due to failures",
            }
            tx.update(cur)

        self.store.update(cb)

    def _set_update_status(self, state: UpdateStatusState, message: str):
        def cb(tx):
            cur = tx.get_service(self.service_id)
            if cur is None:
                return
            cur = cur.copy()
            cur.update_status = {"state": state.value, "message": message,
                                 "timestamp": time.time()}
            tx.update(cur)

        try:
            self.store.update(cb)
        except Exception:
            pass


class UpdateSupervisor:
    """reference: update/updater.go Supervisor."""

    def __init__(self, store, restart):
        self.store = store
        self.restart = restart
        self._updaters: dict[str, Updater] = {}
        self._lock = threading.Lock()

    def update(self, service, dirty_slots):
        with self._lock:
            existing = self._updaters.get(service.id)
            if existing is not None and existing.is_alive():
                return  # an update is already converging on the live spec
            u = Updater(self.store, self.restart, service.id, self)
            self._updaters[service.id] = u
            u.start()

    def _done(self, service_id: str, updater):
        with self._lock:
            if self._updaters.get(service_id) is updater:
                del self._updaters[service_id]

    def stop(self):
        with self._lock:
            updaters = list(self._updaters.values())
        for u in updaters:
            u.cancel()
        for u in updaters:
            u.join(timeout=2)
