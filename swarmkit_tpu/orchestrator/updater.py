"""Rolling-update supervisor.

Behavioral re-derivation of manager/orchestrator/update/updater.go: dirty
slots are replaced `parallelism` at a time with `delay` between batches,
honoring stop-first vs start-first order; new-task failures within the
monitor window count toward max_failure_ratio, and crossing it triggers the
configured failure action (pause / continue / rollback —
updater.go:204-260, 566-626). One Updater thread runs per service; a newer
spec supersedes the running update (Supervisor.Update spec-diff gate,
updater.go:49-75).
"""
from __future__ import annotations

import threading
import time

from ..api.objects import EventUpdate, Task
from ..api.specs import deepcopy_spec
from ..api.types import (
    TaskState,
    UpdateFailureAction,
    UpdateOrder,
    UpdateStatusState,
)
from ..store import by
from .task import is_task_dirty, new_task


class Updater(threading.Thread):
    def __init__(self, store, restart, service_id: str, supervisor):
        super().__init__(daemon=True, name=f"updater-{service_id[:8]}")
        self.store = store
        self.restart = restart
        self.service_id = service_id
        self.supervisor = supervisor
        self._cancel = threading.Event()

    def cancel(self):
        self._cancel.set()

    def run(self):
        try:
            self._run()
        finally:
            self.supervisor._done(self.service_id, self)

    def _run(self):
        service = self.store.view().get_service(self.service_id)
        if service is None:
            return
        cfg = service.spec.update
        self._set_update_status(UpdateStatusState.UPDATING, "update in progress")

        # monitored: task_id -> monitor deadline; failures accrue
        # asynchronously so batches are NOT serialized behind the window
        # (the reference overlaps monitoring with subsequent batches)
        monitored: dict[str, float] = {}
        failed: set[str] = set()
        updated = 0

        def poll_failures():
            if not monitored:
                return
            view = self.store.view()
            now = time.monotonic()
            for tid in list(monitored):
                t = view.get_task(tid)
                if t is not None and t.status.state in (
                        TaskState.FAILED, TaskState.REJECTED):
                    failed.add(tid)
                    del monitored[tid]
                elif now > monitored[tid]:
                    del monitored[tid]  # window expired healthy

        def over_threshold() -> bool:
            total = max(updated, 1)
            return (cfg.max_failure_ratio >= 0 and failed
                    and len(failed) / total > cfg.max_failure_ratio)

        while not self._cancel.is_set():
            service = self.store.view().get_service(self.service_id)
            if service is None:
                return
            dirty = self._dirty_slots(service)
            if not dirty:
                break
            parallelism = cfg.parallelism or len(dirty)
            for slot_tasks in dirty[:parallelism]:
                nid = self._update_slot(service, slot_tasks, cfg.order)
                if nid and cfg.monitor > 0:
                    monitored[nid] = time.monotonic() + cfg.monitor
                updated += 1
            poll_failures()
            # CONTINUE keeps rolling despite failures; PAUSE/ROLLBACK stop
            if over_threshold() and \
                    cfg.failure_action != UpdateFailureAction.CONTINUE:
                break
            if cfg.delay > 0 and self._cancel.wait(cfg.delay):
                return

        # drain remaining monitor windows (non-blocking batches above mean
        # only the tail waits here), still reacting to failures promptly
        while monitored and not self._cancel.is_set() and not over_threshold():
            if self._cancel.wait(0.05):
                return
            poll_failures()

        if over_threshold():
            total = max(updated, 1)
            if cfg.failure_action == UpdateFailureAction.PAUSE:
                self._set_update_status(
                    UpdateStatusState.PAUSED,
                    f"update paused due to failure ratio {len(failed)}/{total}")
            elif cfg.failure_action == UpdateFailureAction.ROLLBACK:
                self._rollback(self.store.view().get_service(self.service_id))
            else:
                self._set_update_status(
                    UpdateStatusState.COMPLETED,
                    f"update completed with {len(failed)} failures")
            return
        if not self._cancel.is_set():
            self._set_update_status(UpdateStatusState.COMPLETED, "update completed")

    # ------------------------------------------------------------------ steps
    def _dirty_slots(self, service) -> list[list[Task]]:
        tasks = self.store.view().find_tasks(by.ByServiceID(self.service_id))
        from .task import slots_by_service, slot_runnable
        slots = slots_by_service(tasks).get(self.service_id, {})
        dirty = []
        for slot, ts in sorted(slots.items()):
            live = [t for t in ts if t.desired_state <= TaskState.RUNNING]
            if not live or not slot_runnable(live):
                continue
            if any(is_task_dirty(service, t) for t in live):
                dirty.append(live)
        return dirty

    def _update_slot(self, service, slot_tasks: list[Task], order) -> str | None:
        """Replace one slot's tasks with a fresh-spec task. Returns new id."""
        slot = slot_tasks[0].slot
        new_task_id: list[str | None] = [None]

        def cb(tx):
            cur_service = tx.get_service(self.service_id)
            if cur_service is None:
                return
            replacement = new_task(None, cur_service, slot)
            if order == UpdateOrder.START_FIRST:
                replacement.desired_state = TaskState.READY
                tx.create(replacement)
                # old tasks shut down once replacement starts; simplified:
                # shut down now but after creation (start-first semantics are
                # refined with the task-state watcher in a later layer)
            else:
                replacement.desired_state = TaskState.READY
            for t in slot_tasks:
                cur = tx.get_task(t.id)
                if cur is not None and cur.desired_state < TaskState.SHUTDOWN:
                    cur = cur.copy()
                    cur.desired_state = TaskState.SHUTDOWN
                    tx.update(cur)
            if order != UpdateOrder.START_FIRST:
                tx.create(replacement)
            new_task_id[0] = replacement.id

        self.store.update(cb)
        if new_task_id[0]:
            # promote READY→RUNNING immediately (no restart delay on update)
            def promote(tx):
                cur = tx.get_task(new_task_id[0])
                if cur is not None and cur.desired_state == TaskState.READY:
                    cur = cur.copy()
                    cur.desired_state = TaskState.RUNNING
                    tx.update(cur)

            self.store.update(promote)
        return new_task_id[0]

    def _rollback(self, service):
        def cb(tx):
            cur = tx.get_service(self.service_id)
            if cur is None or cur.previous_spec is None:
                return
            cur = cur.copy()
            cur.spec, cur.previous_spec = cur.previous_spec, None
            cur.spec_version.index += 1
            cur.update_status = {
                "state": UpdateStatusState.ROLLBACK_STARTED.value,
                "message": "update rolled back due to failures",
            }
            tx.update(cur)

        self.store.update(cb)

    def _set_update_status(self, state: UpdateStatusState, message: str):
        def cb(tx):
            cur = tx.get_service(self.service_id)
            if cur is None:
                return
            cur = cur.copy()
            cur.update_status = {"state": state.value, "message": message,
                                 "timestamp": time.time()}
            tx.update(cur)

        try:
            self.store.update(cb)
        except Exception:
            pass


class UpdateSupervisor:
    """reference: update/updater.go Supervisor."""

    def __init__(self, store, restart):
        self.store = store
        self.restart = restart
        self._updaters: dict[str, Updater] = {}
        self._lock = threading.Lock()

    def update(self, service, dirty_slots):
        with self._lock:
            existing = self._updaters.get(service.id)
            if existing is not None and existing.is_alive():
                return  # an update is already converging on the live spec
            u = Updater(self.store, self.restart, service.id, self)
            self._updaters[service.id] = u
            u.start()

    def _done(self, service_id: str, updater):
        with self._lock:
            if self._updaters.get(service_id) is updater:
                del self._updaters[service_id]

    def stop(self):
        with self._lock:
            updaters = list(self._updaters.values())
        for u in updaters:
            u.cancel()
        for u in updaters:
            u.join(timeout=2)
