"""Rolling-update supervisor.

Behavioral re-derivation of manager/orchestrator/update/updater.go: dirty
slots are replaced `parallelism` at a time with `delay` between batches,
honoring stop-first vs start-first order; new-task failures within the
monitor window count toward max_failure_ratio, and crossing it triggers the
configured failure action (pause / continue / rollback —
updater.go:204-260, 566-626). One Updater thread runs per service; a newer
spec supersedes the running update (Supervisor.Update spec-diff gate,
updater.go:49-75).
"""
from __future__ import annotations

import logging
import queue as queue_mod
import threading
import time

from ..analysis.lockgraph import make_lock
from ..api.objects import EventUpdate, Task
from ..api.specs import deepcopy_spec
from ..api.types import (
    TaskState,
    UpdateFailureAction,
    UpdateOrder,
    UpdateStatusState,
)
from ..store import by
from .task import is_task_dirty, mark_shutdown, new_task

log = logging.getLogger("swarmkit_tpu.orchestrator.updater")


# --------------------------------------------------------- shared protocol
# The slot-flip / verdict primitives shared by BOTH rolling-update
# implementations — the per-service threaded `Updater` (the scalar
# oracle) and the shared `UpdateWavePlanner` (orchestrator/batched.py).
# They are the pair's common vocabulary: the mirror registry
# (analysis/mirror.py, pair "orch-update") pins that both members keep
# riding these instead of growing private store-write paths.

def dirty_slots(store, service) -> list[list[Task]]:
    """Runnable slots whose live tasks drifted from the service spec —
    the unit of rolling-update work (updater.go slotsNeedingUpdate)."""
    from .task import slot_runnable, slots_by_service

    tasks = store.view().find_tasks(by.ByServiceID(service.id))
    slots = slots_by_service(tasks).get(service.id, {})
    dirty = []
    for slot, ts in sorted(slots.items()):
        live = [t for t in ts if t.desired_state <= TaskState.RUNNING]
        if not live or not slot_runnable(live):
            continue
        if any(is_task_dirty(service, t) for t in live):
            dirty.append(live)
    return dirty


def create_replacement(store, service_id: str, slot: int,
                       desired: TaskState,
                       shutdown: list[Task] = ()) -> str | None:
    """Create the fresh-spec replacement for one slot; with `shutdown`
    the old tasks come down in the SAME transaction (stop-first — the
    slot must never look empty to the orchestrator's reconcile,
    updater.go:385-409)."""
    new_task_id: list[str | None] = [None]

    def cb(tx):
        cur_service = tx.get_service(service_id)
        if cur_service is None:
            return
        replacement = new_task(None, cur_service, slot)
        replacement.desired_state = desired
        tx.create(replacement)
        for t in shutdown:
            cur = tx.get_task(t.id)
            if cur is not None and cur.desired_state < TaskState.SHUTDOWN:
                cur = cur.copy()
                mark_shutdown(cur)
                tx.update(cur)
        new_task_id[0] = replacement.id

    store.update(cb)
    return new_task_id[0]


def shutdown_tasks(store, slot_tasks: list[Task]) -> None:
    def cb(tx):
        for t in slot_tasks:
            cur = tx.get_task(t.id)
            if cur is not None and cur.desired_state < TaskState.SHUTDOWN:
                cur = cur.copy()
                mark_shutdown(cur)
                tx.update(cur)

    store.update(cb)


def remove_task(store, task_id: str) -> None:
    def cb(tx):
        cur = tx.get_task(task_id)
        if cur is not None and cur.desired_state < TaskState.REMOVE:
            cur = cur.copy()
            cur.desired_state = TaskState.REMOVE
            tx.update(cur)

    store.update(cb)


def promote_task(store, task_id: str) -> None:
    def cb(tx):
        cur = tx.get_task(task_id)
        if cur is not None and cur.desired_state == TaskState.READY:
            cur = cur.copy()
            cur.desired_state = TaskState.RUNNING
            tx.update(cur)

    store.update(cb)


def rollback_service(store, service_id: str) -> None:
    """Flip the spec back to previous_spec and mark ROLLBACK_STARTED
    (updater.go:566-626); the resulting service event re-drives a fresh
    update pass in rollback mode."""

    def cb(tx):
        cur = tx.get_service(service_id)
        if cur is None or cur.previous_spec is None:
            return
        cur = cur.copy()
        cur.spec, cur.previous_spec = cur.previous_spec, None
        cur.spec_version.index += 1
        cur.update_status = {
            "state": UpdateStatusState.ROLLBACK_STARTED.value,
            "message": "update rolled back due to failures",
            "timestamp": time.time(),
        }
        tx.update(cur)

    store.update(cb)


def set_update_status(store, service_id: str, state: UpdateStatusState,
                      message: str) -> None:
    def cb(tx):
        cur = tx.get_service(service_id)
        if cur is None:
            return
        cur = cur.copy()
        cur.update_status = {"state": state.value, "message": message,
                             "timestamp": time.time()}
        tx.update(cur)

    try:
        store.update(cb)
    except Exception:
        pass


def finalize_update(store, service_id: str, cfg, rolling_back: bool,
                    failed_out: bool, n_failed: int, total: int) -> None:
    """The shared terminal verdict: failure-policy dispatch when the
    ratio tripped (rollback / pause / continue-with-failures), else the
    completed status for the running kind (updater.go:204-260). A
    failing ROLLBACK cannot roll back again: it pauses."""
    kind = "rollback" if rolling_back else "update"
    paused_state = (UpdateStatusState.ROLLBACK_PAUSED if rolling_back
                    else UpdateStatusState.PAUSED)
    done_state = (UpdateStatusState.ROLLBACK_COMPLETED if rolling_back
                  else UpdateStatusState.COMPLETED)
    if failed_out:
        if cfg.failure_action == UpdateFailureAction.ROLLBACK \
                and not rolling_back:
            rollback_service(store, service_id)
        elif cfg.failure_action == UpdateFailureAction.ROLLBACK:
            set_update_status(
                store, service_id, paused_state,
                f"rollback paused due to failure ratio {n_failed}/{total}")
        elif cfg.failure_action == UpdateFailureAction.PAUSE:
            set_update_status(
                store, service_id, paused_state,
                f"{kind} paused due to failure ratio {n_failed}/{total}")
        else:
            set_update_status(
                store, service_id, done_state,
                f"{kind} completed with {n_failed} failures")
        return
    set_update_status(store, service_id, done_state, f"{kind} completed")


class Updater(threading.Thread):
    def __init__(self, store, restart, service_id: str, supervisor):
        super().__init__(daemon=True, name=f"updater-{service_id[:8]}")
        self.store = store
        self.restart = restart
        self.service_id = service_id
        self.supervisor = supervisor
        self._cancel = threading.Event()
        # failure-policy abort: in-flight slot waits unwind promptly, but
        # (unlike cancel) the final status still gets written
        self._abort = threading.Event()

    def cancel(self):
        self._cancel.set()

    def run(self):
        try:
            self._run()
        finally:
            self.supervisor._done(self.service_id, self)

    def _run(self):
        service = self.store.view().get_service(self.service_id)
        if service is None:
            return
        # a PAUSED update stays paused until the operator acts: the spec
        # update that resolves it clears update_status (controlapi), and
        # only then may a fresh updater run (updater.go Run:129-134)
        state = (service.update_status or {}).get("state")
        if state in (UpdateStatusState.PAUSED.value,
                     UpdateStatusState.ROLLBACK_PAUSED.value):
            return
        # a rollback in progress keeps the rollback status family and uses
        # the rollback config (updater.go Run:162-170)
        rolling_back = state == UpdateStatusState.ROLLBACK_STARTED.value
        if rolling_back:
            from ..api.defaults import default_update_config

            cfg = service.spec.rollback or default_update_config()
        else:
            cfg = service.spec.update
            self._set_update_status(UpdateStatusState.UPDATING,
                                    "update in progress")

        # Worker-pool shape (updater.go:190-260): `parallelism` workers
        # pull slots from a queue, each flipping independently — a slot
        # wedged in its per-slot deadline occupies ONE worker while the
        # others keep rolling; monitor windows overlap everything and
        # failures accrue asynchronously.
        lock = make_lock('orchestrator.updater.rollout')
        monitored: dict[str, float] = {}
        failed: set[str] = set()
        counters = {"updated": 0}
        in_flight: set[int] = set()          # slot numbers queued/flipping
        slot_q: queue_mod.Queue = queue_mod.Queue()
        no_more = threading.Event()

        def poll_failures():
            with lock:
                pending = list(monitored)
            if not pending:
                return
            view = self.store.view()
            now = time.monotonic()
            for tid in pending:
                t = view.get_task(tid)
                with lock:
                    if tid not in monitored:
                        continue
                    if t is not None and t.status.state in (
                            TaskState.FAILED, TaskState.REJECTED):
                        failed.add(tid)
                        del monitored[tid]
                    elif now > monitored[tid]:
                        del monitored[tid]  # window expired healthy

        def over_threshold() -> bool:
            with lock:
                total = max(counters["updated"], 1)
                return (cfg.max_failure_ratio >= 0 and failed
                        and len(failed) / total > cfg.max_failure_ratio)

        def pacing_wait(seconds: float) -> bool:
            """Sleep that also wakes on abort / pool drain. True = bail."""
            deadline = time.monotonic() + seconds
            while True:
                if self._abort.is_set() or no_more.is_set():
                    return False  # no point pacing a finished update
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                if self._cancel.wait(min(0.1, remaining)):
                    return True

        def worker():
            while not (self._cancel.is_set() or self._abort.is_set()):
                try:
                    slot_tasks = slot_q.get(timeout=0.1)
                except queue_mod.Empty:
                    if no_more.is_set():
                        return
                    continue
                outcome, nid = "error", None
                try:
                    outcome, nid = self._update_slot(slot_tasks, cfg.order)
                except Exception:
                    log.exception("updater %s: slot flip failed",
                                  self.service_id[:8])
                with lock:
                    in_flight.discard(slot_tasks[0].slot)
                    if outcome == "ok" and nid is not None:
                        counters["updated"] += 1
                        if cfg.monitor > 0:
                            monitored[nid] = time.monotonic() + cfg.monitor
                    elif outcome == "failed":
                        # per-slot deadline expired: the wedged replacement
                        # was removed; it counts toward the failure ratio
                        # instead of stalling the update (round-2 verdict #7)
                        counters["updated"] += 1
                        failed.add(nid or f"slot-{slot_tasks[0].slot}")
                if outcome == "error":
                    # store unavailable during churn: the slot stays dirty
                    # and re-queues; back off instead of hot-spinning
                    if pacing_wait(1.0):
                        return
                if cfg.delay > 0 and pacing_wait(cfg.delay):
                    return

        workers: list[threading.Thread] = []

        def ensure_workers(want: int):
            while len(workers) < want:
                w = threading.Thread(target=worker, daemon=True,
                                     name=f"{self.name}-w{len(workers)}")
                w.start()
                workers.append(w)

        aborted = False
        try:
            while not self._cancel.is_set():
                poll_failures()
                # CONTINUE keeps rolling despite failures; PAUSE/ROLLBACK
                # stop — checked BEFORE queueing retries, or a failed slot
                # would start one more doomed flip on its way out
                if over_threshold() and \
                        cfg.failure_action != UpdateFailureAction.CONTINUE:
                    aborted = True
                    self._abort.set()  # unwind in-flight waits promptly
                    break
                service = self.store.view().get_service(self.service_id)
                if service is None:
                    self._abort.set()  # flips are moot: unwind and drain
                    return
                with lock:
                    busy = set(in_flight)
                fresh = [st for st in self._dirty_slots(service)
                         if st[0].slot not in busy]
                with lock:
                    for st in fresh:
                        in_flight.add(st[0].slot)
                    backlog = len(in_flight)
                for st in fresh:
                    slot_q.put(st)
                if backlog:
                    # pool sized by the whole backlog, not just this
                    # iteration's arrivals: slots dirtied one at a time
                    # must not queue behind a wedged worker while the
                    # parallelism budget has headroom
                    ensure_workers(min(cfg.parallelism or backlog, backlog))
                with lock:
                    idle = not in_flight
                if idle and not fresh:
                    break
                if self._cancel.wait(0.1):
                    return
        finally:
            no_more.set()
        for w in workers:
            w.join(timeout=5)

        # drain remaining monitor windows (the pool overlapped them with
        # the flips; only the tail waits here), reacting to failures
        while not self._cancel.is_set() and not over_threshold():
            with lock:
                if not monitored:
                    break
            if self._cancel.wait(0.05):
                return
            poll_failures()

        if over_threshold() or aborted:
            with lock:
                total = max(counters["updated"], 1)
                n_failed = len(failed)
            finalize_update(self.store, self.service_id, cfg, rolling_back,
                            True, n_failed, total)
            return
        if not self._cancel.is_set():
            finalize_update(self.store, self.service_id, cfg, rolling_back,
                            False, 0, 1)

    # ------------------------------------------------------------------ steps
    def _dirty_slots(self, service) -> list[list[Task]]:
        return dirty_slots(self.store, service)

    # bound for the stop-first old-task drain
    SLOT_PHASE_TIMEOUT = 30.0
    # bound for the start-first replacement start: generous (slow prepares
    # are legitimate), and on expiry the stuck replacement is REMOVED so
    # the retry can't accumulate duplicates in the slot
    START_FIRST_TIMEOUT = 600.0

    def _update_slot(self, slot_tasks: list[Task],
                     order) -> tuple[str, str | None]:
        """Replace one slot's tasks with a fresh-spec task. Returns
        (outcome, new_task_id): 'ok' (flip landed — the monitor window
        judges it from here), 'failed' (the per-slot deadline expired and
        the wedged replacement was removed; counts toward the failure
        ratio), or 'error' (store hiccup / abort; the slot stays dirty
        and re-queues).

        Both orders are two-phase (update/updater.go:367-451):
          start-first: create + start the replacement, WAIT until it is
          observed RUNNING (replica count never dips below desired), then
          shut the old tasks down; if the replacement dies first, the old
          tasks are left running and the failure feeds the monitor.
          stop-first: shut the old tasks down, WAIT until they stopped,
          then create the replacement.
        """
        slot = slot_tasks[0].slot
        if order == UpdateOrder.START_FIRST:
            new_id = self._create_replacement(slot, TaskState.RUNNING)
            if new_id is None:
                return "error", None
            outcome = self._wait_task_state(new_id, TaskState.RUNNING,
                                            timeout=self.START_FIRST_TIMEOUT)
            if outcome == "running":
                self._shutdown_tasks(slot_tasks)
            elif outcome == "aborted":
                # the update is over (policy abort / supersession): don't
                # leave an unstarted replacement behind in the slot
                self._remove_task(new_id)
                return "error", None
            elif outcome == "timeout":
                # a replacement that never starts (unschedulable on a full
                # cluster) must not pile up: remove it, keep the old task,
                # count the failure so the policy can act
                self._remove_task(new_id)
                return "failed", new_id
            # 'failed' (died before RUNNING) flows through the monitor
            # window like any young-task death
            return "ok", new_id
        # stop-first: the replacement is created (desired READY) in the
        # SAME transaction that brings the old tasks down, so the slot
        # never looks empty to the orchestrator's reconcile — else it
        # races in a duplicate replica (updater.go:385-409 does the
        # create + removeOldTasks in one batch for this exact reason).
        # The READY→RUNNING promote happens once the old tasks stopped.
        new_id = self._create_replacement(slot, TaskState.READY,
                                          shutdown=slot_tasks)
        if new_id is None:
            return "error", None
        self._wait_tasks_stopped(slot_tasks)
        self._promote(new_id)
        return "ok", new_id

    def _create_replacement(self, slot: int, desired: TaskState,
                            shutdown: list[Task] = ()) -> str | None:
        return create_replacement(self.store, self.service_id, slot,
                                  desired, shutdown)

    def _shutdown_tasks(self, slot_tasks: list[Task]):
        shutdown_tasks(self.store, slot_tasks)

    def _remove_task(self, task_id: str):
        remove_task(self.store, task_id)

    def _promote(self, task_id: str):
        promote_task(self.store, task_id)

    def _wait_task_state(self, task_id: str, want: TaskState,
                         timeout: float | None = SLOT_PHASE_TIMEOUT) -> str:
        """Poll until the task is observed at `want`, dies first, the
        updater is cancelled/aborted, or (when bounded) the phase times
        out. Returns 'running' | 'failed' | 'timeout' | 'aborted'."""
        deadline = (time.monotonic() + timeout) if timeout is not None \
            else float("inf")
        while not self._cancel.is_set() and time.monotonic() < deadline:
            if self._abort.is_set():
                return "aborted"
            t = self.store.view().get_task(task_id)
            if t is None:
                return "failed"
            if t.status.state >= TaskState.FAILED:
                return "failed"
            if t.status.state >= want:
                return "running"
            if self._cancel.wait(0.05):
                break
        return "aborted" if (self._cancel.is_set() or self._abort.is_set()) \
            else "timeout"

    def _wait_tasks_stopped(self, slot_tasks: list[Task]):
        deadline = time.monotonic() + self.SLOT_PHASE_TIMEOUT
        ids = [t.id for t in slot_tasks]
        while not self._cancel.is_set() and not self._abort.is_set() \
                and time.monotonic() < deadline:
            view = self.store.view()
            live = [tid for tid in ids
                    if (t := view.get_task(tid)) is not None
                    and t.status.state <= TaskState.RUNNING]
            if not live:
                return
            if self._cancel.wait(0.05):
                return

    def _rollback(self, service):
        rollback_service(self.store, self.service_id)

    def _set_update_status(self, state: UpdateStatusState, message: str):
        set_update_status(self.store, self.service_id, state, message)


class UpdateSupervisor:
    """reference: update/updater.go Supervisor.

    With the batched orchestration plane enabled (the default; ISSUE 14,
    SWARMKIT_TPU_NO_BATCHED_ORCH=1 reverts) updates run on the SHARED
    `UpdateWavePlanner` — one thread schedules every service's
    replacement waves instead of one thread per updating service. The
    per-service threaded Updater above stays as the scalar oracle."""

    def __init__(self, store, restart, clock=None):
        self.store = store
        self.restart = restart
        self._updaters: dict[str, Updater] = {}
        self._lock = make_lock('orchestrator.updater.supervisor')
        from .batched import UpdateWavePlanner, plane_enabled

        self.planner = (UpdateWavePlanner(store, restart, clock=clock)
                        if plane_enabled(store) else None)

    def update(self, service, dirty_slots):
        if self.planner is not None:
            self.planner.update(service, dirty_slots)
            return
        with self._lock:
            existing = self._updaters.get(service.id)
            if existing is not None and existing.is_alive():
                return  # an update is already converging on the live spec
            u = Updater(self.store, self.restart, service.id, self)
            self._updaters[service.id] = u
            u.start()

    def _done(self, service_id: str, updater):
        with self._lock:
            if self._updaters.get(service_id) is updater:
                del self._updaters[service_id]

    def stop(self):
        if self.planner is not None:
            self.planner.stop()
        with self._lock:
            updaters = list(self._updaters.values())
        for u in updaters:
            u.cancel()
        for u in updaters:
            u.join(timeout=2)
