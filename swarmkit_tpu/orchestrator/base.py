"""Shared event-loop scaffolding for manager control components.

Every L3 component in the reference is a `Run(ctx)` goroutine over store
watches started on leadership (manager/manager.go:1093-1146). Here each is a
thread: snapshot-then-watch, dispatch events, periodic idle callback.
"""
from __future__ import annotations

import logging
import threading

from ..store.memory import MemoryStore
from ..store.watch import ChannelClosed
from ..utils.leadership import leadership_lost

log = logging.getLogger("swarmkit_tpu.orchestrator")


class EventLoopComponent:
    name = "component"
    # burst drain bound: after a blocking get, up to this many queued
    # events are consumed without sleeping before flush_events() runs —
    # batching components (the batched replicated orchestrator) coalesce
    # a mass-update storm into ONE vectorized pass per burst instead of
    # one store transaction per event
    MAX_DRAIN = 256

    def __init__(self, store: MemoryStore):
        self.store = store
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=self.name)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)

    # -- subclass hooks ------------------------------------------------------
    def setup(self, tx):
        """Runs under the snapshot view; return value is passed to on_start."""

    def on_start(self, snapshot):
        """Initial reconcile after snapshot, before consuming events."""

    def handle(self, event):
        raise NotImplementedError

    def flush_events(self):
        """Called after each drained event burst (and before going back
        to blocking on the channel). Components that coalesce work
        across events (batched reconcile passes) apply it here."""

    def idle(self):
        """Called when no events arrived within the poll interval."""

    # -- loop ----------------------------------------------------------------
    def _run(self):
        snapshot, ch = self.store.view_and_watch(self.setup, limit=None)
        try:
            try:
                self.on_start(snapshot)
            except Exception as exc:
                if leadership_lost(exc):
                    # demoted before the initial reconcile committed: stop
                    # cleanly, the manager's leadership handler stop()s us
                    log.info("%s: leadership lost; stopping", self.name)
                    return
                # initial reconcile may fail transiently; the event loop
                # must still come up — events re-drive the state
                log.exception("%s: initial reconcile failed", self.name)
            while not self._stop.is_set():
                try:
                    ev = ch.get(timeout=0.2)
                except TimeoutError:
                    try:
                        self.idle()
                    except Exception as exc:
                        if leadership_lost(exc):
                            log.info("%s: leadership lost; stopping",
                                     self.name)
                            return
                        log.exception("%s: idle pass failed", self.name)
                    continue
                except ChannelClosed:
                    return
                closed = False
                drained = 1
                while True:
                    try:
                        self.handle(ev)
                    except Exception as exc:
                        if leadership_lost(exc):
                            log.info("%s: leadership lost; stopping",
                                     self.name)
                            return
                        log.exception("%s: error handling %r",
                                      self.name, ev)
                    # drain the burst without sleeping so flush_events
                    # sees the whole storm at once; never pop an event
                    # this burst won't handle (budget checked BEFORE
                    # the pop, or the 257th event would be dropped)
                    if drained >= self.MAX_DRAIN:
                        break
                    try:
                        ev = ch.try_get()
                    except ChannelClosed:
                        closed, ev = True, None
                    if ev is None:
                        break
                    drained += 1
                try:
                    self.flush_events()
                except Exception as exc:
                    if leadership_lost(exc):
                        log.info("%s: leadership lost; stopping", self.name)
                        return
                    log.exception("%s: flush pass failed", self.name)
                if closed:
                    return
        finally:
            self.store.queue.stop_watch(ch)
