"""Shared orchestrator task helpers.

Behavioral re-derivation of manager/orchestrator/{task.go, slot.go,
service.go}: the NewTask factory, spec-dirtiness check driving rolling
updates, slot grouping, and runnability predicates.
"""
from __future__ import annotations

import time
from collections import defaultdict

from ..api.objects import Service, Task, Version
from ..api.specs import deepcopy_spec, spec_equal
from ..api.types import ServiceMode, TaskState
from ..utils import lifecycle
from ..utils.identity import new_id


def new_task(cluster, service: Service, slot: int, node_id: str = "") -> Task:
    """reference: manager/orchestrator/task.go NewTask."""
    t = Task(id=new_id())
    t.service_id = service.id
    t.slot = slot
    t.node_id = node_id
    t.spec = deepcopy_spec(service.spec.task)
    t.service_annotations = deepcopy_spec(service.spec.annotations)
    t.annotations = deepcopy_spec(service.spec.annotations)
    t.status.state = TaskState.NEW
    t.status.timestamp = time.time()
    t.status.message = "created"
    t.desired_state = (TaskState.COMPLETE if is_job(service)
                       else TaskState.RUNNING)
    t.spec_version = Version(service.spec_version.index)
    if is_job(service) and service.job_status is not None:
        t.job_iteration = Version(service.job_status.get("iteration", 0))
    # lifecycle plane: the NEW record, stamped with the same timestamp
    # the status carries (one truthiness test disarmed). The factory is
    # the one decision boundary every orchestrator's task creation
    # crosses; a creation whose enclosing tx aborts leaves a timeline
    # that simply never advances (observability, not bookkeeping).
    lifecycle.record(t.id, TaskState.NEW, t=t.status.timestamp)
    return t


def is_job(service: Service) -> bool:
    return service.spec.mode in (ServiceMode.REPLICATED_JOB, ServiceMode.GLOBAL_JOB)


def is_replicated(service: Service) -> bool:
    return service.spec.mode == ServiceMode.REPLICATED


def is_global(service: Service) -> bool:
    return service.spec.mode == ServiceMode.GLOBAL


def is_task_dirty(service: Service, task: Task) -> bool:
    """Spec drift that requires replacing the task
    (reference: manager/orchestrator/task.go IsTaskDirty)."""
    if task.spec_version is not None and service.spec_version is not None \
            and task.spec_version.index == service.spec_version.index:
        return False
    return not spec_equal(service.spec.task, task.spec)


def task_runnable(task: Task) -> bool:
    """Desired up and not observed dead."""
    return (task.desired_state <= TaskState.RUNNING
            and task.status.state <= TaskState.RUNNING)


def task_dead(task: Task) -> bool:
    return task.status.state > TaskState.RUNNING


def slots_by_service(tasks: list[Task]) -> dict[str, dict[int, list[Task]]]:
    """Service -> slot -> tasks (a slot may hold >1 task mid-update),
    mirroring the reference's Slot abstraction (slot.go)."""
    out: dict[str, dict[int, list[Task]]] = defaultdict(lambda: defaultdict(list))
    for t in tasks:
        out[t.service_id][t.slot].append(t)
    return out


def slot_runnable(slot_tasks: list[Task]) -> bool:
    return any(task_runnable(t) for t in slot_tasks)


def mark_shutdown(cur: Task) -> None:
    """Raise desired_state to SHUTDOWN on a (copied) task, finalizing the
    OBSERVED state too when no agent can: a task that was never dispatched
    to a node (status < ASSIGNED) has nothing running anywhere and nobody
    who would ever report it stopped — leaving its status PENDING wedges
    every 'wait until the old tasks stopped' loop for its full timeout
    (the reference's orchestrators write terminal status directly for
    unassigned tasks, updater.go removeOldTasks / restart.go)."""
    import time as _time

    cur.desired_state = TaskState.SHUTDOWN
    if cur.status.state < TaskState.ASSIGNED:
        cur.status.state = TaskState.SHUTDOWN
        cur.status.message = "shut down before assignment"
        cur.status.timestamp = _time.time()
        # the orchestrator is the status writer of record here (no agent
        # will ever report this task): close its timeline too
        lifecycle.record(cur.id, TaskState.SHUTDOWN,
                         t=cur.status.timestamp)
