"""Jobs orchestrator: replicated-job and global-job services.

Behavioral re-derivation of manager/orchestrator/jobs/{orchestrator.go,
replicated/reconciler.go, global/reconciler.go}: one-shot task execution
tracked per JobIteration. Job tasks are created with
desired_state=COMPLETE and are never restarted after reaching COMPLETE
(failure restarts still flow through the restart supervisor per policy).

Replicated jobs run `total_completions` tasks overall with at most
`max_concurrent` in flight; global jobs run one task per eligible node
per iteration.
"""
from __future__ import annotations

from ..api.objects import (
    EventCreate,
    EventDelete,
    EventUpdate,
    Node,
    Service,
    Task,
)
from ..api.types import ServiceMode, TaskState
from ..store import by
from .base import EventLoopComponent
from .global_ import _node_eligible
from .restart import RestartSupervisor
from .task import is_job, new_task


def job_iteration(service: Service) -> int:
    """Current iteration from the service's JobStatus (0 before first run)."""
    if isinstance(service.job_status, dict):
        return int(service.job_status.get("iteration", 0))
    return 0


def _task_in_iteration(task: Task, iteration: int) -> bool:
    it = task.job_iteration.index if task.job_iteration is not None else 0
    return it == iteration


class JobsOrchestrator(EventLoopComponent):
    """reference: manager/orchestrator/jobs/orchestrator.go."""

    name = "jobs-orchestrator"

    def __init__(self, store):
        super().__init__(store)
        self.restart = RestartSupervisor(store)

    def stop(self):
        self.restart.stop()
        super().stop()

    def setup(self, tx):
        return [s for s in tx.find_services() if is_job(s)]

    def on_start(self, services):
        for s in services:
            self.reconcile_service(s.id)

    def handle(self, event):
        obj = getattr(event, "obj", None)
        if isinstance(obj, Service):
            if isinstance(event, EventDelete):
                self._delete_service_tasks(obj)
            elif is_job(obj):
                self.reconcile_service(obj.id)
        elif isinstance(obj, Node) and not isinstance(event, EventDelete):
            self._reconcile_node(obj.id)
        elif isinstance(obj, Task) and isinstance(event, EventUpdate):
            self._handle_task_change(obj)

    # ------------------------------------------------------------- reconcile
    def reconcile_service(self, service_id: str):
        def cb(tx):
            service = tx.get_service(service_id)
            if service is None or not is_job(service):
                return
            if service.spec.mode == ServiceMode.REPLICATED_JOB:
                self._reconcile_replicated_job(tx, service)
            else:
                self._reconcile_global_job(tx, service)

        self.store.update(cb)

    def _reconcile_replicated_job(self, tx, service: Service):
        """reference: jobs/replicated/reconciler.go ReconcileService."""
        iteration = job_iteration(service)
        total = max(1, service.spec.job.total_completions)
        max_concurrent = service.spec.job.max_concurrent or total

        tasks = [t for t in tx.find_tasks(by.ByServiceID(service.id))
                 if _task_in_iteration(t, iteration)]
        completed = sum(1 for t in tasks
                        if t.status.state == TaskState.COMPLETE)
        # in-flight: desired COMPLETE, not yet terminally observed, not
        # shut down by an update
        # in flight includes restart replacements held at desired READY
        active_slots: set[int] = set()
        for t in tasks:
            if (t.desired_state <= TaskState.COMPLETE
                    and t.status.state < TaskState.COMPLETE):
                active_slots.add(t.slot)
        active = len(active_slots)

        to_create = min(max_concurrent - active, total - completed - active)
        if to_create <= 0:
            return
        used = {t.slot for t in tasks
                if t.status.state == TaskState.COMPLETE} | active_slots
        slot_num = 1
        created = 0
        while created < to_create:
            if slot_num not in used:
                t = new_task(None, service, slot_num)
                tx.create(t)
                used.add(slot_num)
                created += 1
            slot_num += 1

    def _reconcile_global_job(self, tx, service: Service):
        """reference: jobs/global/reconciler.go ReconcileService."""
        iteration = job_iteration(service)
        tasks = [t for t in tx.find_tasks(by.ByServiceID(service.id))
                 if _task_in_iteration(t, iteration)]
        by_node: dict[str, list[Task]] = {}
        for t in tasks:
            by_node.setdefault(t.node_id, []).append(t)
        for node in tx.find_nodes():
            if not _node_eligible(node, service):
                continue
            existing = by_node.get(node.id, [])
            # a node is satisfied if any task for this iteration completed
            # or is still in flight
            if any(t.status.state == TaskState.COMPLETE
                   or (t.desired_state <= TaskState.COMPLETE
                       and t.status.state < TaskState.COMPLETE)
                   for t in existing):
                continue
            t = new_task(None, service, 0, node_id=node.id)
            tx.create(t)

    def _reconcile_node(self, node_id: str):
        """A node appearing/recovering may need global-job tasks."""
        def cb(tx):
            node = tx.get_node(node_id)
            if node is None:
                return
            for service in tx.find_services():
                if service.spec.mode == ServiceMode.GLOBAL_JOB:
                    self._reconcile_global_job(tx, service)

        self.store.update(cb)

    # ----------------------------------------------------------- task events
    def _handle_task_change(self, task: Task):
        """Failed job task → restart per policy; completed tasks may
        unblock the next wave of a replicated job."""
        if task.status.state == TaskState.COMPLETE:
            self.reconcile_service(task.service_id)
            return
        if task.status.state <= TaskState.RUNNING:
            return
        if task.desired_state > TaskState.COMPLETE:
            return  # shutdown/remove requested

        def cb(tx):
            service = tx.get_service(task.service_id)
            if service is None or not is_job(service):
                return
            self.restart.restart(tx, None, service, task)

        self.store.update(cb)

    def _delete_service_tasks(self, service: Service):
        def cb(batch):
            tasks = self.store.view().find_tasks(by.ByServiceID(service.id))
            for t in tasks:
                def delete_one(tx, t=t):
                    if tx.get_task(t.id) is not None:
                        tx.delete(Task, t.id)
                batch.update(delete_one)

        self.store.batch(cb)
