"""Startup task fix-up.

Behavioral re-derivation of manager/orchestrator/taskinit/init.go
CheckTasks: when an orchestrator (re)starts — e.g. after a leadership
change — tasks may be stranded mid-lifecycle: dead but never restarted, or
in flight on a node that went down while no leader was watching. This pass
runs once over a snapshot and routes each such task through the restart
supervisor so the normal reconcile loops take over from a clean state.
"""
from __future__ import annotations

from collections.abc import Callable

from ..api.objects import Service, Task
from ..api.types import NodeAvailability, NodeStatusState, TaskState
from ..store import by
from ..store.memory import MemoryStore
from .restart import RestartSupervisor


def check_tasks(store: MemoryStore, restart: RestartSupervisor,
                is_related: Callable[[Service], bool]) -> int:
    """Fix up stranded tasks for services matching `is_related`.
    Returns the number of tasks routed to restart."""
    fixed = 0

    def cb(tx):
        nonlocal fixed
        node_down = {}
        for n in tx.find_nodes():
            node_down[n.id] = (
                n.status.state == NodeStatusState.DOWN
                or n.spec.availability == NodeAvailability.DRAIN)
        for t in tx.find_tasks():
            if t.desired_state > TaskState.RUNNING:
                continue
            service = tx.get_service(t.service_id)
            if service is None or not is_related(service):
                continue
            dead = t.status.state > TaskState.RUNNING
            stranded = (
                t.node_id != ""
                and t.status.state >= TaskState.ASSIGNED
                and node_down.get(t.node_id, True))
            if dead or stranded:
                # node-down wins over delay-limbo: re-arming a promote
                # timer for a task on a dead node would strand it forever
                restart.restart(tx, None, service, t)
                fixed += 1
            elif t.desired_state == TaskState.READY \
                    and t.status.state <= TaskState.READY:
                # restart-delay limbo: the promote timer lived on the
                # previous leader and died with it — re-arm the delayed
                # start (taskinit/init.go:174 restartSupervisor.DelayStart)
                restart.resume_delay(t, service)
                fixed += 1

    store.update(cb)
    return fixed
