"""Constraint and volume enforcers.

Behavioral re-derivation of
manager/orchestrator/constraintenforcer/constraint_enforcer.go and
manager/orchestrator/volumeenforcer/volume_enforcer.go: when a node stops
satisfying a task's placement constraints (label change, role change) or no
longer has the resources, running tasks are evicted by raising their
observed state to REJECTED — the restart machinery then reschedules them
elsewhere. The volume enforcer does the same for tasks using a volume whose
availability drops to "drain".
"""
from __future__ import annotations

from ..api.objects import EventCreate, EventUpdate, Node, Task, Volume
from ..api.types import NodeAvailability, TaskState
from ..scheduler import constraint as constraint_mod
from ..store import by
from .base import EventLoopComponent

REJECT_MESSAGE = "assigned node no longer meets constraints"
VOLUME_REJECT_MESSAGE = "volume is being drained"


class ConstraintEnforcer(EventLoopComponent):
    """reference: constraint_enforcer.go:65-233 rejectNoncompliantTasks."""

    name = "constraint-enforcer"

    def setup(self, tx):
        return None

    def handle(self, event):
        obj = getattr(event, "obj", None)
        if isinstance(obj, Node) and isinstance(event, (EventCreate, EventUpdate)):
            self.reject_noncompliant_tasks(obj.id)

    def reject_noncompliant_tasks(self, node_id: str):
        def cb(tx):
            node = tx.get_node(node_id)
            if node is None:
                return
            tasks = tx.find_tasks(by.ByNodeID(node_id))
            # resource re-check needs running totals over surviving tasks
            available_cpu = available_mem = None
            if node.description is not None:
                available_cpu = node.description.resources.nano_cpus
                available_mem = node.description.resources.memory_bytes
            live = [t for t in tasks
                    if TaskState.ASSIGNED <= t.status.state <= TaskState.RUNNING
                    and t.desired_state <= TaskState.RUNNING]
            for t in live:
                if available_cpu is not None:
                    available_cpu -= t.spec.resources.reservations.nano_cpus
                    available_mem -= t.spec.resources.reservations.memory_bytes

            for t in live:
                violated = False
                exprs = t.spec.placement.constraints
                if exprs:
                    try:
                        constraints = constraint_mod.parse(exprs)
                        if not constraint_mod.node_matches(constraints, node):
                            violated = True
                    except constraint_mod.InvalidConstraint:
                        pass
                # resource overcommit after a shrink (reference :150-199)
                if not violated and available_cpu is not None and (
                        available_cpu < 0 or available_mem < 0):
                    violated = True
                    # evicting this task frees its reservation
                    available_cpu += t.spec.resources.reservations.nano_cpus
                    available_mem += t.spec.resources.reservations.memory_bytes
                if violated:
                    cur = tx.get_task(t.id)
                    if cur is None:
                        continue
                    cur = cur.copy()
                    cur.status.state = TaskState.REJECTED
                    cur.status.message = REJECT_MESSAGE
                    tx.update(cur)

        self.store.update(cb)


class VolumeEnforcer(EventLoopComponent):
    """reference: volume_enforcer.go rejectNoncompliantTasks."""

    name = "volume-enforcer"

    def setup(self, tx):
        return None

    def handle(self, event):
        obj = getattr(event, "obj", None)
        if isinstance(obj, Volume) and isinstance(event, EventUpdate):
            if obj.spec.availability == "drain":
                self.reject_tasks_using(obj.id)

    def reject_tasks_using(self, volume_id: str):
        def cb(tx):
            for t in tx.find_tasks():
                if volume_id not in t.volumes:
                    continue
                if t.status.state > TaskState.RUNNING:
                    continue
                cur = tx.get_task(t.id).copy()
                cur.status.state = TaskState.REJECTED
                cur.status.message = VOLUME_REJECT_MESSAGE
                tx.update(cur)

        self.store.update(cb)
