"""Runnable CSI plugin: directory-backed volumes over the plugin wire.

The analogue of running an external CSI driver binary next to swarmd:
this process serves the controller + node method sets on a unix socket
(swarmkit_tpu.csi.wire protocol) and materializes volumes as directories
under --data-dir, with node-publish creating a per-target symlink — real
enough that an agent's workload sees a filesystem path appear and
disappear with the volume lifecycle.

    python -m swarmkit_tpu.cmd.csi_plugin_example \
        --socket /run/myplugin.sock --data-dir /var/lib/myplugin \
        [--name dir-csi] [--no-stage]

Prints `CSI_PLUGIN_READY socket=…` once serving. swarmd attaches with
`--csi-plugin /run/myplugin.sock`.
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import threading

from ..analysis.lockgraph import make_lock
from ..csi.plugin import CSIPlugin, CSIPluginError, VolumeInfo
from ..csi.wire import CSIPluginServer, PluginCapabilities


class DirectoryPlugin(CSIPlugin):
    """Volumes are directories; publishes are symlinks (a minimal but
    REAL storage backend — state survives plugin restarts)."""

    def __init__(self, name: str, data_dir: str):
        self.name = name
        self.data_dir = data_dir
        os.makedirs(os.path.join(data_dir, "volumes"), exist_ok=True)
        os.makedirs(os.path.join(data_dir, "published"), exist_ok=True)
        self._lock = make_lock('cmd.csi_plugin_example.lock')

    def _vol_path(self, volume_id: str) -> str:
        return os.path.join(self.data_dir, "volumes", volume_id)

    # ------------------------------------------------------ controller side
    def create_volume(self, volume) -> VolumeInfo:
        vol_id = f"{self.name}-{volume.id}"
        path = self._vol_path(vol_id)
        with self._lock:
            os.makedirs(path, exist_ok=True)
            with open(os.path.join(path, "meta.json"), "w") as f:
                json.dump({"swarm_volume": volume.id,
                           "name": volume.spec.annotations.name}, f)
        return VolumeInfo(volume_id=vol_id, capacity_bytes=1 << 30,
                          volume_context={"path": path})

    def delete_volume(self, volume) -> None:
        info = volume.volume_info
        vol_id = info.volume_id if info else f"{self.name}-{volume.id}"
        path = self._vol_path(vol_id)
        with self._lock:
            if os.path.isdir(path):
                import shutil

                shutil.rmtree(path)

    def controller_publish(self, volume, node_id: str) -> dict[str, str]:
        info = volume.volume_info
        vol_id = info.volume_id if info else ""
        if not vol_id or not os.path.isdir(self._vol_path(vol_id)):
            raise CSIPluginError(f"unknown volume {vol_id!r}")
        return {"path": self._vol_path(vol_id), "node": node_id}

    def controller_unpublish(self, volume, node_id: str) -> None:
        pass  # nothing node-specific to tear down controller-side

    # ------------------------------------------------------------ node side
    def _target(self, volume_assignment) -> str:
        return os.path.join(self.data_dir, "published",
                            volume_assignment.id)

    def node_stage(self, volume_assignment) -> None:
        if not os.path.isdir(self._vol_path(volume_assignment.volume_id)):
            raise CSIPluginError(
                f"volume {volume_assignment.volume_id!r} does not exist")

    def node_unstage(self, volume_assignment) -> None:
        pass

    def node_publish(self, volume_assignment) -> None:
        src = self._vol_path(volume_assignment.volume_id)
        if not os.path.isdir(src):
            raise CSIPluginError(
                f"volume {volume_assignment.volume_id!r} does not exist")
        target = self._target(volume_assignment)
        with self._lock:
            if os.path.islink(target) and os.readlink(target) == src \
                    and os.path.exists(target):
                return  # already correctly published: leave it untouched
            # re-point ATOMICALLY (tmp symlink + rename): a stale link
            # from a previous volume generation must not survive, but a
            # concurrent reader must never observe a missing target
            tmp = target + ".tmp"
            if os.path.lexists(tmp):
                os.unlink(tmp)
            os.symlink(src, tmp)
            os.replace(tmp, target)

    def node_unpublish(self, volume_assignment) -> None:
        target = self._target(volume_assignment)
        with self._lock:
            if os.path.islink(target):
                os.unlink(target)


def main(argv=None):
    ap = argparse.ArgumentParser(prog="csi-plugin-example")
    ap.add_argument("--socket", required=True)
    ap.add_argument("--data-dir", required=True)
    ap.add_argument("--name", default="dir-csi")
    ap.add_argument("--no-stage", action="store_true",
                    help="drop the STAGE_UNSTAGE capability (clients must "
                         "skip the stage round trips)")
    args = ap.parse_args(argv)

    plugin = DirectoryPlugin(args.name, args.data_dir)
    caps = PluginCapabilities(stage_unstage=not args.no_stage)
    server = CSIPluginServer(plugin, args.socket, capabilities=caps)
    server.start()
    print(f"CSI_PLUGIN_READY socket={args.socket} name={args.name}",
          flush=True)

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    server.stop()


if __name__ == "__main__":
    main()
