"""external-ca-example: a demo cfssl-compatible signing server.

Counterpart of the reference's swarmd/cmd/external-ca-example: an operator
CA service that holds the cluster root's SIGNING key outside the managers.
swarmd runs with `--external-ca url=http://…/sign` and a root cert whose
key lives only here; managers forward CSRs and publish the returned certs.

    # mint a root (or point at an existing one) and serve it
    python -m swarmkit_tpu.cmd.external_ca_example \
        --state-dir /tmp/extca --listen 127.0.0.1:8989

    # the manager then bootstraps against the SAME root:
    #   ca.pem is written into --state-dir for distribution

Protocol (what ca/external.py speaks): POST {"certificate_request": pem}
→ {"success": true, "result": {"certificate": pem}}.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="external-ca-example")
    ap.add_argument("--state-dir", required=True,
                    help="holds rootca.pem / rootca.key (created if absent)")
    ap.add_argument("--listen", default="127.0.0.1:0", help="host:port")
    ap.add_argument("--org", default="swarmkit-tpu")
    args = ap.parse_args(argv)

    from ..ca import RootCA

    os.makedirs(args.state_dir, exist_ok=True)
    cert_path = os.path.join(args.state_dir, "rootca.pem")
    key_path = os.path.join(args.state_dir, "rootca.key")
    if os.path.exists(cert_path) and os.path.exists(key_path):
        with open(cert_path, "rb") as f:
            cert_pem = f.read()
        with open(key_path, "rb") as f:
            key_pem = f.read()
        root = RootCA(cert_pem, key_pem)
    else:
        root = RootCA.create(args.org)
        with open(cert_path, "wb") as f:
            f.write(root.cert_pem)
        fd = os.open(key_path, os.O_WRONLY | os.O_CREAT, 0o600)
        with os.fdopen(fd, "wb") as f:
            f.write(root.key_pem or b"")

    class Signer(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):
            try:
                body = json.loads(
                    self.rfile.read(int(self.headers["Content-Length"])))
                csr = body["certificate_request"].encode()
                cert = root.sign_csr(csr)
                out = {"success": True,
                       "result": {"certificate": cert.decode()}}
                code = 200
            except Exception as exc:
                out = {"success": False, "errors": [str(exc)]}
                code = 400
            payload = json.dumps(out).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

    host, _, port = args.listen.rpartition(":")
    httpd = ThreadingHTTPServer((host or "127.0.0.1", int(port)), Signer)
    addr = "%s:%d" % httpd.server_address[:2]
    print(f"EXTERNAL_CA_READY url=http://{addr}/sign ca={cert_path}",
          flush=True)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
